"""Multi-mon quorum: leader election + replicated commit over peers.

The reference control plane is a mon quorum: rank-based leader election
(src/mon/Elector.h:37, ElectionLogic.cc — election epochs, one vote per
epoch, persisted), a single-slot proposal pipeline driven by the leader
(src/mon/Paxos.h:57-88 collect/begin/accept/commit), and a store every
mon replicates through the commit path (src/mon/MonitorDBStore.h).

``QuorumNode`` is that machinery, transport-abstract: ``send(rank,
msg) -> reply`` is injected (in-process dict calls in unit tests;
authenticated WireClients in the mon daemon), so the protocol is
testable without processes and deployable over the wire unchanged.

Safety properties (tested in tests/test_mon_quorum.py and the
threaded stress in test_mon_quorum_stress.py):
  * one vote per election epoch, persisted — two leaders cannot both
    win the same epoch;
  * an entry is acknowledged only after a majority stores it, so any
    later winner's vote majority intersects the storing majority and
    the collect phase recovers the entry (no acked commit lost);
  * a deposed leader's begin AND commit carry a stale election epoch
    and are refused (both are epoch-gated);
  * a recovered in-flight tail is RE-ACCEPTED by a majority under the
    new leader's epoch before it commits (Paxos phase 2 on recovery,
    src/mon/Paxos.h:57-88): a minority tail from an old epoch can
    never race a later election into a divergent commit, because the
    re-accept stamps the chosen value with the newest epoch on a
    majority, which every later collect majority intersects;
  * commits apply strictly in version order on every rank, regardless
    of which thread delivers them;
  * a restarted or lagging node catches up from the leader's log
    (fetch), applying entries in order.

Partition tolerance (ISSUE 6): the leader extends a READ LEASE on a
majority each round (Paxos::extend_lease / lease_expire roles); a rank
whose lease lapsed — a minority-side mon after a netsplit — answers
``readable() == False`` and the daemon stalls map reads instead of
serving a stale map as fresh, while the majority side elects, keeps
committing, and re-grants leases.  The healed minority catches up
through the normal fetch path, so every rank's committed log stays a
prefix of the quorum's (no split-brain double-commit).

Simplifications vs the reference, on purpose: one in-flight slot (no
pipelining, Paxos.h pipelines too but one-at-a-time is its documented
base case), and election preference by rank emerges from staggered
timeouts rather than a deferral subprotocol.
"""
from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..common.lockdep import LockdepLock
from ..common.log import dout

SendFn = Callable[[int, Dict[str, Any]], Dict[str, Any]]
ApplyFn = Callable[[int, bytes], None]


class NotLeader(RuntimeError):
    def __init__(self, leader: Optional[int]):
        super().__init__(f"not the leader (leader={leader})")
        self.leader = leader


class QuorumNode:
    """One mon rank's consensus state machine."""

    def __init__(self, rank: int, n_ranks: int, db, apply_fn: ApplyFn,
                 send_fn: SendFn, lease_duration: float = 2.0,
                 now_fn: Callable[[], float] = time.monotonic):
        self.rank = rank
        self.n_ranks = n_ranks
        self.db = db
        self.apply_fn = apply_fn
        self.send_fn = send_fn
        # read lease (Paxos::extend_lease / lease_expire): the leader
        # extends it on a MAJORITY each round; a rank whose lease
        # lapsed must treat its committed state as possibly stale —
        # map reads stall instead of serving a minority-side view.
        # ``now_fn`` is injectable so unit tests drive a fake clock.
        self.lease_duration = float(lease_duration)
        self._now = now_fn
        # lease state: 0.0 = never granted (bootstrap: nothing newer
        # exists to be stale against), -1.0 = EXPIRED.  Whether a
        # lease was ever granted is PERSISTED — a restarted rank that
        # held leases before must come back NOT readable, or crashing
        # a minority-side mon would silently defeat the stale-read
        # stall for the rest of the partition
        self._lease_ever = db.get("quorum", "leased") is not None
        self._lease_until = -1.0 if self._lease_ever else 0.0
        self._lock = LockdepLock("mon.quorum")
        # ordered-apply machinery: commits may be delivered on
        # concurrent wire-handler threads; the log itself grows in
        # order (version gate under _lock) and this queue + single
        # drainer guarantees apply_fn sees the same order, without
        # ever holding a quorum lock across apply_fn (the daemon's
        # apply path takes its own lock and its propose path re-enters
        # here — holding our lock across apply would deadlock)
        self._apply_q: List[Tuple[int, bytes]] = []
        self._applying = False
        # ONE in-flight slot is a safety property, not a convenience:
        # two concurrent propose() calls on the same leader would both
        # target committed+1 at the same epoch with different values,
        # and each could reach a majority on a different acceptor
        # subset — two values committed at one version.  This lock
        # serializes the whole store->begin->commit span (propose and
        # the collect re-accept share it).
        self._propose_lock = LockdepLock("mon.propose",
                                         recursive=False)
        self.leader: Optional[int] = None
        # persisted state
        self.election_epoch = int(db.get("quorum", "election_epoch")
                                  or b"0")
        self.committed = int(db.get("quorum", "committed") or b"0")
        self.applied = 0          # caller advances via replay/apply

    # -------------------------------------------------------- persistence --
    def _put(self, key: str, value: bytes) -> None:
        from .kv import WriteBatch
        self.db.submit(WriteBatch().set("quorum", key, value))

    def _log_key(self, version: int) -> str:
        return f"log:{version:010d}"

    def _get_entry(self, version: int) -> Optional[bytes]:
        return self.db.get("quorum", self._log_key(version))

    def _entry_epoch(self, version: int) -> int:
        b = self.db.get("quorum", f"logep:{version:010d}")
        return int(b or b"0")

    def _store_entry(self, version: int, value: bytes,
                     epoch: int) -> None:
        """Entry + the election epoch that accepted it: the collect
        phase must prefer the HIGHEST-epoch accepted value for a slot
        (classic Paxos — a stale minority tail at the same version
        must not beat a later majority-accepted one)."""
        from .kv import WriteBatch
        self.db.submit(WriteBatch()
                       .set("quorum", self._log_key(version), value)
                       .set("quorum", f"logep:{version:010d}",
                            str(epoch).encode()))

    def quorum(self) -> int:
        return self.n_ranks // 2 + 1

    # ------------------------------------------------------------- lease --
    def readable(self) -> bool:
        """May this rank serve committed state as CURRENT?  True until
        the first lease is granted (bootstrap: there is nothing newer
        to be stale against), then only while the lease holds — across
        restarts (the granted-once fact is persisted).  A minority-
        side rank's lease lapses within ``lease_duration`` of the cut
        and its reads stall until the quorum heals."""
        if self._lease_until == 0.0:
            return True
        return self._now() < self._lease_until

    def lease_remaining(self) -> float:
        return max(0.0, self._lease_until - self._now())

    def _grant_lease(self, until: float) -> None:
        if not self._lease_ever:
            self._lease_ever = True
            self._put("leased", b"1")
        self._lease_until = until

    def extend_lease(self) -> bool:
        """Leader-only: grant the read lease to a majority (the
        Paxos::extend_lease round).  The leader's OWN lease extends
        iff a majority acked — a deposed/minority leader fails here
        and stalls its reads too.  Returns success."""
        with self._lock:
            if self.leader != self.rank:
                return False
            e = self.election_epoch
        until = self._now() + self.lease_duration
        acks = 1
        for r in range(self.n_ranks):
            if r == self.rank:
                continue
            try:
                rep = self.send_fn(r, {"q": "lease", "epoch": e,
                                       "leader": self.rank,
                                       "duration":
                                           self.lease_duration,
                                       "committed": self.committed})
            except Exception:
                continue
            if rep.get("ok"):
                acks += 1
        if acks < self.quorum():
            return False
        self._grant_lease(until)
        return True

    def _on_lease(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        e = int(msg["epoch"])
        with self._lock:
            if e < self.election_epoch:
                # a stale (deposed, minority-side) leader's lease must
                # not let this rank serve reads on its behalf
                return {"ok": False, "epoch": self.election_epoch}
            if e > self.election_epoch:
                self.election_epoch = e
                self._put("election_epoch", str(e).encode())
            self.leader = int(msg["leader"])
            leader = self.leader
            behind = int(msg.get("committed", 0)) > self.committed
        if behind:
            # a lease that ADOPTS the leader also suppresses this
            # rank's election trigger — so it must carry the catch-up
            # duty victory messages have, or a revived laggard would
            # idle forever behind the quorum (outside the lock, like
            # _on_victory: the fetch takes peer round-trips)
            try:
                self._catch_up_from(leader, int(msg["committed"]))
            except Exception:
                # STILL behind: refuse the lease — accepting it would
                # stamp this rank's stale state as fresh, the exact
                # read the lease machinery exists to stall.  The
                # leader's next round retries the grant (and this
                # rank's fetch).
                return {"ok": False, "epoch": self.election_epoch,
                        "behind": True}
        self._grant_lease(self._now() + float(msg["duration"]))
        return {"ok": True}

    # ---------------------------------------------------------- election --
    def start_election(self) -> bool:
        """Run one election round as candidate.  Returns True when this
        rank won (and synchronized the quorum)."""
        with self._lock:
            e = self.election_epoch + 1
            self.election_epoch = e
            self._put("election_epoch", str(e).encode())
            self.leader = None
        votes = 1                      # self
        voters: List[Tuple[int, int, Optional[Tuple[int, bytes]]]] = [
            (self.rank, self.committed, self._tail())]
        for r in range(self.n_ranks):
            if r == self.rank:
                continue
            try:
                rep = self.send_fn(r, {"q": "vote", "epoch": e,
                                       "candidate": self.rank})
            except Exception:
                continue
            if rep.get("granted"):
                votes += 1
                tail = rep.get("tail")
                voters.append((r, int(rep["committed"]),
                               (int(tail[0]), bytes(tail[1]),
                                int(tail[2]))
                               if tail else None))
        if votes < self.quorum():
            dout("mon", 10, f"rank {self.rank} lost election epoch "
                            f"{e} ({votes} votes)")
            return False
        # leadership is NOT claimed yet: a client propose racing ahead
        # of the collect below would claim the very slot collect must
        # recover (overwriting a majority-accepted tail with a fresh
        # value at the new epoch — two values committed at one slot).
        # First adopt the longest committed log among the vote
        # majority; a failure (voter died) aborts the election.
        try:
            best_rank, best_committed = self.rank, self.committed
            for rank, committed, tail in voters:
                if committed > best_committed:
                    best_rank, best_committed = rank, committed
            if best_committed > self.committed:
                self._catch_up_from(best_rank, best_committed)
        except Exception:
            return False
        # victory BEFORE the collect re-accept: peers learn the leader
        # and catch up, so the re-accept round below lands on nodes
        # whose next slot is ours
        for r in range(self.n_ranks):
            if r == self.rank:
                continue
            try:
                self.send_fn(r, {"q": "victory", "epoch": e,
                                 "leader": self.rank,
                                 "committed": self.committed})
            except Exception:
                continue
        if not self._collect(voters, e):
            # the recovered tail could not be re-accepted by a
            # majority under epoch e: the election is NOT complete
            dout("mon", 5, f"rank {self.rank} won votes for epoch {e}"
                           f" but collect re-accept failed; yielding")
            return False
        with self._lock:
            if self.election_epoch != e:
                # a newer election superseded us mid-collect: its
                # winner (not us) owns the quorum now
                return False
            self.leader = self.rank      # open for proposals
        dout("mon", 5, f"rank {self.rank} won election epoch {e} "
                       f"({votes} votes)")
        return True

    def _tail(self) -> Optional[Tuple[int, bytes, int]]:
        """The accepted-but-uncommitted entry + its accept epoch, if
        any (at most one: single in-flight slot)."""
        v = self.committed + 1
        blob = self._get_entry(v)
        return (v, blob, self._entry_epoch(v)) \
            if blob is not None else None

    def _collect(self, voters, e: int) -> bool:
        """Paxos collect, phase 2 included: pick the accepted-but-
        uncommitted tail with the HIGHEST accept epoch among the vote
        majority (it may have been acknowledged to a client; a stale
        minority tail at the same version loses to a later-epoch one),
        then RE-ACCEPT it on a majority under our new epoch ``e``
        before committing.  Committing without the re-accept round is
        the classic Paxos mistake (src/mon/Paxos.h:57-88): a minority
        tail recovered here could race a later election that recovers
        a different, higher-epoch minority tail at the same version —
        two values committed at one slot.  The re-accept stamps the
        chosen value with epoch ``e`` on a majority, which every later
        collect majority intersects, making the choice final.

        Returns False when no majority re-accepts (caller must step
        down: the election is incomplete)."""
        best_tail: Optional[Tuple[int, bytes, int]] = None
        for rank, committed, tail in voters:
            if tail is None or tail[0] != self.committed + 1:
                continue              # stale/irrelevant slot
            if best_tail is None or tail[2] > best_tail[2]:
                best_tail = tail
        if best_tail is None:
            return True               # no in-flight slot to finish
        v, blob = best_tail[0], bytes(best_tail[1])
        with self._propose_lock:
            ok = self._reaccept_and_commit(v, blob, e)
        # apply AFTER releasing _propose_lock (see _commit_no_apply)
        self._drain_applies()
        return ok

    def _reaccept_and_commit(self, v: int, blob: bytes,
                             e: int) -> bool:
        with self._lock:
            # atomic re-check: a concurrent newer leader may have
            # committed this slot (or deposed us) between picking the
            # tail and storing — never overwrite a committed entry
            if v != self.committed + 1:
                return True           # slot already finished
            if self.election_epoch != e:
                return False          # deposed mid-collect
            self._store_entry(v, blob, e)      # self re-accept
        acks = 1
        for r in range(self.n_ranks):
            if r == self.rank:
                continue
            try:
                rep = self.send_fn(r, {"q": "begin", "epoch": e,
                                       "version": v, "value": blob,
                                       "leader": self.rank})
            except Exception:
                continue
            if rep.get("accepted"):
                acks += 1
        if acks < self.quorum():
            return False
        self._commit_no_apply(v, blob)    # caller holds _propose_lock
        self._replicate_commit(v, blob, e)
        return True

    def _catch_up_from(self, rank: int, target: int) -> None:
        """Fetch + commit the peer's log past ours.  Raises when the
        peer's response did not reach ``target``: callers that go on
        to act on "caught up" (the election path) must abort instead
        of proceeding on a short log."""
        rep = self.send_fn(rank, {"q": "fetch",
                                  "after": self.committed})
        for v, blob in rep["entries"]:
            if v != self.committed + 1:
                continue
            self._commit_entry(v, bytes(blob))
        if self.committed < target:
            raise IOError(f"catch-up from mon.{rank} stopped at "
                          f"{self.committed} < target {target}")

    # ------------------------------------------------------------ commit --
    def _commit_entry(self, version: int, value: bytes) -> None:
        """Persist + mark committed + apply, in that order (replay on
        restart re-applies anything past the service's state).

        The log grows strictly in order (version gate under _lock);
        applies are queued under the same lock and drained by a single
        thread so apply_fn observes that same order even when commits
        arrive on concurrent wire-handler threads.  apply_fn runs with
        NO quorum lock held (see __init__ note)."""
        self._commit_no_apply(version, value)
        self._drain_applies()

    def _commit_no_apply(self, version: int, value: bytes) -> None:
        """Log/commit-marker half of _commit_entry, for callers that
        hold _propose_lock: they must release it BEFORE draining
        applies (apply_fn may take the daemon's lock, and a daemon
        thread holding that lock may be waiting on _propose_lock —
        holding _propose_lock across apply_fn is an AB-BA deadlock)."""
        with self._lock:
            if version != self.committed + 1:
                return
            self._store_entry(version, value, self.election_epoch)
            self.committed = version
            self._put("committed", str(version).encode())
            self._apply_q.append((version, value))

    def _drain_applies(self) -> None:
        """Single-drainer, in-order apply of queued commits, holding
        no quorum lock across apply_fn.  A failed apply stays at the
        queue head so the next drain retries it first — later commits
        can never apply past a version gap in-process (replay() covers
        the restart case)."""
        with self._lock:
            if self._applying:
                return            # the active drainer will take it
            self._applying = True
        while True:
            with self._lock:
                if not self._apply_q:
                    self._applying = False
                    return
                v, blob = self._apply_q[0]
            try:
                self.apply_fn(v, blob)
            except Exception:
                with self._lock:
                    self._applying = False
                raise
            with self._lock:
                self._apply_q.pop(0)

    def _replicate_commit(self, version: int, value: bytes,
                          epoch: int) -> None:
        for r in range(self.n_ranks):
            if r == self.rank:
                continue
            try:
                self.send_fn(r, {"q": "commit", "epoch": epoch,
                                 "version": version, "value": value,
                                 "leader": self.rank})
            except Exception:
                continue          # laggard catches up later

    def propose(self, value: bytes) -> bool:
        """Leader path: begin/accept on a majority, then commit.  The
        caller may acknowledge its client iff this returns True.
        Serialized end-to-end by _propose_lock (one in-flight slot)."""
        with self._propose_lock:
            with self._lock:
                if self.leader != self.rank:
                    raise NotLeader(self.leader)
                e = self.election_epoch
                v = self.committed + 1
                self._store_entry(v, value, e)    # self-accept
            acks = 1
            for r in range(self.n_ranks):
                if r == self.rank:
                    continue
                try:
                    rep = self.send_fn(r, {"q": "begin", "epoch": e,
                                           "version": v,
                                           "value": value,
                                           "leader": self.rank})
                except Exception:
                    continue
                if rep.get("accepted"):
                    acks += 1
            if acks < self.quorum():
                # no majority (partition / deposed): the stored entry
                # stays uncommitted; a future leader's collect may
                # still finish it, which is safe — we report failure
                # and the caller must not ack its client
                return False
            self._commit_no_apply(v, value)
            self._replicate_commit(v, value, e)
        # apply AFTER releasing _propose_lock (see _commit_no_apply)
        self._drain_applies()
        return True

    # ---------------------------------------------------------- handlers --
    def handle(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Peer-message dispatch (the mon<->mon wire surface)."""
        q = msg["q"]
        if q == "vote":
            return self._on_vote(msg)
        if q == "victory":
            return self._on_victory(msg)
        if q == "begin":
            return self._on_begin(msg)
        if q == "commit":
            self._on_commit(msg)
            return {"ok": True}
        if q == "lease":
            return self._on_lease(msg)
        if q == "fetch":
            after = int(msg["after"])
            entries = []
            v = after + 1
            while v <= self.committed:
                blob = self._get_entry(v)
                if blob is None:
                    break
                entries.append((v, blob))
                v += 1
            return {"entries": entries, "committed": self.committed}
        if q == "ping":
            return {"leader": self.leader,
                    "epoch": self.election_epoch,
                    "committed": self.committed}
        raise ValueError(f"unknown quorum message {q!r}")

    def _on_vote(self, msg) -> Dict[str, Any]:
        e = int(msg["epoch"])
        with self._lock:
            if e <= self.election_epoch:
                return {"granted": False, "epoch": self.election_epoch}
            # one vote per epoch, persisted BEFORE granting
            self.election_epoch = e
            self._put("election_epoch", str(e).encode())
            self.leader = None
            return {"granted": True, "committed": self.committed,
                    "tail": self._tail()}

    def _on_victory(self, msg) -> Dict[str, Any]:
        e = int(msg["epoch"])
        with self._lock:
            if e < self.election_epoch:
                return {"ok": False}
            self.election_epoch = e
            self._put("election_epoch", str(e).encode())
            self.leader = int(msg["leader"])
            behind = int(msg["committed"]) > self.committed
            leader = self.leader
        if behind:
            try:
                self._catch_up_from(leader, int(msg["committed"]))
            except Exception:
                pass
        return {"ok": True}

    def _on_begin(self, msg) -> Dict[str, Any]:
        e, v = int(msg["epoch"]), int(msg["version"])
        with self._lock:
            if e < self.election_epoch:
                # deposed leader: stale epoch refused
                return {"accepted": False,
                        "epoch": self.election_epoch}
            if e > self.election_epoch:
                # a leader we missed the victory of: adopt it
                self.election_epoch = e
                self._put("election_epoch", str(e).encode())
            # a begin at epoch e can only come from e's single vote
            # winner (one persisted vote per epoch), so it is safe to
            # accept even before the victory message arrives — the
            # collect re-accept round depends on this
            if "leader" in msg:
                self.leader = int(msg["leader"])
            if v != self.committed + 1:
                return {"accepted": False,
                        "committed": self.committed}
            self._store_entry(v, bytes(msg["value"]), e)
            return {"accepted": True}

    def _on_commit(self, msg) -> None:
        e, v = int(msg.get("epoch", 0)), int(msg["version"])
        with self._lock:
            if e < self.election_epoch:
                # a deposed leader's commit is REFUSED: after a new
                # election this rank may have re-accepted a different
                # value at the same version; only current-epoch
                # commits (from the epoch's single winner) apply
                return
            if e > self.election_epoch:
                self.election_epoch = e
                self._put("election_epoch", str(e).encode())
            if "leader" in msg:
                self.leader = int(msg["leader"])
        if v == self.committed + 1:
            self._commit_entry(v, bytes(msg["value"]))
        elif v > self.committed:
            # gap: pull the backlog from the leader
            leader = int(msg.get("leader", -1))
            src = leader if leader >= 0 else \
                (self.leader if self.leader is not None else -1)
            if src >= 0 and src != self.rank:
                try:
                    self._catch_up_from(src, v)
                except Exception:
                    pass

    # ------------------------------------------------------------ replay --
    def replay(self, applied_hint: int = 0) -> int:
        """On restart: re-apply committed entries beyond what the
        service already holds (the MonitorDBStore recovery walk)."""
        applied = applied_hint
        v = applied + 1
        while v <= self.committed:
            blob = self._get_entry(v)
            if blob is None:
                break
            self.apply_fn(v, blob)
            applied = v
            v += 1
        return applied


# ----------------------------------------------------------- encoding ---

def encode_decree(kind: str, **fields) -> bytes:
    """Typed JSON decree (no pickle on the quorum wire)."""
    return json.dumps({"kind": kind, **fields}).encode()


def decode_decree(blob: bytes) -> Dict[str, Any]:
    return json.loads(bytes(blob).decode())
