"""Monitor — the control plane: consensus log, map service, config db,
health checks.

Compact TPU-native re-creation of the mon's roles (src/mon/):

  * ``QuorumModel`` — the consensus substrate (src/mon/Paxos.{h,cc}): a
    proposal/accept/commit state machine over N in-process ranks with
    majority acceptance and monotone proposal numbers.  One class,
    testable, with the properties that matter: committed versions are
    sequential, a minority cannot commit, a new leader's higher
    proposal number supersedes a stalled one.
  * ``Monitor`` — PaxosService analog hosting:
      - the OSDMap service: full map + Incremental history; consumers
        catch up via get_incrementals(since) (OSDMonitor role —
        src/mon/OSDMonitor.cc map publication);
      - the config db (src/mon/ConfigMonitor.cc): committed key=value
        options pushed into the process options registry at FILE level;
      - health checks (src/mon/HealthMonitor.cc + the osdmap checks):
        OSD_DOWN / OSD_OUT / PG_DEGRADED computed from the current map
        and (optionally) a ClusterSim's shard state;
      - failure reports: OSD peers report a down OSD; past the quorum
        threshold the mon commits a map epoch marking it down
        (OSDMonitor::prepare_failure semantics).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import faults
from ..common.options import LEVEL_FILE, OptionError, config
from ..placement.crush_map import ITEM_NONE
from .osdmap import Incremental, OSDMap

faults.declare("mon.map_churn",
               "piggyback an extra empty epoch bump on a committed "
               "incremental — map churn without state change, forcing "
               "every subscriber through its catch-up/resend path "
               "(the thrash-map-epochs axis)")


# ------------------------------------------------------------- consensus ---

class QuorumModel:
    """In-process MODEL of single-decree quorum acceptance (NOT the
    deployable consensus — that is cluster/mon_quorum.QuorumNode, a
    real elected multi-mon log over the wire; this class backs
    standalone single-mon setups and the consensus unit tests).

    The reference pipelines one decree at a time through
    collect/begin/accept/commit (Paxos.h:57-88 'The Leader election ...
    proposal pipeline').  Here: `propose(value)` runs one round as the
    current leader; commit succeeds iff a majority of live ranks
    accept.  Ranks can be marked unreachable to model partitions.
    """

    def __init__(self, n_ranks: int = 3):
        if n_ranks < 1:
            raise ValueError("need at least one rank")
        self.n_ranks = n_ranks
        self.reachable = [True] * n_ranks
        self.leader = 0
        # per-rank acceptor state: (promised_pn, accepted_pn)
        self.promised = [0] * n_ranks
        self.accepted_pn = [0] * n_ranks
        self.committed: List[Any] = []        # version v = index + 1
        self._pn = 0

    @property
    def version(self) -> int:
        return len(self.committed)

    def quorum(self) -> int:
        return self.n_ranks // 2 + 1

    def elect(self, leader: int) -> int:
        """New leader takes over with a higher proposal number
        (collect phase)."""
        self.leader = leader
        self._pn = (max(self.promised) // 100 + 1) * 100 + leader
        n_promised = 0
        for r in range(self.n_ranks):
            if self.reachable[r] and self.promised[r] < self._pn:
                self.promised[r] = self._pn
                n_promised += 1
        return n_promised

    def propose(self, value: Any) -> bool:
        """begin/accept/commit one value; False when no quorum."""
        if self._pn == 0 or self.promised[self.leader] > self._pn:
            self.elect(self.leader)
        accepts = 0
        for r in range(self.n_ranks):
            if not self.reachable[r]:
                continue
            if self.promised[r] <= self._pn:
                self.accepted_pn[r] = self._pn
                accepts += 1
        if accepts < self.quorum():
            return False
        self.committed.append(value)
        return True


# --------------------------------------------------------------- monitor ---

@dataclass
class HealthCheck:
    code: str
    severity: str          # "HEALTH_WARN" | "HEALTH_ERR"
    summary: str


class Monitor:
    """Single logical mon cluster (QuorumModel-backed) owning the OSDMap.
    Committed state persists into a KeyValueDB (the MonitorDBStore
    role, src/mon/MonitorDBStore.h over src/kv/): prefixes `osdmap`
    (per-epoch incrementals), `config` (central options), `paxos`
    (commit markers)."""

    def __init__(self, osdmap: OSDMap, n_ranks: int = 3,
                 failure_reports_needed: int = 2, db=None,
                 proposer: Optional[Callable[[Tuple], bool]] = None):
        from .kv import MemDB
        self.osdmap = osdmap
        self.paxos = QuorumModel(n_ranks)
        self.incrementals: List[Incremental] = []
        self.config_db: Dict[str, Any] = {}
        self.failure_reports_needed = failure_reports_needed
        self._failure_reports: Dict[int, set] = {}
        self.db = db if db is not None else MemDB()
        # consensus seam: None = the in-process QuorumModel decides AND
        # this object applies inline; a wire-quorum daemon installs its
        # QuorumNode.propose here, and application happens through the
        # quorum's apply path (apply_committed_*) on every rank —
        # including this one — so proposal success implies local state
        # is already updated
        self._proposer = proposer
        # slow-op rollup from daemonized OSDs (each OSD process owns
        # its OWN OpTracker; its heartbeat reports slow_ops_summary()
        # so SLOW_OPS covers the whole cluster, not just this
        # process's tracker): daemon entity -> last nonzero summary
        self._daemon_slow: Dict[str, Dict[str, Any]] = {}
        # boot-time fsck damage rollup (the CrashDev pipeline): an OSD
        # that browned out reports objects its fsck quarantined; the
        # STORE_DAMAGED health check surfaces them until the daemon
        # reports clean (or the reporter ages out like slow ops)
        self._store_damage: Dict[str, Dict[str, Any]] = {}
        # ClusterTelemetry stats aggregation (the PGMap + mgr
        # prometheus role): daemons ship perf counters / histograms /
        # utilization over the heartbeat path; the aggregator merges
        # them into cluster p50/p99/p999, io rates, df / osd df
        from ..mgr.cluster_stats import ClusterStats
        self.cluster_stats = ClusterStats()
        # ------ flap dampening (the osd_markdown_log role) ------
        # an OSD marked down >= _flap_count times inside _flap_window
        # gets its next boot HELD for a doubling backoff (capped), so
        # a flapping link cannot churn the map/peering every tick.
        # Disabled by default (_flap_count = 0): the process tier opts
        # in via the cluster spec, sims via configure_flap_dampening.
        # Time source: wall clock unless a tick clock is installed
        # (HeartbeatMonitor installs its tick counter — seeded soaks
        # must not depend on wall time).
        self._flap_count = 0
        self._flap_window = 60.0
        self._flap_hold = 5.0
        self._flap_hold_cap = 30.0
        self.flap_clock: Optional[Callable[[], float]] = None
        self._markdown_log: Dict[int, List[float]] = {}
        self._boot_hold_until: Dict[int, float] = {}
        self.boots_held = 0           # hysteresis-refused boots

    def set_proposer(self,
                     fn: Optional[Callable[[Tuple], bool]]) -> None:
        self._proposer = fn

    @staticmethod
    def _inc_json(inc: Incremental) -> bytes:
        """Complete serialization — a lossy record would replay into a
        wrong acting set."""
        import json
        return json.dumps({
            "epoch": inc.epoch,
            "new_up": {str(k): v for k, v in inc.new_up.items()},
            "new_weight": {str(k): int(v)
                           for k, v in inc.new_weight.items()},
            "new_primary_affinity": {
                str(k): int(v)
                for k, v in inc.new_primary_affinity.items()},
            "new_pg_upmap_items": {
                f"{p}.{g}": items
                for (p, g), items in inc.new_pg_upmap_items.items()},
            "new_pg_temp": {
                f"{p}.{g}": temp
                for (p, g), temp in inc.new_pg_temp.items()},
            "new_pool_pg_num": {str(k): int(v)
                                for k, v in inc.new_pool_pg_num.items()},
            "new_pools": {str(k): v for k, v in inc.new_pools.items()},
            "old_pools": list(inc.old_pools),
            "new_pool_tier": {str(k): v for k, v in
                              inc.new_pool_tier.items()},
            "new_flags": dict(inc.new_flags),
        }).encode()

    @staticmethod
    def _inc_from_json(blob: bytes) -> Incremental:
        import json
        d = json.loads(blob.decode())
        return Incremental(
            epoch=d["epoch"],
            new_up={int(k): v for k, v in d["new_up"].items()},
            new_weight={int(k): int(v)
                        for k, v in d["new_weight"].items()},
            new_primary_affinity={
                int(k): int(v)
                for k, v in d["new_primary_affinity"].items()},
            new_pg_upmap_items={
                (int(s.split(".")[0]), int(s.split(".")[1])): items
                for s, items in d["new_pg_upmap_items"].items()},
            new_pg_temp={
                (int(s.split(".")[0]), int(s.split(".")[1])): temp
                for s, temp in d["new_pg_temp"].items()},
            new_pool_pg_num={int(k): int(v)
                             for k, v in d.get("new_pool_pg_num",
                                               {}).items()},
            new_pools={int(k): v
                       for k, v in d.get("new_pools", {}).items()},
            old_pools=[int(p) for p in d.get("old_pools", [])],
            new_pool_tier={int(k): v for k, v in
                           d.get("new_pool_tier", {}).items()},
            new_flags={str(k): bool(v) for k, v in
                       d.get("new_flags", {}).items()},
        )

    @classmethod
    def open(cls, base_osdmap: OSDMap, db, n_ranks: int = 3,
             failure_reports_needed: int = 2) -> "Monitor":
        """Mount a monitor from its durable store: replay every
        committed osdmap incremental beyond the base map's epoch and
        reload the config db (MonitorDBStore recovery,
        src/mon/MonitorDBStore.h + Monitor::preinit's map load)."""
        import json
        mon = cls(base_osdmap, n_ranks=n_ranks,
                  failure_reports_needed=failure_reports_needed, db=db)
        for _, blob in db.iterate("osdmap"):
            inc = cls._inc_from_json(blob)
            if inc.epoch <= base_osdmap.epoch:
                continue                    # already in the base map
            if inc.epoch != base_osdmap.epoch + 1:
                raise ValueError(
                    f"mon store gap: incremental epoch {inc.epoch} "
                    f"against map epoch {base_osdmap.epoch} — wrong "
                    "base map for this store")
            base_osdmap.apply_incremental(inc)
            mon.incrementals.append(inc)
        for key, blob in db.iterate("config"):
            value = json.loads(blob.decode())
            mon.config_db[key] = value
            try:
                config().set(key, value, level=LEVEL_FILE)
            except OptionError:
                pass
        # consensus log resumes after the highest committed version
        # (decree payloads are not re-read; markers hold the positions)
        versions = db.keys("paxos")
        if versions:
            mon.paxos.committed = [("recovered",)] * int(versions[-1])
        return mon

    # ------------------------------------------------------- map service --
    def commit_incremental(self, inc: Incremental) -> bool:
        """Propose a map mutation through consensus, then apply.
        Epoch is validated BEFORE proposing so the consensus log can
        never hold a decree the map refused (direct bump_epoch callers
        can race the mon)."""
        if inc.epoch != self.osdmap.epoch + 1:
            raise ValueError(
                f"incremental epoch {inc.epoch} != "
                f"{self.osdmap.epoch} + 1")
        if self._proposer is not None:
            # wire quorum: commit applies on every rank (incl. here)
            # through apply_committed_incremental before this returns
            ok = self._proposer(("osdmap", inc))
        else:
            if not self.paxos.propose(("osdmap", inc)):
                return False
            self.apply_committed_incremental(inc, paxos_marker=True)
            ok = True
        if ok and not getattr(self, "_churning", False) and \
                faults.fire("mon.map_churn") is not None:
            # one extra EMPTY epoch: subscribers must catch up again.
            # Reentrancy-guarded — the churn commit re-enters here and
            # an `always` schedule would otherwise recurse forever.
            self._churning = True
            try:
                self.commit_incremental(self.next_incremental())
            finally:
                self._churning = False
        return ok

    def apply_committed_incremental(self, inc: Incremental,
                                    paxos_marker: bool = False) -> None:
        """Apply + persist an incremental the quorum already decided
        (the commit path every mon rank runs)."""
        if inc.epoch != self.osdmap.epoch + 1:
            raise ValueError(
                f"committed incremental epoch {inc.epoch} does not "
                f"follow map epoch {self.osdmap.epoch}")
        self.osdmap.apply_incremental(inc)
        self.incrementals.append(inc)
        from .kv import WriteBatch
        b = WriteBatch().set("osdmap", f"{inc.epoch:010d}",
                             self._inc_json(inc))
        if paxos_marker:
            b.set("paxos", f"{self.paxos.version:010d}", b"osdmap")
        self.db.submit(b)

    def next_incremental(self) -> Incremental:
        return Incremental(epoch=self.osdmap.epoch + 1)

    def get_incrementals(self, since_epoch: int) -> List[Incremental]:
        """Deltas a consumer at `since_epoch` needs (map subscription)."""
        return [i for i in self.incrementals if i.epoch > since_epoch]

    # --------------------------------------------------------- config db --
    def config_set(self, key: str, value: Any) -> bool:
        """Central config commit (ConfigMonitor): consensus first, then
        push into the process registry at FILE level."""
        if self._proposer is not None:
            return self._proposer(("config", key, value))
        if not self.paxos.propose(("config", key, value)):
            return False
        self.apply_committed_config(key, value, paxos_marker=True)
        return True

    def apply_committed_config(self, key: str, value: Any,
                               paxos_marker: bool = False) -> None:
        self.config_db[key] = value
        import json
        from .kv import WriteBatch
        b = WriteBatch().set("config", key,
                             json.dumps(value).encode())
        if paxos_marker:
            b.set("paxos", f"{self.paxos.version:010d}", b"config")
        self.db.submit(b)
        try:
            config().set(key, value, level=LEVEL_FILE)
        except OptionError:
            pass          # unknown keys stay mon-side only

    def config_get(self, key: str) -> Any:
        return self.config_db.get(key)

    # ------------------------------------------------------------- flags --
    def set_flag(self, flag: str, on: bool = True) -> bool:
        """Set/clear a cluster-wide osdmap flag (noout/nodown) through
        a committed incremental — `ceph osd set noout` (OSDMonitor
        prepare_command CEPH_OSDMAP_* role)."""
        from .osdmap import CLUSTER_FLAGS
        if flag not in CLUSTER_FLAGS:
            raise ValueError(f"unknown osdmap flag {flag!r} "
                             f"(known: {CLUSTER_FLAGS})")
        if (flag in self.osdmap.flags) == on:
            return True              # idempotent: already there
        inc = self.next_incremental()
        inc.new_flags[flag] = on
        return self.commit_incremental(inc)

    # ----------------------------------------------------- flap damping --
    def configure_flap_dampening(self, count: int, window: float,
                                 hold: float,
                                 hold_cap: float) -> None:
        """Arm markdown hysteresis: ``count`` markdowns inside
        ``window`` hold the next boot for ``hold`` (doubling per extra
        markdown, capped at ``hold_cap``).  count=0 disables."""
        self._flap_count = int(count)
        self._flap_window = float(window)
        self._flap_hold = float(hold)
        self._flap_hold_cap = float(hold_cap)

    def _flap_now(self) -> float:
        import time as _time
        return self.flap_clock() if self.flap_clock is not None \
            else _time.monotonic()

    def _record_markdown(self, osd: int) -> None:
        if not self._flap_count:
            return
        now = self._flap_now()
        log = [t for t in self._markdown_log.get(osd, [])
               if now - t <= self._flap_window]
        log.append(now)
        self._markdown_log[osd] = log
        extra = len(log) - self._flap_count
        if extra >= 0:
            hold = min(self._flap_hold_cap,
                       self._flap_hold * (2.0 ** extra))
            self._boot_hold_until[osd] = now + hold

    def flap_status(self, osd: int) -> Dict[str, Any]:
        now = self._flap_now()
        return {
            "markdowns_in_window": len(
                [t for t in self._markdown_log.get(osd, [])
                 if now - t <= self._flap_window]),
            "held_for": max(0.0, self._boot_hold_until.get(osd, 0.0)
                            - now),
        }

    # ---------------------------------------------------- failure reports --
    def report_failure(self, target: int, reporter: int) -> bool:
        """OSD peers report a dead peer; at the threshold the mon
        commits an epoch marking it down (OSDMonitor::prepare_failure).
        Returns True when the target was marked down.  The ``nodown``
        cluster flag vetoes the markdown (reports still accumulate, so
        clearing the flag acts on the evidence immediately) — the
        operator's ride-out-a-known-partition knob."""
        if not self.osdmap.is_up(target):
            return False
        reps = self._failure_reports.setdefault(target, set())
        reps.add(reporter)
        if len(reps) < self.failure_reports_needed:
            return False
        if "nodown" in self.osdmap.flags:
            return False
        inc = self.next_incremental()
        inc.new_up[target] = False
        if self.commit_incremental(inc):
            del self._failure_reports[target]
            self._record_markdown(target)
            return True
        return False

    def auto_out_down(self, osd: int) -> bool:
        """Down->out transition after the grace (the
        mon_osd_down_out_interval role, driven by the heartbeat
        monitor's tick): vetoed by the ``noout`` flag."""
        if "noout" in self.osdmap.flags:
            return False
        if self.osdmap.is_up(osd) or self.osdmap.osd_weight[osd] == 0:
            return False
        inc = self.next_incremental()
        inc.new_weight[osd] = 0
        return self.commit_incremental(inc)

    def osd_boot(self, osd: int, weight: int = 0x10000) -> bool:
        """An OSD announces itself up (the MOSDBoot path,
        OSDMonitor::prepare_boot): commits a map epoch marking it up
        and restoring its in-weight, so subscribed clients catch up.
        A flapping OSD (markdown hysteresis engaged) is HELD down for
        its backoff: the boot returns False and the announcer retries
        — the reference's osd_markdown_log suicide/backoff shape."""
        hold = self._boot_hold_until.get(osd)
        if hold is not None:
            if self._flap_now() < hold:
                self.boots_held += 1
                return False
            del self._boot_hold_until[osd]
        inc = self.next_incremental()
        inc.new_up[osd] = True
        inc.new_weight[osd] = weight
        if not self.commit_incremental(inc):
            return False
        # a boot cancels pending failure reports (prepare_boot):
        # otherwise stale pre-boot reporters count toward marking the
        # fresh OSD down again
        self._failure_reports.pop(osd, None)
        return True

    # ------------------------------------------------------------ health --
    def health(self, sim=None,
               include_pg_state: bool = True) -> List[HealthCheck]:
        """HealthMonitor analog over the current map (+ optional sim
        shard state for degraded-PG detection).

        ``include_pg_state=False`` skips the PG_DEGRADED sweep: it
        runs the batched device mapper over every pool, which is the
        right cost in-process but compiles the mapper inside a mon
        DAEMON whose only other duties are map/auth bookkeeping — the
        wire `health` command defaults it off and lets callers opt
        in (``{"cmd": "health", "pgs": True}``)."""
        checks: List[HealthCheck] = []
        om = self.osdmap
        exists = om.osd_exists
        down = int((exists & ~om.osd_up).sum())
        if down:
            checks.append(HealthCheck(
                "OSD_DOWN", "HEALTH_WARN", f"{down} osds down"))
        out = int((exists & (om.osd_weight == 0)).sum())
        if out:
            checks.append(HealthCheck(
                "OSD_OUT", "HEALTH_WARN", f"{out} osds out"))
        degraded = 0
        ups = {}
        for pid in (om.pools if include_pg_state else ()):
            up, _ = om.map_pgs_batch(pid)
            ups[pid] = up
            holes = (up == ITEM_NONE).any(axis=1)
            degraded += int(holes.sum())
        stale = 0
        if sim is not None:
            # real shard-state input: PGs whose log is ahead of some up
            # member's last applied version — reusing the batched up
            # arrays computed above (one scalar do_rule per PG would be
            # exactly the cost the batched mapper exists to remove);
            # the sparse pg_temp overlay still takes the scalar path
            from .pglog import ZERO
            for (pid, pg), log in sim.pg_logs.items():
                pool = om.pools.get(pid)
                if pool is None or log.head == ZERO:
                    continue
                if (pid, pg) in om.pg_temp:
                    members = sim.pg_up(pool, pg)
                elif pid in ups and pg < len(ups[pid]):
                    members = [int(o) for o in ups[pid][pg]]
                else:
                    continue
                for o in members:
                    if o == ITEM_NONE:
                        continue
                    lc = sim.osds[o].last_complete.get((pid, pg), ZERO)
                    if lc < log.head:
                        stale += 1
                        break
        if degraded or stale:
            checks.append(HealthCheck(
                "PG_DEGRADED", "HEALTH_WARN",
                f"{degraded} pgs with unfilled slots, "
                f"{stale} pgs with stale replicas"))
        # SLOW_OPS (the HealthMonitor "N slow ops" rollup): ops
        # currently blocked past op_tracker_complaint_time plus
        # recently completed slow ops, from this process's tracker
        # (which sees everything in the in-process sim) MERGED with
        # the summaries daemonized OSDs report over the wire
        # (report_slow_ops on their heartbeat) — their trackers live
        # in other processes
        import time as _time
        from ..common.op_tracker import tracker as _op_tracker
        slow = _op_tracker().slow_ops_summary()
        num = int(slow["num"])
        oldest = float(slow["oldest_s"])
        daemons = list(slow["daemons"])
        now = _time.time()
        for entity, rep in sorted(self._daemon_slow.items()):
            if now - float(rep.get("ts", now)) > 600.0:
                continue              # reporter gone silent: stale
            num += int(rep.get("num", 0))
            oldest = max(oldest, float(rep.get("oldest_s", 0.0)))
            for d in rep.get("daemons") or [entity]:
                if d not in daemons:
                    daemons.append(d)
        if num:
            names = ",".join(sorted(daemons)) or "unknown"
            checks.append(HealthCheck(
                "SLOW_OPS", "HEALTH_WARN",
                f"{num} slow ops, oldest one blocked for "
                f"{oldest:.3f} sec, daemons [{names}] "
                f"have slow ops"))
        # STORE_DAMAGED (the CrashDev boot-fsck rollup): a power-cut
        # OSD quarantined torn objects at boot — recovery must
        # re-replicate them, and the operator must know it happened
        dmg_n = 0
        dmg_daemons = []
        for entity, rep in sorted(self._store_damage.items()):
            if now - float(rep.get("ts", now)) > 600.0:
                continue              # reporter gone silent: stale
            if int(rep.get("errors", 0)) > 0:
                dmg_n += int(rep["errors"])
                dmg_daemons.append(entity)
        if dmg_n:
            checks.append(HealthCheck(
                "STORE_DAMAGED", "HEALTH_WARN",
                f"{dmg_n} objects quarantined by boot-time fsck on "
                f"[{','.join(dmg_daemons)}] (power-loss damage; "
                f"recovery re-replicates)"))
        return checks

    def record_daemon_slow_ops(self, daemon: str,
                               summary: Dict[str, Any]) -> None:
        """Ingest one daemon's ``slow_ops_summary()`` (reported over
        the wire on its heartbeat).  A zero report clears the entry —
        an OSD whose slow window drained stops contributing; a daemon
        that stops reporting entirely ages out of health() after 600s."""
        import time as _time
        if summary and int(summary.get("num", 0)) > 0:
            self._daemon_slow[daemon] = dict(summary,
                                             ts=_time.time())
        else:
            self._daemon_slow.pop(daemon, None)

    def record_daemon_perf(self, daemon: str,
                           report: Dict[str, Any]) -> None:
        """Ingest one daemon's telemetry report (perf counter dump +
        store utilization, shipped on its heartbeat like the slow-op
        summaries) into the cluster stats aggregator."""
        self.cluster_stats.ingest(daemon, report)

    def record_store_damage(self, daemon: str, errors: int,
                            repaired: int = 0) -> None:
        """Ingest one daemon's boot-fsck report (the heartbeat
        carries it).  A zero-error report clears the entry — the
        daemon's store fsck'd clean again."""
        import time as _time
        if int(errors) > 0:
            self._store_damage[daemon] = {
                "errors": int(errors), "repaired": int(repaired),
                "ts": _time.time()}
        else:
            self._store_damage.pop(daemon, None)

    def health_status(self, sim=None) -> str:
        checks = self.health(sim)
        if any(c.severity == "HEALTH_ERR" for c in checks):
            return "HEALTH_ERR"
        return "HEALTH_WARN" if checks else "HEALTH_OK"
