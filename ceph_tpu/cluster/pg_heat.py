"""PGHeatTracker — per-PG client-io heat with exponential decay.

Role of the reference's pool HitSet machinery (src/osd/HitSet.h, the
pg_pool_t hit_set_* knobs: per-PG access populations the tiering agent
and read balancer consume), collapsed to the piece the ClusterScope
observability loop needs: each executing OSD counts client rd/wr
ops+bytes PER PG, decayed exponentially so the numbers mean "recent
load", and ships the table on its existing heartbeat report.  The mon
merges the per-OSD tables into `ceph pg heat` and the balancer
advisor's per-OSD load model.

Two ledgers per (pool, pg):

  * DECAYED heat — halved every ``half_life`` clock units (lazy decay
    at touch/snapshot time, no background thread), the "what is hot
    NOW" signal;
  * RAW monotonic totals — never decayed, so the per-OSD rollup can
    be asserted equal to the ``osd.io`` counters counted at the very
    same call sites (the agrees-with-osd.io acceptance check), and so
    the sim tier can synthesize per-OSD ``osd.io`` counters for the
    history/rate pipeline from one source of truth.

Clock: injectable.  The daemon tier passes wall time; the sim tier
drives the tracker off the heartbeat TICK clock (``advance()``), so
heat decay is seed-deterministic — two runs with the same seed and
tick schedule produce bit-identical heat tables (the property test's
contract).  With no clock and no advance() calls time stands still
and decay is a no-op.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..common.lockdep import LockdepLock

PGId = Tuple[int, int]

_TRACKER_IDS = itertools.count(1)

_FIELDS = ("rd_ops", "wr_ops", "rd_bytes", "wr_bytes")


class PGHeatTracker:
    """Per-(pool, pg) decayed heat + raw totals, thread-safe (OSD
    dispatcher threads record while heartbeat threads snapshot)."""

    def __init__(self, half_life: float = 60.0,
                 clock: Optional[Callable[[], float]] = None):
        self.half_life = float(half_life)
        self._clock = clock
        self._now = 0.0              # manual clock (advance())
        # leaf lock (no other lock is taken while held); per-instance
        # name — non-recursive locks need one (see LockdepLock)
        self._lock = LockdepLock(
            f"pg_heat.{next(_TRACKER_IDS)}", recursive=False)
        # pg -> [decayed x4, raw x4, last_touch]
        self._pgs: Dict[PGId, List[float]] = {}

    # ------------------------------------------------------------- clock --
    def now(self) -> float:
        return self._clock() if self._clock is not None else self._now

    def advance(self, t: float) -> None:
        """Drive the manual clock (sim heartbeat ticks); never moves
        backwards."""
        with self._lock:
            if t > self._now:
                self._now = t

    def _decay_locked(self, row: List[float], now: float) -> None:
        dt = now - row[8]
        if dt <= 0:
            return
        f = 0.5 ** (dt / self.half_life)
        for i in range(4):
            row[i] *= f
        row[8] = now

    # ------------------------------------------------------------ record --
    def record(self, pool: int, pg: int, rw: str, ops: int = 1,
               nbytes: int = 0) -> None:
        """Count one client op against its PG; ``rw`` is "rd"/"wr"."""
        now = self.now()
        oi, bi = (0, 2) if rw == "rd" else (1, 3)
        with self._lock:
            row = self._pgs.get((pool, pg))
            if row is None:
                row = self._pgs[(pool, pg)] = [0.0] * 8 + [now]
            else:
                self._decay_locked(row, now)
            row[oi] += ops
            row[bi] += nbytes
            row[4 + oi] += ops
            row[4 + bi] += nbytes

    # -------------------------------------------------------------- dump --
    def dump(self) -> Dict[str, Any]:
        """Wire/heartbeat payload: {"t": clock, "pgs": {"pool.pg":
        {decayed fields..., "tot_*" raw fields...}}}.  String pg ids —
        the dict crosses typed wire encoding."""
        now = self.now()
        with self._lock:
            pgs = {}
            for (pool, pg), row in self._pgs.items():
                self._decay_locked(row, now)
                ent = {f: round(row[i], 6)
                       for i, f in enumerate(_FIELDS)}
                ent.update({f"tot_{f}": row[4 + i]
                            for i, f in enumerate(_FIELDS)})
                pgs[f"{pool}.{pg}"] = ent
            return {"t": now, "half_life": self.half_life, "pgs": pgs}

    def totals(self) -> Dict[str, float]:
        """Raw (undecayed) rollup across every PG — by construction
        equal to what the ``osd.io`` counters counted at the same
        sites."""
        with self._lock:
            out = {f: 0.0 for f in _FIELDS}
            for row in self._pgs.values():
                for i, f in enumerate(_FIELDS):
                    out[f] += row[4 + i]
            return out

    def reset(self) -> None:
        """A daemon restart loses this table (in-memory state)."""
        with self._lock:
            self._pgs.clear()


def merge_heat(dumps: Dict[str, Dict[str, Any]],
               pool: Optional[int] = None,
               top: Optional[int] = None) -> List[Dict[str, Any]]:
    """Mon-side merge of per-OSD heat dumps into `ceph pg heat` rows.

    ``dumps`` maps reporter ("osd.N") -> PGHeatTracker.dump().  Rows
    sum the decayed fields per PG across every reporting OSD (each
    OSD counts the client ops IT served, so the sum is the PG's
    cluster-wide client load), sorted hottest first.  ``heat`` is the
    ops-oriented scalar the advisor ranks on: decayed rd+wr ops plus
    a byte term scaled so 4 MiB ~ one op.
    """
    merged: Dict[str, Dict[str, Any]] = {}
    for reporter, d in sorted(dumps.items()):
        for pgid, ent in (d.get("pgs") or {}).items():
            try:
                pid = int(pgid.split(".", 1)[0])
            except (ValueError, AttributeError):
                continue
            if pool is not None and pid != pool:
                continue
            row = merged.setdefault(pgid, {
                "pgid": pgid, "pool": pid, "osds": [],
                **{f: 0.0 for f in _FIELDS},
                **{f"tot_{f}": 0.0 for f in _FIELDS}})
            for f in _FIELDS:
                row[f] += float(ent.get(f, 0.0))
                row[f"tot_{f}"] += float(ent.get(f"tot_{f}", 0.0))
            row["osds"].append(reporter)
    rows = []
    for row in merged.values():
        row["heat"] = round(
            row["rd_ops"] + row["wr_ops"] +
            (row["rd_bytes"] + row["wr_bytes"]) / (4 << 20), 6)
        for f in _FIELDS:
            row[f] = round(row[f], 6)
        rows.append(row)
    rows.sort(key=lambda r: (-r["heat"], r["pgid"]))
    return rows[:top] if top else rows


def osd_heat_rollup(dumps: Dict[str, Dict[str, Any]]
                    ) -> Dict[str, Dict[str, float]]:
    """Per-OSD rollup (raw totals + decayed heat) from the same
    dumps — the series the agrees-with-osd.io assertion compares."""
    out: Dict[str, Dict[str, float]] = {}
    for reporter, d in sorted(dumps.items()):
        tot = {f: 0.0 for f in _FIELDS}
        hot = {f: 0.0 for f in _FIELDS}
        for ent in (d.get("pgs") or {}).values():
            for f in _FIELDS:
                tot[f] += float(ent.get(f"tot_{f}", 0.0))
                hot[f] += float(ent.get(f, 0.0))
        out[reporter] = {
            **{f"tot_{f}": round(v, 6) for f, v in tot.items()},
            "heat": round(hot["rd_ops"] + hot["wr_ops"] +
                          (hot["rd_bytes"] + hot["wr_bytes"])
                          / (4 << 20), 6)}
    return out
