"""DR drill — two-zone disaster recovery as a gated scenario.

``ceph serve --dr`` (and this module's ``drill_main``): run a seeded
S3 workload against zone A while zone B syncs, sever the zones with
the existing ``net.partition`` faultpoint (entities ``zone.a`` /
``zone.b`` — the same axis the daemons' netsplits arm), FAIL WRITES
OVER to zone B, heal, and gate HARD on convergence:

  * every acked ETag readable in BOTH zones (the acked-oracle rule
    the serving harness uses, applied cross-zone);
  * zero replay double-applies and zero full-sync restarts
    (structural counters on the sync agents);
  * bounded replication lag, read as p99 off the MERGED per-agent
    lag histograms (mgr.cluster_stats.merge_histograms/quantile —
    the cluster histogram-merge path);
  * the sever provably bit (partition fire counts + a blocked pump),
    and — when a reshard ran mid-catch-up — the generation cutover
    actually happened.

The gate is falsifiable: ``--lose-bilog`` arms the seeded
``rgw.bilog_lost_entry`` fault for exactly one append (an acked write
whose bilog entry is silently dropped) and the drill MUST exit red.

Tiers: the default drill runs on two in-process sim clusters (fast,
deterministic — same-seed runs produce identical schedules, asserted
via the schedule digest).  ``--chaos`` makes zone A a live Vstart
cluster and composes kill9 + powercycle (device.power_loss +
torn-WAL reboot) of zone-A OSDs into the catch-up phase, while zone B
keeps syncing across the process boundary.
"""
from __future__ import annotations

import hashlib
import json
import os
import random
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..common import faults

_BUCKET = "dr"


@dataclass
class DrillConfig:
    seed: int = 0
    keys: int = 16                 # distinct hot keys per phase
    phase_ops: int = 36            # ops per write phase
    shards: int = 4                # source bucket index shards
    reshard_to: int = 8            # 0 = no mid-catch-up reshard
    lag_bound_s: float = 60.0      # replication-lag p99 gate
    heal_rounds: int = 60          # pump budget for convergence
    lose_bilog: bool = False       # falsifiability: drop one append
    chaos: bool = False            # live zone A + kill/powercycle
    n_osds: int = 3                # live-tier zone A size
    hb_interval: float = 0.25
    chaos_hold_s: float = 0.8
    workdir: Optional[str] = None  # live-tier cluster dir root
    json_out: bool = False


# ------------------------------------------------------------- zones --

class _SimZone:
    """One in-process zone: sim cluster + Rados client + gateway."""

    def __init__(self, name: str):
        from ..client.rados import Rados
        from ..rgw.gateway import RGWGateway
        from .thrasher import build_default_stack
        self.name = name
        self.sim, mon = build_default_stack(n_hosts=4,
                                            osds_per_host=2,
                                            k=2, m=1)
        self.ioctx = Rados(self.sim, mon).connect().open_ioctx("rep")
        self.gw = RGWGateway(self.ioctx)
        self.live = False

    def close(self) -> None:
        self.sim.shutdown()


class _LiveZone:
    """One process-tier zone: Vstart daemons + remote client +
    gateway (the chaos tier — kill9/powercycle need real PIDs and a
    real store to tear)."""

    def __init__(self, name: str, workdir: str, n_osds: int,
                 hb_interval: float):
        from ..client.remote import RemoteCluster
        from ..client.remote_ioctx import RemoteIoCtx
        from ..rgw.gateway import RGWGateway
        from ..tools.vstart import Vstart, build_cluster_dir
        self.name = name
        self.n_osds = n_osds
        self.hb_interval = hb_interval
        self.dir = os.path.join(workdir, f"zone_{name}")
        build_cluster_dir(self.dir, n_osds=n_osds, osds_per_host=1,
                          fsync=True, n_mons=1)
        self.v = Vstart(self.dir)
        self.v.start(n_osds, hb_interval=hb_interval)
        self.rc = RemoteCluster(self.dir)
        self.ioctx = RemoteIoCtx(self.rc, "rep")
        self.gw = RGWGateway(self.ioctx)
        self.live = True

    def close(self) -> None:
        try:
            self.rc.close()
        finally:
            self.v.stop()


# ------------------------------------------------------------- drill --

class DrDrill:
    """One seeded sever -> failover -> heal -> verify run."""

    def __init__(self, cfg: DrillConfig):
        self.cfg = cfg
        self.rng = random.Random(cfg.seed)
        self.oracle: Dict[str, Dict[str, Any]] = {}
        self.schedule: List[Tuple] = []
        self.events: List[str] = []
        self.failures: List[str] = []
        self.chaos_log: List[Tuple[str, int]] = []

    # ------------------------------------------------------- workload --
    def _data(self, key: str, i: int, zone: str) -> bytes:
        return (f"{key}:{i}:{zone}|".encode()
                * self.rng.randrange(8, 64))

    def _one_op(self, zone, phase: str, i: int) -> None:
        """One seeded put/delete against ``zone``; only ACKED results
        enter the oracle (a raised write proves nothing either way —
        the serving harness's acked-oracle rule)."""
        key = f"k{self.rng.randrange(self.cfg.keys):03d}"
        live = [k for k, v in self.oracle.items()
                if not v.get("deleted") and k.startswith("k")]
        do_delete = live and self.rng.random() < 0.18
        if do_delete:
            key = live[self.rng.randrange(len(live))]
        data = b"" if do_delete else self._data(key, i, zone.name)
        self.schedule.append((phase, zone.name,
                              "delete" if do_delete else "put",
                              key, len(data)))
        try:
            b = zone.gw.bucket(_BUCKET)
            if do_delete:
                b.delete_object(key)
                self.oracle[key] = {"deleted": True}
            else:
                etag = b.put_object(key, data)
                self.oracle[key] = {"etag": etag}
        except (IOError, OSError) as e:
            # un-acked op: the oracle keeps the previous acked state
            self.events.append(f"{phase} op {i} {key}: "
                               f"{type(e).__name__}: {e}")

    # ----------------------------------------------------------- sync --
    def _pump(self, agents: List, rounds: int = 1
              ) -> Tuple[int, int]:
        """Run each agent ``rounds`` passes; -> (applied, errors)."""
        applied = errors = 0
        for _ in range(rounds):
            for ag in agents:
                if ag is None:
                    continue
                try:
                    s = ag.sync()
                    applied += s["puts"] + s["deletes"]
                    errors += len(ag.last_errors)
                except (IOError, OSError) as e:
                    errors += 1
                    self.events.append(f"sync {ag.src_zone}->"
                                       f"{ag.zone}: "
                                       f"{type(e).__name__}: {e}")
        return applied, errors

    def _pump_until_quiet(self, agents: List,
                          budget: int) -> bool:
        """Pump until two consecutive all-quiet rounds (nothing
        applied, no errors) — the convergence condition."""
        quiet = 0
        for _ in range(budget):
            applied, errors = self._pump(agents)
            if applied == 0 and errors == 0:
                quiet += 1
                if quiet >= 2:
                    return True
            else:
                quiet = 0
        return False

    # ---------------------------------------------------------- chaos --
    def _chaos_event(self, zone, kind: str) -> None:
        """kill9 or powercycle one zone-A OSD mid-catch-up (live
        tier only).  Each event heals before the drill continues —
        catch-up must survive the shape, not an unbounded pileup."""
        import contextlib

        from ..common.admin import admin_request
        from .crashdev import tear_wal_tail
        victim = self.rng.randrange(zone.n_osds)
        self.chaos_log.append((kind, victim))
        self.events.append(f"chaos: {kind} osd.{victim}")
        if kind == "kill":
            zone.v.kill9(f"osd.{victim}")
            time.sleep(self.cfg.chaos_hold_s)
        else:                                     # powercycle
            with contextlib.suppress(OSError, IOError):
                admin_request(
                    os.path.join(zone.dir, f"osd.{victim}.asok"),
                    {"prefix": "fault_injection", "action": "arm",
                     "name": "device.power_loss", "mode": "one_in",
                     "n": 2, "seed": self.cfg.seed * 7 + victim,
                     "params": {"exit": True}})
            # scratch traffic trips the armed barrier (these writes
            # are MEANT to die; they never enter the oracle)
            with contextlib.suppress(OSError, IOError):
                sb = zone.gw.bucket("chaos-scratch")
                for i in range(8):
                    if not zone.v.alive(f"osd.{victim}"):
                        break
                    sb.put_object(f"s{i}", b"brownout" * 32)
            if zone.v.alive(f"osd.{victim}"):
                zone.v.kill9(f"osd.{victim}")   # fallback: keep moving
            tear_wal_tail(
                os.path.join(zone.dir, f"osd.{victim}.store"),
                self.rng)
        zone.v.start_osd(victim, hb_interval=zone.hb_interval)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                not zone.v.alive(f"osd.{victim}"):
            time.sleep(0.2)
        with contextlib.suppress(OSError, IOError):
            zone.rc.refresh_map()

    # ------------------------------------------------------------ run --
    def run(self) -> Dict[str, Any]:
        cfg = self.cfg
        from ..common.perf_counters import perf as _perf
        from ..rgw.sync import BucketSyncAgent, make_sync_engine
        for pair in ("a.b", "b.a"):
            _perf(f"geosync.{pair}").reset()
        za = zb = None
        engine = make_sync_engine(4)
        resharded = False
        try:
            if cfg.chaos:
                import tempfile
                workdir = cfg.workdir or tempfile.mkdtemp(
                    prefix="drdrill_")
                za = _LiveZone("a", workdir, cfg.n_osds,
                               cfg.hb_interval)
            else:
                za = _SimZone("a")
            zb = _SimZone("b")
            za.gw.create_bucket(_BUCKET, num_shards=cfg.shards)
            if cfg.chaos:
                za.gw.create_bucket("chaos-scratch")
            ab = BucketSyncAgent(za.gw, zb.gw, _BUCKET, zone="b",
                                 src_zone="a", engine=engine)
            ba = None
            agents = [ab]
            # ---- phase 1: normal serving against A, B catching up --
            chaos_at = {}
            if cfg.chaos:
                chaos_at = {cfg.phase_ops // 3: "kill",
                            (2 * cfg.phase_ops) // 3: "powercycle"}
            for i in range(cfg.phase_ops):
                self._one_op(za, "normal", i)
                if cfg.reshard_to and i == cfg.phase_ops // 2:
                    self.schedule.append(("reshard", "a",
                                          cfg.reshard_to))
                    za.gw.reshard_bucket(_BUCKET, cfg.reshard_to)
                    resharded = True
                    self.events.append(
                        f"resharded {_BUCKET} {cfg.shards} -> "
                        f"{cfg.reshard_to} mid-catch-up")
                if i in chaos_at:
                    self._chaos_event(za, chaos_at[i])
                if i % 6 == 5:
                    self._pump(agents)
            if not self._pump_until_quiet(agents, cfg.heal_rounds):
                self.failures.append("pre-sever catch-up never went "
                                     "quiet")
            # the reverse agent exists from here: B's bucket is real
            ba = BucketSyncAgent(zb.gw, za.gw, _BUCKET, zone="a",
                                 src_zone="b", engine=engine)
            agents = [ab, ba]
            # ---- sever ---------------------------------------------
            fires0 = faults.fire_counts().get("net.partition", 0)
            faults.arm("net.partition",
                       groups=[["zone.a"], ["zone.b"]])
            self.events.append("severed zone.a <-> zone.b")
            # a canary acked on A during the partition must cross
            # after heal; pumping it NOW must visibly fail
            try:
                etag = za.gw.bucket(_BUCKET).put_object(
                    "canary-sever", b"written during the partition")
                self.oracle["canary-sever"] = {"etag": etag}
                self.schedule.append(("sever", "a", "put",
                                      "canary-sever", 31))
            except (IOError, OSError) as e:
                self.events.append(f"canary write failed: {e}")
            _applied, errs = self._pump([ab])
            sever_verified = (
                errs > 0 and
                faults.fire_counts().get("net.partition", 0) > fires0)
            # ---- failover: writes move to B ------------------------
            for i in range(cfg.phase_ops):
                self._one_op(zb, "failover", i)
            if cfg.lose_bilog:
                # falsifiability: ONE acked write whose bilog entry
                # is dropped — replication can never learn about it,
                # so the convergence gate below MUST go red
                faults.arm("rgw.bilog_lost_entry", mode="always",
                           count=1)
                try:
                    etag = zb.gw.bucket(_BUCKET).put_object(
                        "lost-canary", b"this entry never logs")
                    self.oracle["lost-canary"] = {"etag": etag}
                    self.schedule.append(("failover", "b", "put",
                                          "lost-canary", 26))
                finally:
                    faults.disarm("rgw.bilog_lost_entry")
            # ---- heal ----------------------------------------------
            faults.disarm("net.partition")
            self.events.append("healed the partition")
            converged = self._pump_until_quiet(agents,
                                               cfg.heal_rounds)
            # ---- gate ----------------------------------------------
            gate = evaluate_gate(
                self.oracle, za, zb, [a for a in agents if a],
                lag_bound_s=cfg.lag_bound_s,
                sever_verified=sever_verified, converged=converged,
                resharded=resharded)
            self.failures.extend(gate["failures"])
            digest = hashlib.sha256(
                json.dumps(self.schedule, sort_keys=True).encode()
            ).hexdigest()
            return {
                "seed": cfg.seed,
                "ok": not self.failures,
                "failures": self.failures,
                "converged": converged,
                "sever_verified": sever_verified,
                "resharded": resharded,
                "keys": len(self.oracle),
                "lag_p99_s": gate["lag_p99_s"],
                "lag_samples": gate["lag_samples"],
                "agents": {f"{a.src_zone}->{a.zone}": dict(a.stats)
                           for a in agents if a},
                "chaos": list(self.chaos_log),
                "events": self.events,
                "schedule_digest": digest,
            }
        finally:
            faults.disarm("net.partition")
            faults.disarm("rgw.bilog_lost_entry")
            engine.close()
            for z in (za, zb):
                if z is not None:
                    try:
                        z.close()
                    except Exception:
                        pass


def evaluate_gate(oracle: Dict[str, Dict[str, Any]], za, zb,
                  agents: List, lag_bound_s: float,
                  sever_verified: bool, converged: bool,
                  resharded: bool) -> Dict[str, Any]:
    """The hard convergence verdict, pure over its inputs: acked
    ETags in BOTH zones, structural at-most-once counters, merged
    replication-lag p99 under the bound, and drill honesty (the
    sever bit; the reshard cut over)."""
    from ..mgr.cluster_stats import merge_histograms, quantile
    from ..rgw.gateway import RGWError
    failures: List[str] = []
    if not converged:
        failures.append("zones did not converge within the heal "
                        "budget")
    if not sever_verified:
        failures.append("net.partition never blocked a pump — the "
                        "drill severed nothing")
    for zname, zone in (("a", za), ("b", zb)):
        try:
            b = zone.gw.bucket(_BUCKET)
        except RGWError:
            failures.append(f"zone {zname}: bucket {_BUCKET!r} "
                            f"missing")
            continue
        for key, want in sorted(oracle.items()):
            try:
                _data, ent = b.get_object(key)
                if want.get("deleted"):
                    failures.append(f"zone {zname}: {key} readable "
                                    f"after acked delete")
                elif ent["etag"] != want["etag"]:
                    failures.append(
                        f"zone {zname}: {key} etag "
                        f"{ent['etag'][:8]} != acked "
                        f"{want['etag'][:8]}")
            except RGWError:
                if not want.get("deleted"):
                    failures.append(f"zone {zname}: acked key {key} "
                                    f"unreadable")
    double = sum(a.stats["double_applies"] for a in agents)
    if double:
        failures.append(f"{double} double-applies — at-most-once "
                        f"replay broke")
    fulls = sum(a.stats["full_syncs"] for a in agents)
    if fulls:
        failures.append(f"{fulls} full-sync restarts — cutover must "
                        f"drain, not restart")
    if resharded and not any(a.stats["gen_cutovers"] for a in agents):
        failures.append("reshard ran but no generation cutover was "
                        "recorded")
    merged = merge_histograms([a.lag_dump() for a in agents])
    p99 = quantile(merged, 0.99)
    if p99 is None:
        failures.append("no replication-lag samples recorded — the "
                        "lag bound was never exercised")
    elif p99 > lag_bound_s:
        failures.append(f"replication-lag p99 {p99:.3f}s exceeds "
                        f"the {lag_bound_s}s bound")
    return {"failures": failures, "lag_p99_s": p99,
            "lag_samples": int(merged.get("count", 0))}


def run_drill(cfg: DrillConfig) -> Dict[str, Any]:
    return DrDrill(cfg).run()


def drill_main(argv: Optional[Sequence[str]] = None,
               out=None) -> int:
    """`ceph serve --dr [--seed N --chaos --lose-bilog --json]` —
    exit 0 only when the convergence gate holds."""
    import argparse
    import sys
    out = out or sys.stdout
    ap = argparse.ArgumentParser(
        prog="ceph serve --dr",
        description="two-zone DR drill: sever, fail over, heal, "
                    "gate on convergence")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--keys", type=int, default=16)
    ap.add_argument("--phase-ops", type=int, default=36)
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--reshard-to", type=int, default=8,
                    help="reshard the source bucket to this many "
                         "shards mid-catch-up (0 = skip)")
    ap.add_argument("--lag-bound-s", type=float, default=60.0)
    ap.add_argument("--lose-bilog", action="store_true",
                    help="falsifiability check: drop one acked "
                         "write's bilog entry — the gate MUST fail")
    ap.add_argument("--chaos", action="store_true",
                    help="zone A runs live OSD daemons and eats "
                         "kill9 + powercycle during catch-up")
    ap.add_argument("--json", action="store_true")
    ns = ap.parse_args(list(argv or []))
    cfg = DrillConfig(seed=ns.seed, keys=ns.keys,
                      phase_ops=ns.phase_ops, shards=ns.shards,
                      reshard_to=ns.reshard_to,
                      lag_bound_s=ns.lag_bound_s,
                      lose_bilog=ns.lose_bilog, chaos=ns.chaos,
                      json_out=ns.json)
    report = run_drill(cfg)
    if ns.json:
        out.write(json.dumps(report, indent=2, sort_keys=True)
                  + "\n")
    else:
        out.write(f"dr drill seed={report['seed']} "
                  f"keys={report['keys']} "
                  f"lag_p99={report['lag_p99_s']} "
                  f"{'OK' if report['ok'] else 'FAILED'}\n")
        for f in report["failures"]:
            out.write(f"  FAIL: {f}\n")
    return 0 if report["ok"] else 1
