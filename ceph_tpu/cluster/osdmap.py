"""OSDMap — the versioned cluster map and the object→PG→OSD pipeline.

Re-creates the placement policy surface of the reference's OSDMap
(src/osd/OSDMap.{h,cc}): pools, OSD existence/up/in states and weights,
pg_temp / primary_temp overrides, pg_upmap / pg_upmap_items exceptions,
primary affinity, and the full pipeline

    _pg_to_raw_osds (CRUSH) → _apply_upmap → _raw_to_up_osds →
    _pick_primary/_apply_primary_affinity → pg_temp override
    (reference: src/osd/OSDMap.cc:2435-2715)

with two execution paths:

  * scalar per-PG (`pg_to_up_acting_osds`) — oracle + control plane;
  * batched (`map_pgs_batch`) — all PGs of a pool in one jitted CRUSH
    call via XlaMapper, with the host-side pipeline stages vectorized in
    NumPy.  This supersedes the thread-pool ParallelPGMapper
    (src/osd/OSDMapMapping.h:18).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops import hashing
from ..placement.crush_map import ITEM_NONE, CrushMap
from ..placement import scalar_mapper
from ..placement.xla_mapper import UnsupportedMapError, XlaMapper

# pool types (reference: src/osd/osd_types.h pg_pool_t::TYPE_*)
POOL_REPLICATED = 1
POOL_ERASURE = 3

# flags (subset)
FLAG_HASHPSPOOL = 1 << 0
FLAG_EC_OVERWRITES = 1 << 17   # reference: src/osd/osd_types.h:1244

# cluster-wide osdmap flags an operator sets to ride out known events
# (reference: CEPH_OSDMAP_NOOUT / CEPH_OSDMAP_NODOWN,
# src/osd/OSDMap.h get_flags; `ceph osd set noout`): "noout" stops the
# automatic down->out transition, "nodown" stops failure reports from
# marking OSDs down — both honored by the heartbeat/markdown path
CLUSTER_FLAGS = ("noout", "nodown")

MAX_PRIMARY_AFFINITY = 0x10000
WEIGHT_IN = 0x10000


def _calc_bits_of(n: int) -> int:
    bits = 0
    while n:
        n >>= 1
        bits += 1
    return bits


def pg_num_mask(pg_num: int) -> int:
    """(1 << cbits(pg_num-1)) - 1 (reference: pg_pool_t::calc_pg_masks)."""
    return (1 << _calc_bits_of(pg_num - 1)) - 1 if pg_num else 0


def stable_mod(x: int, b: int, bmask: int) -> int:
    """ceph_stable_mod (reference: src/include/ceph_hash.h semantics;
    cited via src/osd/osd_types.cc:1781)."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


@dataclass
class PGId:
    """pg_t: (pool, ps)."""
    pool: int
    ps: int

    def __hash__(self):
        return hash((self.pool, self.ps))


@dataclass
class PGPool:
    """pg_pool_t subset relevant to placement (src/osd/osd_types.h)."""
    id: int
    name: str = ""
    type: int = POOL_REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 8
    pgp_num: int = 0
    crush_rule: int = 0
    flags: int = FLAG_HASHPSPOOL
    erasure_code_profile: str = ""
    # EC stripe unit (reference: osd_pool_erasure_code_stripe_unit,
    # default 4 KiB); chunk size of every stripe in the pool
    stripe_unit: int = 4096
    # pool snapshot context (pg_pool_t::snap_seq / snaps)
    snap_seq: int = 0
    snaps: Dict[int, str] = field(default_factory=dict)
    # cache tiering (pg_pool_t::tier_of / read_tier / write_tier,
    # src/osd/osd_types.h): a CACHE pool carries tier_of = its base
    # pool; the BASE pool carries read_tier/write_tier = the cache
    # pool the op engine redirects reads/writes to
    tier_of: int = -1
    read_tier: int = -1
    write_tier: int = -1
    cache_mode: str = ""

    def __post_init__(self):
        if not self.pgp_num:
            self.pgp_num = self.pg_num

    @property
    def pg_num_mask(self) -> int:
        return pg_num_mask(self.pg_num)

    @property
    def pgp_num_mask(self) -> int:
        return pg_num_mask(self.pgp_num)

    def can_shift_osds(self) -> bool:
        """Replicated pools compact holes; EC pools are positional
        (src/osd/osd_types.h pg_pool_t::can_shift_osds)."""
        return self.type == POOL_REPLICATED

    def raw_pg_to_pg(self, ps: int) -> int:
        return stable_mod(ps, self.pg_num, self.pg_num_mask)

    def raw_pg_to_pps(self, ps: int) -> int:
        """Placement seed (src/osd/osd_types.cc:1798-1811)."""
        if self.flags & FLAG_HASHPSPOOL:
            return hashing.hash2(
                stable_mod(ps, self.pgp_num, self.pgp_num_mask), self.id)
        return stable_mod(ps, self.pgp_num, self.pgp_num_mask) + self.id

    def raw_pg_to_pps_batch(self, pss: np.ndarray) -> np.ndarray:
        ps = np.asarray(pss, dtype=np.int64)
        masked = ps & self.pgp_num_mask
        sm = np.where(masked < self.pgp_num, masked,
                      ps & (self.pgp_num_mask >> 1))
        if self.flags & FLAG_HASHPSPOOL:
            return hashing.np_hash2(sm.astype(np.uint32),
                                    np.uint32(self.id)).astype(np.int64)
        return sm + self.id


@dataclass
class Incremental:
    """A versioned map delta (OSDMap::Incremental role): the mon
    publishes these per epoch; consumers apply them in order instead of
    refetching full maps.  Only the mutation surface the simulator uses."""
    epoch: int                                   # resulting epoch
    new_up: Dict[int, bool] = field(default_factory=dict)
    new_weight: Dict[int, int] = field(default_factory=dict)
    new_primary_affinity: Dict[int, int] = field(default_factory=dict)
    new_pg_upmap_items: Dict[Tuple[int, int],
                             Optional[List[Tuple[int, int]]]] = \
        field(default_factory=dict)              # None = remove
    new_pg_temp: Dict[Tuple[int, int], Optional[List[int]]] = \
        field(default_factory=dict)
    # pool mutations (OSDMap::Incremental new_pools subset)
    new_pool_pg_num: Dict[int, int] = field(default_factory=dict)
    # pool creation/removal (new_pools full specs / old_pools):
    # values are PGPool constructor kwargs so the delta is
    # JSON-serializable for the mon quorum's decree log
    new_pools: Dict[int, dict] = field(default_factory=dict)
    old_pools: List[int] = field(default_factory=list)
    # cache-tier wiring: pool id -> {tier_of|read_tier|write_tier|
    # cache_mode} field updates (OSDMonitor 'osd tier add' role)
    new_pool_tier: Dict[int, dict] = field(default_factory=dict)
    # cluster flag changes: name -> set (True) / clear (False)
    # (OSDMap::Incremental new_flags role)
    new_flags: Dict[str, bool] = field(default_factory=dict)


class OSDMap:
    """The cluster map: crush + osd states + pools + exception tables."""

    def __init__(self, crush: CrushMap, max_osd: int = 0, epoch: int = 1):
        self.epoch = epoch
        self.crush = crush
        self.max_osd = max(max_osd, crush.max_devices)
        n = self.max_osd
        self.osd_exists = np.zeros(n, dtype=bool)
        self.osd_up = np.zeros(n, dtype=bool)
        self.osd_weight = np.zeros(n, dtype=np.int64)    # 16.16 in/out
        self.osd_primary_affinity = np.full(n, MAX_PRIMARY_AFFINITY,
                                            dtype=np.int64)
        self.pools: Dict[int, PGPool] = {}
        # cluster-wide flags (noout/nodown — CLUSTER_FLAGS)
        self.flags: set = set()
        # monotonic pool-id high-water mark (the reference's
        # new_pool_max): a deleted pool's id is NEVER reused, or the
        # next pool would inherit its surviving objects/snap state
        self.pool_id_max = 0
        self.pg_temp: Dict[Tuple[int, int], List[int]] = {}
        self.primary_temp: Dict[Tuple[int, int], int] = {}
        self.pg_upmap: Dict[Tuple[int, int], List[int]] = {}
        self.pg_upmap_items: Dict[Tuple[int, int], List[Tuple[int, int]]] = {}
        self._mapper: Optional[XlaMapper] = None
        self._mapper_map: Optional[CrushMap] = None

    # ------------------------------------------------------------ mutate --
    def bump_epoch(self) -> None:
        self.epoch += 1

    def apply_incremental(self, inc: Incremental) -> None:
        """Consume a map delta (OSDMap::apply_incremental): must be the
        next epoch in sequence."""
        if inc.epoch != self.epoch + 1:
            raise ValueError(
                f"incremental epoch {inc.epoch} != {self.epoch} + 1")
        for osd, up in inc.new_up.items():
            self.osd_up[osd] = up
        for osd, w in inc.new_weight.items():
            self.osd_weight[osd] = w
        for osd, a in inc.new_primary_affinity.items():
            self.osd_primary_affinity[osd] = a
        for pgid, items in inc.new_pg_upmap_items.items():
            if items is None:
                self.pg_upmap_items.pop(pgid, None)
            else:
                self.pg_upmap_items[pgid] = list(items)
        for pgid, temp in inc.new_pg_temp.items():
            if temp is None:
                self.pg_temp.pop(pgid, None)
            else:
                self.pg_temp[pgid] = list(temp)
        for pid, pg_num in inc.new_pool_pg_num.items():
            pool = self.pools.get(pid)
            if pool is not None:
                pool.pg_num = pg_num
                pool.pgp_num = pg_num
        for pid, spec in inc.new_pools.items():
            self.pools[pid] = PGPool(**{**spec, "id": pid})
            self.pool_id_max = max(self.pool_id_max, pid)
        for pid, fields in inc.new_pool_tier.items():
            pool = self.pools.get(pid)
            if pool is None:
                continue
            for fk in ("tier_of", "read_tier", "write_tier"):
                if fk in fields:
                    setattr(pool, fk, int(fields[fk]))
            if "cache_mode" in fields:
                pool.cache_mode = str(fields["cache_mode"])
        for flag, on in inc.new_flags.items():
            if on:
                self.flags.add(flag)
            else:
                self.flags.discard(flag)
        for pid in inc.old_pools:
            self.pools.pop(pid, None)
            # stale placement overrides keyed by the dead pool go too
            for table in (self.pg_temp, self.primary_temp,
                          self.pg_upmap, self.pg_upmap_items):
                for key in [k for k in table if k[0] == pid]:
                    del table[key]
        self.epoch = inc.epoch

    def set_osd(self, osd: int, *, exists=True, up=True,
                weight=WEIGHT_IN) -> None:
        self.osd_exists[osd] = exists
        self.osd_up[osd] = up
        self.osd_weight[osd] = weight

    def mark_all_in_up(self) -> None:
        self.osd_exists[:] = True
        self.osd_up[:] = True
        self.osd_weight[:] = WEIGHT_IN

    def mark_down(self, osd: int) -> None:
        self.osd_up[osd] = False
        self.bump_epoch()

    def mark_out(self, osd: int) -> None:
        self.osd_weight[osd] = 0
        self.bump_epoch()

    def add_pool(self, pool: PGPool) -> None:
        self.pools[pool.id] = pool
        self.pool_id_max = max(self.pool_id_max, pool.id)

    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and bool(self.osd_exists[osd])

    def is_up(self, osd: int) -> bool:
        return self.exists(osd) and bool(self.osd_up[osd])

    # -------------------------------------------------- pipeline (scalar) --
    def _crush_rule_for(self, pool: PGPool) -> int:
        return pool.crush_rule

    def _pg_to_raw_osds(self, pool: PGPool, ps: int) -> Tuple[List[int], int]:
        pps = pool.raw_pg_to_pps(ps)
        raw = scalar_mapper.do_rule(
            self.crush, self._crush_rule_for(pool), pps, pool.size,
            list(self.osd_weight[:self.crush.max_devices]))
        self._remove_nonexistent(pool, raw)
        return raw, pps

    def _remove_nonexistent(self, pool: PGPool, raw: List[int]) -> None:
        """(OSDMap.cc _remove_nonexistent_osds)"""
        if pool.can_shift_osds():
            raw[:] = [o for o in raw
                      if o == ITEM_NONE or self.exists(o)]
            raw[:] = [o for o in raw if o != ITEM_NONE]
        else:
            raw[:] = [o if o != ITEM_NONE and self.exists(o) else ITEM_NONE
                      for o in raw]

    def _apply_upmap(self, pool: PGPool, pgid: Tuple[int, int],
                     raw: List[int]) -> List[int]:
        """(OSDMap.cc:2465-2510)"""
        p = self.pg_upmap.get(pgid)
        if p is not None:
            if any(o != ITEM_NONE and 0 <= o < self.max_osd and
                   self.osd_weight[o] == 0 for o in p):
                # any out target rejects the whole exception — including
                # pg_upmap_items (OSDMap.cc:2475 returns, not falls through)
                return raw
            raw = list(p)
        q = self.pg_upmap_items.get(pgid)
        if q is not None:
            for frm, to in q:
                exists_ = False
                pos = -1
                for i, o in enumerate(raw):
                    if o == to:
                        exists_ = True
                        break
                    if o == frm and pos < 0 and not (
                            to != ITEM_NONE and 0 <= to < self.max_osd and
                            self.osd_weight[to] == 0):
                        pos = i
                if not exists_ and pos >= 0:
                    raw[pos] = to
        return raw

    def _raw_to_up(self, pool: PGPool, raw: List[int]) -> List[int]:
        if pool.can_shift_osds():
            return [o for o in raw if self.is_up(o)]
        return [o if o != ITEM_NONE and self.is_up(o) else ITEM_NONE
                for o in raw]

    @staticmethod
    def _pick_primary(osds: Sequence[int]) -> int:
        for o in osds:
            if o != ITEM_NONE:
                return o
        return -1

    def _apply_primary_affinity(self, pps: int, pool: PGPool,
                                up: List[int], primary: int
                                ) -> Tuple[List[int], int]:
        """(OSDMap.cc:2537-2590)"""
        if not any(o != ITEM_NONE and
                   self.osd_primary_affinity[o] != MAX_PRIMARY_AFFINITY
                   for o in up):
            return up, primary
        pos = -1
        for i, o in enumerate(up):
            if o == ITEM_NONE:
                continue
            a = int(self.osd_primary_affinity[o])
            if a < MAX_PRIMARY_AFFINITY and \
                    (hashing.hash2(pps, o) >> 16) >= a:
                if pos < 0:
                    pos = i
            else:
                pos = i
                break
        if pos < 0:
            return up, primary
        primary = up[pos]
        if pool.can_shift_osds() and pos > 0:
            up = [up[pos]] + up[:pos] + up[pos + 1:]
        return up, primary

    def _get_temp_osds(self, pool: PGPool, pgid: Tuple[int, int]
                       ) -> Tuple[List[int], int]:
        """(OSDMap.cc:2592-2625)"""
        temp = []
        raw_temp = self.pg_temp.get(pgid)
        if raw_temp:
            for o in raw_temp:
                if not self.is_up(o):
                    if pool.can_shift_osds():
                        continue
                    temp.append(ITEM_NONE)
                else:
                    temp.append(o)
        temp_primary = self.primary_temp.get(pgid, -1)
        if temp_primary == -1 and temp:
            temp_primary = self._pick_primary(temp)
        return temp, temp_primary

    def pg_to_up_acting_osds(self, pool_id: int, ps: int
                             ) -> Tuple[List[int], int, List[int], int]:
        """The full pipeline (OSDMap.cc:2667-2715): returns
        (up, up_primary, acting, acting_primary)."""
        pool = self.pools.get(pool_id)
        if pool is None or ps >= pool.pg_num:
            return [], -1, [], -1
        pgid = (pool_id, pool.raw_pg_to_pg(ps))
        acting, acting_primary = self._get_temp_osds(pool, pgid)
        raw, pps = self._pg_to_raw_osds(pool, ps)
        raw = self._apply_upmap(pool, pgid, raw)
        up = self._raw_to_up(pool, raw)
        up_primary = self._pick_primary(up)
        up, up_primary = self._apply_primary_affinity(
            pps, pool, up, up_primary)
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    # ------------------------------------------------- pipeline (batched) --
    def _batched_mapper(self) -> XlaMapper:
        # keyed on the CrushMap object, not the epoch: osd weights are
        # runtime operands of map_batch, so up/down/out changes must NOT
        # recompile; only crush topology edits (a new map value) do
        if self._mapper is None or self._mapper_map is not self.crush:
            self._mapper = XlaMapper(self.crush)
            self._mapper_map = self.crush
        return self._mapper

    def map_pgs_batch(self, pool_id: int,
                      pss: Optional[np.ndarray] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Map many PGs of one pool in a single jitted CRUSH call.

        Returns (up [N, size] int32 with ITEM_NONE holes per EC semantics,
        up_primary [N] int32).  pg_temp/primary_temp are control-plane
        overlays applied by callers that need acting sets (they are sparse
        dicts; see pg_to_up_acting_osds).
        """
        pool = self.pools.get(pool_id)
        if pool is None:
            raise KeyError(f"no pool {pool_id}")
        if pss is None:
            pss = np.arange(pool.pg_num, dtype=np.int64)
        pss = np.asarray(pss, dtype=np.int64)
        pps = pool.raw_pg_to_pps_batch(pss)
        mapper = self._batched_mapper()
        # sharded data plane: the PG lane axis splits across the mesh
        # (the multi-chip ParallelPGMapper, src/osd/OSDMapMapping.h:18)
        # — million-PG remap sweeps run one shard per chip; identical
        # results, the mapper pads lanes to the mesh size internally
        from ..parallel.data_plane import plane as _data_plane
        dp = _data_plane()
        raw = mapper.map_batch(
            self._crush_rule_for(pool), pps, pool.size,
            self.osd_weight[:self.crush.max_devices],
            mesh=dp.mesh if dp is not None else None).astype(np.int64)
        if dp is not None:
            dp.account("map", len(pss), 4 * pool.size)
        return self._post_crush_batch(pool, pss, pps, raw)

    def _post_crush_batch(self, pool: PGPool, pss, pps, raw
                          ) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized upmap/up/primary stages on host (NumPy)."""
        N, R = raw.shape
        # nonexistent / down → NONE
        ids = np.clip(raw, 0, self.max_osd - 1)
        valid = (raw >= 0) & (raw < self.max_osd) & \
            self.osd_exists[ids] & self.osd_up[ids]
        up = np.where(valid & (raw != ITEM_NONE), raw, ITEM_NONE)
        # sparse upmap exceptions via the scalar path
        if self.pg_upmap or self.pg_upmap_items:
            pgids = [(pool.id, pool.raw_pg_to_pg(int(p))) for p in pss]
            hit = [i for i, g in enumerate(pgids)
                   if g in self.pg_upmap or g in self.pg_upmap_items]
            for i in hit:
                raw_i = [int(v) for v in raw[i]]
                self._remove_nonexistent(pool, raw_i)
                raw_i = self._apply_upmap(pool, pgids[i], raw_i)
                up_i = self._raw_to_up(pool, raw_i)
                row = np.full(R, ITEM_NONE, dtype=np.int64)
                row[:len(up_i)] = up_i
                up[i] = row
        if pool.can_shift_osds():
            # compact NONE holes leftward, preserving order: a stable
            # argsort on the hole mask is the whole permutation
            order = np.argsort(up == ITEM_NONE, axis=1, kind="stable")
            up = np.take_along_axis(up, order, axis=1)
        # primary: first non-NONE (affinity overlay for the non-default case)
        primary = np.full(N, -1, dtype=np.int64)
        has = (up != ITEM_NONE)
        anyrow = has.any(axis=1)
        primary[anyrow] = up[anyrow, has[anyrow].argmax(axis=1)]
        if np.any(self.osd_primary_affinity != MAX_PRIMARY_AFFINITY):
            up, primary = self._apply_primary_affinity_batch(
                pool, pps, up, primary)
        return up.astype(np.int32), primary.astype(np.int32)

    def _apply_primary_affinity_batch(self, pool: PGPool, pps, up, primary):
        """Array form of _apply_primary_affinity (OSDMap.cc:2537-2590):
        position-ordered scan becomes accept/reject masks + one gather.

        Scalar semantics per row: walking non-NONE entries left to
        right, an entry with affinity a < MAX is REJECTED when
        hash(pps, osd) >> 16 >= a; the first accepted entry becomes
        primary (breaking the scan), else the first rejected one; for
        shifting pools the winner rotates to the front."""
        from ..ops import hashing
        N, R = up.shape
        valid = up != ITEM_NONE
        ids = np.clip(up, 0, self.max_osd - 1)
        aff = np.where(valid, self.osd_primary_affinity[ids],
                       MAX_PRIMARY_AFFINITY).astype(np.int64)
        h = hashing.np_hash2(
            np.broadcast_to(np.asarray(pps, dtype=np.uint32)[:, None],
                            (N, R)),
            ids.astype(np.uint32)).astype(np.int64) >> 16
        rejected = valid & (aff < MAX_PRIMARY_AFFINITY) & (h >= aff)
        accepted = valid & ~rejected
        any_acc = accepted.any(axis=1)
        any_rej = rejected.any(axis=1)
        first_acc = accepted.argmax(axis=1)
        first_rej = rejected.argmax(axis=1)
        pos = np.where(any_acc, first_acc,
                       np.where(any_rej, first_rej, -1))
        rows = np.arange(N)
        picked = pos >= 0
        primary = np.where(picked, up[rows, np.maximum(pos, 0)], primary)
        if pool.can_shift_osds():
            # rotate the winner to the front of each picked row
            idx = np.broadcast_to(np.arange(R), (N, R)).copy()
            p = np.maximum(pos, 0)[:, None]
            src = np.where(idx == 0, p, np.where(idx <= p, idx - 1, idx))
            rotated = np.take_along_axis(up, src, axis=1)
            up = np.where((picked & (pos > 0))[:, None], rotated, up)
        return up, primary

    # ---------------------------------------------------------- analytics --
    def pg_counts_per_osd(self, pool_ids: Optional[Sequence[int]] = None
                          ) -> np.ndarray:
        """PG replica count per OSD across pools (balancer input)."""
        counts = np.zeros(self.max_osd, dtype=np.int64)
        for pid in (pool_ids if pool_ids is not None else self.pools):
            up, _ = self.map_pgs_batch(pid)
            vals = up[up != ITEM_NONE]
            np.add.at(counts, vals, 1)
        return counts
