"""Daemon processes: authenticated wire servers for mon and OSD.

The process model VERDICT r2 called for (Missing #2): OSDs and the mon
run as REAL operating-system processes, each owning a durable store,
exchanging the typed envelopes over unix-domain sockets with a
cephx-style handshake on every connection (common/auth.py) and
per-frame session MACs (msg/wire.py).  Reference shape: ceph_osd.cc
main wiring messengers + OSD::init (src/ceph_osd.cc:540-551,
src/osd/OSD.cc:3373), ceph_mon main, and the cephx handshake on every
connection (src/auth/cephx/CephxProtocol.h).

Servers here are intentionally compact: a threaded accept loop; each
connection = banner -> auth -> framed request/reply.  Two handshake
modes, matching cephx:

  * secret mode (client <-> mon): the entity proves knowledge of its
    OWN keyring secret; the mon returns a sealed session key.  This is
    the cephx AUTH phase that bootstraps everything else.
  * ticket mode (anything <-> osd): the client presents a ticket
    sealed under the TARGET's secret plus an authorizer; no mon
    round-trip needed (CephxAuthorizeHandler::verify role).

OSD daemons: FileStore-backed shard ops through the mClock scheduler,
peer heartbeats with failure reports to the mon, replicated-write
fan-out to peer OSDs (daemon-to-daemon traffic), and primary-driven
PG recovery (list/pull/push).
"""
from __future__ import annotations

import argparse
import json
import os
import secrets
import socket
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..common import auth as cx
from ..common import crcutil
from ..common import faults
from ..common import tracer as _trace
from ..common.admin import AdminServer
from ..common.backoff import ExpBackoff
from ..common.lockdep import LockdepLock
from ..common.op_tracker import mark_active, tracker as _op_tracker
from ..common.perf_counters import perf as _perf
from ..msg import encoding
from ..msg.queue import Envelope
from ..msg import wire

# daemon-tier faultpoints: the ms_inject_socket_failures option is now
# a registry client (name + status field kept for compat), and the
# thrasher's crash/hang axes fire at the op-dispatch phase boundary
# (select a phase by arming with match={"cmd": "put_shard"})
faults.declare("wire.inject_socket_failures",
               "drop the connection mid-request without a reply — the "
               "reference's ms_inject_socket_failures axis, armed "
               "one-in-N from the cluster spec; every client path "
               "must reconnect and retry")
faults.declare("daemon.crash_op",
               "kill this daemon process (os._exit) as a wire op "
               "arrives — the thrashosds kill_osd axis at a chosen "
               "phase (arm with match={'cmd': ...})")
faults.declare("daemon.hang_op",
               "stall a wire op for params['seconds'] (default 0.5) "
               "before dispatch — the stalled-daemon axis feeding the "
               "SLOW_OPS / heartbeat pipelines")

# message types — canonical values live with the framing (msg/wire.py);
# aliased here for the daemon code that grew up around these names
MSG_AUTH_NONCE = wire.MSG_AUTH_NONCE
MSG_AUTH_SECRET = wire.MSG_AUTH_SECRET   # secret-mode proof
MSG_AUTH_TICKET = wire.MSG_AUTH_TICKET   # ticket-mode (ticket + authorizer)
MSG_AUTH_OK = wire.MSG_AUTH_OK
MSG_AUTH_FAIL = wire.MSG_AUTH_FAIL
MSG_REQ = wire.MSG_REQ       # typed-encoded {"cmd": ..., ...}
MSG_REPLY = wire.MSG_REPLY
MSG_ERR = wire.MSG_ERR

# typed wire encoding (msg/encoding.py) — pickle never touches
# network input (reference: typed struct encode/decode,
# src/include/encoding.h)
_dumps = encoding.dumps


class _ShmPoisoned(Exception):
    """A shared-memory doorbell's ring record failed its verify scan
    (bit flip, torn record, client overwrite): the connection must
    DROP without a reply, exactly like a corrupt socket frame —
    an error reply would acknowledge bytes that were never valid."""


def mon_sockets(cluster_dir: str) -> List[str]:
    """The cluster's mon socket paths (single source of the naming
    convention: 'mon.sock' for a lone mon, 'mon.{r}.sock' per rank
    for a quorum).  Consumed by clients, OSDs and vstart alike."""
    try:
        spec = json.load(open(os.path.join(cluster_dir,
                                           "cluster.json")))
        n = int(spec.get("n_mons", 1))
    except FileNotFoundError:
        n = 1
    if n == 1:
        return [os.path.join(cluster_dir, "mon.sock")]
    return [os.path.join(cluster_dir, f"mon.{r}.sock")
            for r in range(n)]


# ---------------------------------------------------------------- server ---

class WireServer:
    """Threaded unix-socket server with mandatory auth handshake."""

    def __init__(self, sock_path: str, service: str, keyring: cx.Keyring,
                 handler: Callable[[str, Dict[str, Any]], Any],
                 secret_mode_keyring: Optional[cx.Keyring] = None,
                 inject_socket_failures: int = 0,
                 net_entity: Optional[str] = None):
        """``handler(entity, request) -> reply_obj`` (may raise).
        ``secret_mode_keyring``: when set (the mon), clients may
        authenticate by entity secret; otherwise only tickets sealed
        under this service's secret are accepted.
        ``inject_socket_failures``: fault injection (the reference's
        ms_inject_socket_failures option, src/common/options.cc) —
        on average one in N requests has its connection dropped
        WITHOUT a reply, exercising every client's reconnect/retry
        path; 0 disables.  Implemented on the faultpoint registry
        (``wire.inject_socket_failures``, seeded from the service
        name so runs reproduce); the registry is process-wide, so the
        last arm in a multi-server process sets the schedule and every
        server in that process drops — daemon processes host exactly
        one server.  The option is only the boot-time arming path: a
        runtime ``fault_injection`` asok arm works identically on a
        daemon whose spec option was 0."""
        self.sock_path = sock_path
        self.service = service
        # this daemon's name in net.partition groups (the service
        # string for OSDs; mons pass their RANKED entity, since
        # "mon." cannot distinguish quorum members in a split)
        self.net_entity = net_entity or service
        self.keyring = keyring
        self.secret_mode_keyring = secret_mode_keyring
        self.handler = handler
        self.inject_socket_failures = int(inject_socket_failures)
        self.injected = 0
        if self.inject_socket_failures > 0:
            faults.arm("wire.inject_socket_failures", mode="one_in",
                       n=self.inject_socket_failures,
                       seed=zlib.crc32(service.encode()))
        self.auth_failures = 0
        self._stop = threading.Event()
        if os.path.exists(sock_path):
            os.unlink(sock_path)
        # reap ring files orphaned by kill9'd clients (a crashed
        # client never reaches its StreamPool.close unlink; the
        # files are sparse but accumulate across chaos soaks)
        from ..msg.shm_ring import sweep_stale
        sweep_stale(os.path.dirname(sock_path) or ".")
        # daemon→client reply rings (RingReply): ONE per client
        # request-ring path, shared by every serving connection of
        # that client's stream pool (a reply doorbell must resolve on
        # whichever stream it arrives; ShmRing's lock makes the
        # cross-connection puts safe).  Refcounted by serving conns —
        # the last close unlinks the file; a kill9'd daemon's orphans
        # are swept by the CLIENT on reconnect (zwreply prefix).
        self._reply_rings: Dict[str, list] = {}
        self._reply_lock = LockdepLock("srv.reply_rings", recursive=False)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(sock_path)
        # deep backlog: injected-drop reconnect storms (every client
        # path re-dialing at once) overflow a 64-entry queue under
        # CPU contention and surface as ECONNREFUSED from a
        # perfectly healthy daemon
        self._sock.listen(512)
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name=f"srv-{service}")
        self._thread.start()

    def _accept_loop(self) -> None:
        import errno
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError as e:
                # TRANSIENT resource pressure must not kill the
                # accept loop: an EMFILE spike (fd exhaustion under
                # reconnect storms / parallel suites) used to return
                # here, after which the still-bound socket's backlog
                # filled and every connect was REFUSED forever — a
                # live daemon that can never be reached again.  Only
                # a closed listener (stop()) ends the loop.
                if e.errno in (errno.EMFILE, errno.ENFILE,
                               errno.ENOBUFS, errno.ENOMEM,
                               errno.EINTR):
                    time.sleep(0.05)
                    continue
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _handshake(self, conn: socket.socket) -> Tuple[str, bytes]:
        """-> (entity, session_key); raises on any failure."""
        wire.exchange_banners(conn)
        nonce = secrets.token_bytes(16)
        wire.send_frame(conn, Envelope(MSG_AUTH_NONCE, 0, -1, nonce))
        env = wire.recv_frame(conn)
        if env.type == MSG_AUTH_TICKET:
            blob = encoding.loads(env.payload)
            entity, session_key = cx.verify_authorizer(
                self.keyring.secret(self.service), blob["ticket"],
                blob["authorizer"], nonce)
            return entity, session_key
        if env.type == MSG_AUTH_SECRET and self.secret_mode_keyring:
            blob = encoding.loads(env.payload)
            entity = blob["entity"]
            secret = self.secret_mode_keyring.secret(entity)
            import hmac as _hmac
            want = _hmac.new(secret, b"secret-proof" + nonce,
                             "sha256").digest()
            if not _hmac.compare_digest(blob["proof"], want):
                raise cx.AuthError(f"bad secret proof from {entity!r}")
            session_key = secrets.token_bytes(32)
            wire.send_frame(conn, Envelope(
                MSG_AUTH_OK, 0, -1, cx.seal(secret, session_key)))
            return entity, session_key
        raise cx.AuthError(f"unsupported auth frame {env.type:#x}")

    def _acquire_reply_ring(self, client_path: str, size: int):
        """Create-or-join the reply ring paired with one client
        request ring; returns the ShmRing or None (creation failed —
        the reply lane stays off, socket replies still work)."""
        from ..msg.shm_ring import ShmRing
        with self._reply_lock:
            ent = self._reply_rings.get(client_path)
            if ent is not None:
                ent[1] += 1
                return ent[0]
            try:
                ring = ShmRing.create(
                    os.path.dirname(self.sock_path) or ".",
                    self.service, int(size), prefix="zwreply")
            except OSError:
                return None
            self._reply_rings[client_path] = [ring, 1]
            return ring

    def _release_reply_ring(self, client_path: str) -> None:
        with self._reply_lock:
            ent = self._reply_rings.get(client_path)
            if ent is None:
                return
            ent[1] -= 1
            if ent[1] > 0:
                return
            del self._reply_rings[client_path]
            ring = ent[0]
        ring.close(unlink=True)

    def _reply_blobs(self, conn, rid: int, reply, key, mode: str,
                     entity: str, reply_ring, reply_toks: dict,
                     reply_sg: bool) -> list:
        """Reply-direction chokepoint (RingReply): route one handler
        reply onto the cheapest lane.  A BulkReply carries the csums
        the store already trusts for its bytes, so in preference
        order: (1) same-host reply ring — the payload crosses via
        mmap and only a one-key doorbell marker rides the typed
        reply: zero copies AND zero send scans; (2) MSG_REPLY_SG
        socket frame — the trusted csums FOLD into the frame crc
        (crc32_combine): zero send scans; (3) legacy typed reply
        (client never advertised reply_sg — blocking WireClient):
        materialized bytes, the send scan runs and is COUNTED,
        exactly the before-lane the bench prices.  A dict carrying
        BulkReply values (the recovery-pull shape) rides the ring
        per-object under a ``_shm_objs`` marker.  Everything else is
        a plain typed reply, unchanged."""
        pc = crcutil._counters()
        if isinstance(reply, wire.BulkReply):
            data, csums = reply.data, reply.csums
            combined = csums.combined if (
                csums is not None and
                csums.length == len(data)) else None
            if reply_ring is not None and len(data) >= wire.SG_MIN:
                tok = reply_ring.put(data, combined)
                if tok is not None:
                    reply_toks[(tok.off, tok.gen)] = tok
                    pc.inc("shm_reply_frames")
                    pc.inc("shm_reply_bytes", len(data))
                    return wire.prepare_frame(
                        conn, MSG_REPLY, rid, -1,
                        [_dumps({"_shm_reply": tok.meta})], key,
                        mode, self.net_entity, entity)
            if reply_sg and len(data) >= wire.SG_MIN:
                return wire.prepare_frame(
                    conn, wire.MSG_REPLY_SG, rid, -1,
                    [wire._U32.pack(0), data], key, mode,
                    self.net_entity, entity, data_csums=csums)
            reply = reply.to_bytes()
        elif isinstance(reply, dict) and any(
                isinstance(v, wire.BulkReply)
                for v in reply.values()):
            if reply_ring is not None:
                out: Dict[str, Any] = {}
                for k, v in reply.items():
                    if isinstance(v, wire.BulkReply) and \
                            len(v.data) >= wire.SG_MIN:
                        comb = v.csums.combined if (
                            v.csums is not None and
                            v.csums.length == len(v.data)) else None
                        tok = reply_ring.put(v.data, comb)
                        if tok is not None:
                            reply_toks[(tok.off, tok.gen)] = tok
                            pc.inc("shm_reply_frames")
                            pc.inc("shm_reply_bytes", len(v.data))
                            out[k] = tok.meta
                            continue
                    out[k] = v.to_bytes() \
                        if isinstance(v, wire.BulkReply) else v
                reply = {"_shm_objs": out}
            else:
                reply = wire.unwrap_bulk(reply)
        return wire.prepare_frame(
            conn, MSG_REPLY, rid, -1, [_dumps(reply)], key, mode,
            self.net_entity, entity)

    def _serve_conn(self, conn: socket.socket) -> None:
        shm_reader = None           # per-connection mapped client ring
        reply_ring = None           # shared daemon→client reply ring
        reply_key: Optional[str] = None   # registry key (client path)
        reply_toks: dict = {}       # (off, gen) -> ShmToken awaiting free
        reply_sg = False            # client understands MSG_REPLY_SG
        try:
            # deep kernel buffers: one pipelined client window should
            # land in as few recv syscalls as possible (syscalls are
            # the priced resource on the sandboxed hosts CI runs on)
            for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
                try:
                    conn.setsockopt(socket.SOL_SOCKET, opt, 1 << 21)
                except OSError:
                    pass
            try:
                entity, key = self._handshake(conn)
            except (cx.AuthError, wire.WireError, Exception) as e:
                self.auth_failures += 1
                try:
                    wire.send_frame(conn, Envelope(
                        MSG_AUTH_FAIL, 0, -1, str(e).encode()))
                except OSError:
                    pass
                return
            try:
                # the handshake-completion ack is un-MAC'd so a
                # rejected client can still read MSG_AUTH_FAIL's reason;
                # integrity comes from the authorizer + every
                # subsequent frame being MAC'd
                wire.send_frame(conn, Envelope(MSG_AUTH_OK, 0, -1, b""))
            except OSError:
                return
            mode = wire.MODE_SECURE
            # Buffered frame reads + coalesced replies: a pipelined
            # stream lands whole windows of requests in one recv, and
            # their replies leave in one sendmsg — on syscall-priced
            # hosts this is where the multi-stream path's throughput
            # lives.  Replies are FLUSHED before any read that could
            # block (a held reply + a blocked read is a distributed
            # deadlock with a window-limited client).
            rd = wire.SockReader(conn)
            out_blobs: list = []

            def _flush() -> None:
                if out_blobs:
                    wire._sendmsg_all(conn, out_blobs)
                    out_blobs.clear()

            while not self._stop.is_set():
                try:
                    env = rd.try_frame(session_key=key, mode=mode)
                    if env is None:
                        _flush()
                        env = rd.read_frame(session_key=key,
                                            mode=mode)
                except OSError:
                    # covers clean closes (WireClosed) AND rejected
                    # frames (WireError is an IOError == OSError):
                    # a poisoned frame (flip_bit) drops the
                    # connection, the client's retry path reconnects
                    return
                if env.type == wire.MSG_SET_MODE:
                    # authenticated data-mode downgrade (the ms_mode
                    # crc/secure negotiation): ack in the OLD mode —
                    # the client switches only after reading it.
                    # ``reply_sg`` advertises a reader that parses
                    # MSG_REPLY_SG bulk replies; legacy blocking
                    # clients never set it and keep typed replies.
                    blob = encoding.loads(env.payload)
                    want = blob.get("mode")
                    if want not in (wire.MODE_CRC, wire.MODE_SECURE):
                        return
                    reply_sg = bool(blob.get("reply_sg"))
                    try:
                        wire.send_frame(conn, Envelope(
                            MSG_REPLY, env.id, -1,
                            _dumps({"mode": want})),
                            session_key=key, src=self.net_entity,
                            dst=entity, mode=mode)
                    except OSError:
                        return
                    mode = want
                    continue
                if env.type == wire.MSG_SHM_ATTACH:
                    # same-host shared-memory lane negotiation: map
                    # the authenticated client's ring file, but ONLY
                    # from this daemon's own cluster directory — an
                    # arbitrary path from a (still authenticated)
                    # client must not make the daemon mmap foreign
                    # files.  Refusal is an ok=False ack: the client
                    # keeps the pure socket lane.
                    ok = False
                    ack: Dict[str, Any] = {}
                    try:
                        blob = encoding.loads(bytes(env.payload))
                        path = os.path.realpath(str(blob["path"]))
                        root = os.path.realpath(
                            os.path.dirname(self.sock_path))
                        if os.path.dirname(path) == root:
                            from ..msg.shm_ring import RingReader
                            if shm_reader is not None:
                                shm_reader.close()
                            shm_reader = RingReader(
                                path, int(blob["size"]))
                            ok = True
                        if ok and blob.get("reply") and \
                                crcutil.flag("wire_reply_ring"):
                            # RingReply: pair the client's request
                            # ring with a daemon-created reply ring
                            # (same size) and name it in the ack —
                            # same-host gets/recovery pulls go
                            # zero-copy BOTH directions
                            if reply_key is not None and \
                                    reply_key != path:
                                self._release_reply_ring(reply_key)
                                reply_ring = reply_key = None
                            if reply_key is None:
                                r = self._acquire_reply_ring(
                                    path, int(blob["size"]))
                                if r is not None:
                                    reply_ring, reply_key = r, path
                            if reply_ring is not None:
                                ack["reply_path"] = reply_ring.path
                                ack["reply_size"] = reply_ring.size
                    except (OSError, KeyError, ValueError, TypeError):
                        # (EncodingError is a ValueError)
                        # ANY malformed attach (non-dict blob, bad
                        # size type, undecodable payload) is a
                        # refusal, never a torn-down connection —
                        # the client just keeps the socket lane
                        ok = False
                        ack = {}
                    ack["ok"] = ok
                    try:
                        wire.send_frame(conn, Envelope(
                            MSG_REPLY, env.id, -1,
                            _dumps(ack)),
                            session_key=key, src=self.net_entity,
                            dst=entity, mode=mode)
                    except OSError:
                        return
                    continue
                if env.type == wire.MSG_SHM_FREE:
                    # reply-ring reclaim doorbell (rid 0, no reply):
                    # the client consumed these records — their
                    # extents may be reused.  Forge-proof and
                    # idempotent: only (off, gen) pairs THIS conn
                    # allocated resolve; anything else is a no-op.
                    try:
                        for m in encoding.loads(bytes(env.payload)):
                            tok = reply_toks.pop(
                                (int(m[0]), int(m[1])), None)
                            if tok is not None and \
                                    reply_ring is not None:
                                reply_ring.free(tok)
                    except (ValueError, TypeError, IndexError):
                        pass    # malformed free: conn-close reclaims
                    continue
                if env.type not in (MSG_REQ, wire.MSG_REQ_SG):
                    continue
                if faults.fire("net.partition", src=entity,
                               dst=self.net_entity) is not None:
                    # inbound half of a cut: the request frame never
                    # arrived — drop the connection, no reply (covers
                    # peers whose OWN registry is not armed: one
                    # process's arm severs both directions with it)
                    return
                if faults.fire("wire.inject_socket_failures",
                               service=self.service) is not None:
                    # drop the connection mid-op, no reply — the
                    # msgr-failure-injection suite axis, now a
                    # registry client (fire counts on perf("faults")).
                    # No option gate here: armed-or-not lives in the
                    # registry alone, so a runtime asok arm works on a
                    # daemon whose spec option was 0 (an arm that
                    # silently injected nothing would be exactly the
                    # CTL601 failure mode)
                    self.injected += 1
                    return
                try:
                    if env.type == wire.MSG_REQ_SG:
                        # scatter-gather request: bulk payload rides
                        # outside the typed encoding and lands back
                        # on the meta dict's "data" key — as a
                        # zero-copy view over the receive buffer,
                        # with the one-pass verify scan's TRUSTED
                        # sub-crcs alongside (the store consumes them
                        # as ready-made blob csums)
                        meta, data = wire.split_sg(env.payload)
                        req = encoding.loads(meta)
                        req["data"] = data
                        if env.csums is not None:
                            req["_csums"] = env.csums
                    else:
                        req = encoding.loads(bytes(env.payload))
                    shm_meta = req.pop("_shm", None) \
                        if isinstance(req, dict) else None
                    if shm_meta is not None:
                        # shared-memory doorbell: the payload lives
                        # in the client's mapped ring; resolve +
                        # verify it in ONE scan.  A poisoned record
                        # (flip_bit, torn, overwritten) is rejected
                        # like a corrupt socket frame — connection
                        # drop, never a delivered payload.
                        if shm_reader is None:
                            raise IOError(
                                "shm doorbell but no ring attached "
                                "on this connection")
                        try:
                            # receive verify through the device-crc
                            # gate: with wire_device_crc active the
                            # ring bytes are staged to HBM and
                            # checked by the GF(2) matmul — zero
                            # host scans; off/cpu = the counted
                            # host scan, same verdict either way
                            data, csums = shm_reader.read(
                                shm_meta, scanner=wire.receive_csums)
                        except wire.WireError as e:
                            raise _ShmPoisoned(str(e))
                        req["data"] = data
                        req["_csums"] = csums
                    reply = self.handler(entity, req)
                    err = None
                except _ShmPoisoned:
                    return
                except Exception as e:
                    reply, err = None, (type(e).__name__, str(e))
                try:
                    # reply direction carries its own src/dst: a
                    # oneway cut can apply the op yet lose the ack —
                    # the case session replay dedup exists for.
                    # Assembled (faultpoints fired per frame) but
                    # only flushed before a blocking read or past
                    # the batch bound — pipelined requests share one
                    # reply sendmsg.  Bulk replies route through the
                    # RingReply chokepoint (_reply_blobs): reply
                    # ring, MSG_REPLY_SG csum fold, or legacy typed.
                    if err is not None:
                        out_blobs.extend(wire.prepare_frame(
                            conn, wire.MSG_ERR, env.id, -1,
                            [_dumps(err)], key, mode,
                            self.net_entity, entity))
                    else:
                        out_blobs.extend(self._reply_blobs(
                            conn, env.id, reply, key, mode, entity,
                            reply_ring, reply_toks, reply_sg))
                    if sum(len(b) for b in out_blobs) >= (4 << 20):
                        _flush()
                except OSError:
                    return
        finally:
            if reply_key is not None:
                # extents whose reclaim doorbell never arrived
                # (client died mid-get, stream killed): freed here,
                # then this conn's ref dropped — the LAST serving
                # conn's release unlinks the ring file
                if reply_ring is not None:
                    for tok in reply_toks.values():
                        reply_ring.free(tok)
                self._release_reply_ring(reply_key)
            if shm_reader is not None:
                shm_reader.close()
            conn.close()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------- client ---

class WireClient:
    """Authenticated connection to one daemon (reconnects per call on
    failure are the caller's policy; this object is one session)."""

    def __init__(self, sock_path: str, entity: str, *,
                 secret: Optional[bytes] = None,
                 ticket: Optional[bytes] = None,
                 session_key: Optional[bytes] = None,
                 timeout: float = 10.0,
                 peer: Optional[str] = None,
                 mode: str = wire.MODE_SECURE):
        self.entity = entity
        # the peer's entity name, when the caller knows it: the
        # net.partition faultpoint severs (entity -> peer) traffic at
        # connect AND per request frame (asymmetric cuts can still
        # deliver the reverse direction)
        self.peer = peer
        if peer is not None and faults.fire(
                "net.partition", src=entity, dst=peer) is not None:
            raise wire.WireClosed(
                f"fault injected: {entity} -> {peer} partitioned "
                f"(connect refused)")
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(timeout)
        self.sock.connect(sock_path)
        wire.exchange_banners(self.sock)
        env = wire.recv_frame(self.sock)
        if env.type != MSG_AUTH_NONCE:
            raise wire.WireError("expected auth nonce")
        nonce = env.payload
        if ticket is not None:
            if session_key is None:
                raise ValueError("ticket mode needs the session key")
            self.key = session_key
            wire.send_frame(self.sock, Envelope(
                MSG_AUTH_TICKET, 0, -1, _dumps({
                    "ticket": ticket,
                    "authorizer": cx.make_authorizer(session_key, nonce),
                })))
        elif secret is not None:
            import hmac as _hmac
            proof = _hmac.new(secret, b"secret-proof" + nonce,
                              "sha256").digest()
            wire.send_frame(self.sock, Envelope(
                MSG_AUTH_SECRET, 0, -1,
                _dumps({"entity": entity, "proof": proof})))
            env = wire.recv_frame(self.sock)
            if env.type != MSG_AUTH_OK:
                raise cx.AuthError(env.payload.decode(errors="replace"))
            self.key = cx.unseal(secret, env.payload)
        else:
            raise ValueError("need secret or ticket")
        env = wire.recv_frame(self.sock)      # un-MAC'd completion ack
        if env.type == MSG_AUTH_FAIL:
            raise cx.AuthError(env.payload.decode(errors="replace"))
        if env.type != MSG_AUTH_OK:
            raise cx.AuthError("handshake rejected")
        self._id = 0
        self._lock = LockdepLock("wire.client", recursive=False)
        # buffered reply reads (one recv where hdr/payload/mac used
        # to take three syscalls); created after the handshake so no
        # handshake byte is ever buffered past a raw recv_frame
        self._rd = wire.SockReader(self.sock)
        self.mode = wire.MODE_SECURE
        if mode == wire.MODE_CRC:
            # authenticated downgrade to crc data mode (the
            # Stream._negotiate_crc contract): the request and its
            # ack travel sealed+MAC'd; only then do frames switch to
            # crc'd plaintext under header-only HMAC.  Required for
            # the one-pass handoff — only crc-mode SG frames carry
            # verify-derived trusted csums to the receiver's store.
            wire.send_frame(self.sock, Envelope(
                wire.MSG_SET_MODE, 0, -1,
                _dumps({"mode": wire.MODE_CRC})),
                session_key=self.key, src=self.entity, dst=self.peer)
            env = self._rd.read_frame(session_key=self.key)
            if env.type != MSG_REPLY:
                raise wire.WireError("mode negotiation rejected")
            self.mode = wire.MODE_CRC

    SG_MIN = wire.SG_MIN

    def call(self, req: Dict[str, Any]) -> Any:
        """One request/reply RTT.  A bulk ``data`` payload rides the
        scatter-gather frame tail (MSG_REQ_SG) — same one-pass
        integrity contract as the async streams (the shared
        wire.extract_bulk split): precomputed ``_csums`` fold into
        the frame crc with no sender scan, and the receiver's single
        verify scan hands trusted csums to its store.  This is the
        daemon->replica sub-write path, so without it every replica
        paid a second (store) scan."""
        req, data, csums = wire.extract_bulk(req, "peer_call")
        with self._lock:
            self._id += 1
            rid = self._id
            if data is not None:
                wire.send_frame_sg(self.sock, wire.MSG_REQ_SG, rid,
                                   _dumps(req), data,
                                   session_key=self.key,
                                   src=self.entity, dst=self.peer,
                                   mode=self.mode, data_csums=csums)
            else:
                wire.send_frame(self.sock, Envelope(MSG_REQ, rid, -1,
                                                    _dumps(req)),
                                session_key=self.key,
                                src=self.entity, dst=self.peer,
                                mode=self.mode)
            env = self._rd.read_frame(session_key=self.key,
                                      mode=self.mode)
        if env.type == MSG_ERR:
            wire.raise_reply_error(env.payload)
        return encoding.loads(env.payload)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# ------------------------------------------------------------- mon daemon ---

class MonDaemon:
    """Monitor process: durable map + config + auth ticket server.

    Serves (entity-checked): get_ticket, get_map, osd_boot,
    report_failure, mark_out, status, mon_status, config_get/set,
    health.

    Multi-mon (``n_mons`` > 1 in cluster.json): each rank runs a
    QuorumNode (cluster/mon_quorum.py) — elected leader, replicated
    commit over authenticated mon<->mon wire calls, per-rank durable
    store that replays the quorum log on restart.  Followers forward
    map mutations to the leader (the reference's peons do the same);
    reads serve the local committed state.  Reference:
    src/mon/Elector.h:37, Paxos.{h,cc}, MonitorDBStore.h.
    """

    MUTATIONS = ("osd_boot", "report_failure", "mark_out", "mark_in",
                 "pool_create", "pool_rm",
                 "pool_tier_add", "pool_tier_remove",
                 "pool_snap_create", "pool_snap_remove",
                 "osd_set_flag", "osd_unset_flag",
                 "config_set")

    def __init__(self, cluster_dir: str, rank: int = 0):
        self.dir = cluster_dir
        self.rank = rank
        spec = json.load(open(os.path.join(cluster_dir, "cluster.json")))
        self.spec = spec
        self.n_mons = int(spec.get("n_mons", 1))
        self.keyring = cx.Keyring.load(
            os.path.join(cluster_dir, "keyring.mon"))
        self.entity = f"mon.{rank}" if \
            f"mon.{rank}" in self.keyring.entries else "mon."
        # span attribution for cross-process trace assembly
        _trace.set_service("mon" if self.n_mons == 1
                           else f"mon.{rank}")
        self.tickets = cx.TicketServer(self.keyring)
        from .monitor import Monitor
        from .wal_kv import WalDB
        store = "mon-store" if self.n_mons == 1 else f"mon-store.{rank}"
        self.db = WalDB(os.path.join(cluster_dir, store),
                        fsync=bool(spec.get("fsync", True)))
        base = self._base_map()
        self.mon = Monitor.open(
            base, self.db,
            failure_reports_needed=spec.get("failure_reports_needed", 2))
        # markdown hysteresis (the osd_markdown_log role): wall-clock
        # windows on the process tier; 0 markdowns-to-hold = disabled
        self.mon.configure_flap_dampening(
            count=int(spec.get("osd_flap_markdown_count", 0)),
            window=float(spec.get("osd_flap_window", 60.0)),
            hold=float(spec.get("osd_flap_hold", 5.0)),
            hold_cap=float(spec.get("osd_flap_hold_cap", 30.0)))
        # RLock: the leader's propose path re-enters through the
        # quorum's local apply (handle -> commit_incremental ->
        # propose -> _commit_entry -> _apply_decree)
        self._lock = LockdepLock("mon.daemon")
        self._stop = threading.Event()
        self.quorum = None
        self._peer_mons: Dict[int, WireClient] = {}
        if self.n_mons > 1:
            from .mon_quorum import QuorumNode
            self.quorum = QuorumNode(
                rank, self.n_mons, self.db, self._apply_decree,
                self._send_peer_mon,
                lease_duration=float(spec.get("mon_lease", 2.0)))
            self.mon.set_proposer(self._propose_value)
            self.quorum.replay(0)      # idempotent re-apply after crash
        sock = os.path.join(cluster_dir, "mon.sock") \
            if self.n_mons == 1 else \
            os.path.join(cluster_dir, f"mon.{rank}.sock")
        self.server = WireServer(
            sock, "mon.", self.keyring, self._handle,
            secret_mode_keyring=self.keyring,
            inject_socket_failures=int(
                spec.get("ms_inject_socket_failures", 0)),
            # net.partition group name: must match what CLIENTS derive
            # from the socket basename ("mon.sock" -> "mon",
            # "mon.N.sock" -> "mon.N") — the keyring entity "mon."
            # would make single-mon cuts silently one-sided
            net_entity="mon" if self.n_mons == 1
            else f"mon.{rank}")
        # per-daemon admin socket (`ceph daemon mon.N ...` — the
        # AdminSocket surface: perf dump, config, tracked-op dumps)
        self.admin = AdminServer()
        self.admin.serve(os.path.join(
            cluster_dir, "mon.asok" if self.n_mons == 1
            else f"mon.{rank}.asok"))
        if self.n_mons > 1 and rank == 0:
            # back-compat alias: clients that only know "mon.sock"
            # reach rank 0 through a symlink
            alias = os.path.join(cluster_dir, "mon.sock")
            try:
                if os.path.islink(alias) or os.path.exists(alias):
                    os.unlink(alias)
                os.symlink(f"mon.{rank}.sock", alias)
            except OSError:
                pass
        if self.quorum is not None:
            threading.Thread(target=self._election_loop, daemon=True,
                             name=f"mon.{rank}-elect").start()

    # ------------------------------------------------------ quorum glue --
    def _peer_call(self, rank: int, req: Dict[str, Any]):
        c = self._peer_mons.get(rank)
        if c is None:
            c = WireClient(
                os.path.join(self.dir, f"mon.{rank}.sock"),
                self.entity,
                secret=self.keyring.secret(self.entity), timeout=3.0,
                peer=f"mon.{rank}")
            self._peer_mons[rank] = c
        try:
            return c.call(req)
        except (OSError, IOError):
            self._peer_mons.pop(rank, None)
            try:
                c.close()
            except Exception:
                pass
            raise

    def _send_peer_mon(self, rank: int, msg: Dict[str, Any]):
        return self._peer_call(rank, {"cmd": "quorum", "msg": msg})

    def _apply_decree(self, version: int, blob: bytes) -> None:
        """Commit path on every rank (idempotent: replay after crash
        re-applies only what the service lacks)."""
        from .mon_quorum import decode_decree
        from .monitor import Monitor
        d = decode_decree(blob)
        with self._lock:      # followers apply off quorum threads
            if d["kind"] == "osdmap":
                inc = Monitor._inc_from_json(d["inc"].encode())
                if inc.epoch <= self.mon.osdmap.epoch:
                    return
                self.mon.apply_committed_incremental(inc)
            elif d["kind"] == "config":
                self.mon.apply_committed_config(d["key"], d["value"])

    def _propose_value(self, value) -> bool:
        from .mon_quorum import encode_decree
        from .monitor import Monitor
        if value[0] == "osdmap":
            blob = encode_decree(
                "osdmap", inc=Monitor._inc_json(value[1]).decode())
        else:
            blob = encode_decree("config", key=value[1], value=value[2])
        return self.quorum.propose(blob)

    def _election_loop(self, interval: float = 0.4) -> None:
        """Leader liveness + election trigger.  Rank-staggered retry
        delays bias low ranks to win (ElectionLogic's rank preference
        without the deferral subprotocol).  Every protocol call is
        guarded: a peer dying mid-election (e.g. between granting a
        vote and serving the catch-up fetch) must not kill this
        thread — the loop IS the retry mechanism."""
        time.sleep(0.05 + 0.15 * self.rank)
        while not self._stop.is_set():
            q = self.quorum
            lead = q.leader
            try:
                if lead is None:
                    q.start_election()
                elif lead == self.rank:
                    # leader: extend the read lease on a majority each
                    # round (Paxos::extend_lease).  A leader that can
                    # no longer reach a majority (netsplit minority)
                    # fails here, its own lease expires, and its map
                    # reads stall instead of serving stale state.
                    q.extend_lease()
                elif lead != self.rank:
                    try:
                        self._send_peer_mon(lead, {"q": "ping"})
                    except Exception:
                        with self._lock:
                            if q.leader == lead:
                                q.leader = None
                        time.sleep(0.05 + 0.15 * self.rank)
                        q.start_election()
            except Exception as e:
                from ..common.log import dout
                dout("mon", 5, f"mon.{self.rank} election round "
                               f"failed: {e!r}")
            time.sleep(interval)

    def _base_map(self):
        from ..placement.compiler import compile_crushmap
        from .osdmap import OSDMap, PGPool
        cmap = compile_crushmap(
            open(os.path.join(self.dir, "crushmap.txt")).read())
        m = OSDMap(cmap)
        m.mark_all_in_up()
        for p in self.spec["pools"]:
            m.add_pool(PGPool(**p))
        return m

    def map_blob(self) -> Dict[str, Any]:
        from ..placement.compiler import decompile_crushmap
        m = self.mon.osdmap
        # pools come from the LIVE map (committed incrementals create
        # and remove them at runtime), not the static bootstrap spec
        pools = [{"id": p.id, "name": p.name, "type": p.type,
                  "size": p.size, "min_size": p.min_size,
                  "pg_num": p.pg_num, "crush_rule": p.crush_rule,
                  "erasure_code_profile": p.erasure_code_profile,
                  "stripe_unit": p.stripe_unit,
                  "tier_of": p.tier_of, "read_tier": p.read_tier,
                  "write_tier": p.write_tier,
                  "cache_mode": p.cache_mode}
                 for p in m.pools.values()]
        return {
            "epoch": m.epoch,
            "crush_text": decompile_crushmap(m.crush),
            "pools": pools,
            "flags": sorted(m.flags),
            "pool_id_max": m.pool_id_max,
            "osd_up": [bool(v) for v in m.osd_up[:m.max_osd]],
            "osd_weight": [int(v) for v in m.osd_weight[:m.max_osd]],
            "addrs": {str(i): os.path.join(self.dir, f"osd.{i}.sock")
                      for i in range(m.max_osd)},
            "mons": ([os.path.join(self.dir, "mon.sock")]
                     if self.n_mons == 1 else
                     [os.path.join(self.dir, f"mon.{r}.sock")
                      for r in range(self.n_mons)]),
            "pool_snaps": {
                str(p["id"]): (self.mon.config_get(
                    f"pool.{p['id']}.snaps") or
                    {"seq": 0, "snaps": {}})
                for p in pools},
        }

    def _osd_probe(self, osd: int, req: Dict[str, Any]) -> Any:
        """One short-lived authenticated mon -> OSD call (the mon
        holds every service secret, so it mints its own ticket)."""
        ticket, key_box = self.tickets.grant(self.entity,
                                             f"osd.{osd}")
        key = cx.open_key_box(self.keyring.secret(self.entity),
                              key_box)
        c = WireClient(os.path.join(self.dir, f"osd.{osd}.sock"),
                       self.entity, ticket=ticket, session_key=key,
                       timeout=2.0, peer=f"osd.{osd}")
        try:
            return c.call(req)
        finally:
            c.close()

    def _count_pool_objects(self, pool_id: int) -> int:
        """Best-effort object count for one pool across the OSDs
        (replica-counted — callers gate on nonzero, not the value).
        An OSD that cannot be checked — marked down, or up but
        unreachable — counts as holding data: a safety gate must not
        read 'cannot check' as 'empty' (a down OSD may hold the only
        copies of acknowledged cache writes; ``force`` is the
        operator override)."""
        m = self.mon.osdmap
        total = 0
        for osd in range(m.max_osd):
            if not m.osd_exists[osd]:
                continue
            if not m.osd_up[osd]:
                total += 1      # down holder is unverifiable: blocks
                continue
            try:
                total += int(self._osd_probe(
                    osd, {"cmd": "count_pool", "pool": pool_id}))
            except (OSError, IOError, cx.AuthError):
                total += 1      # unverifiable holder blocks the gate
        return total

    def _forward_to_leader(self, entity: str,
                           req: Dict[str, Any]) -> Any:
        lead = self.quorum.leader
        if lead is None:
            raise IOError("mon quorum has no leader (election pending)")
        fwd = dict(req)
        fwd["fwd_entity"] = entity
        return self._peer_call(lead, {"cmd": "_forwarded",
                                      "req": fwd})["reply"]

    def _handle(self, entity: str, req: Dict[str, Any]) -> Any:
        cmd = req["cmd"]
        if cmd == "quorum":
            # mon<->mon consensus traffic only
            if not entity.startswith("mon."):
                raise cx.AuthError(f"{entity} may not speak quorum")
            return self.quorum.handle(req["msg"])
        if cmd == "mon_status":
            q = self.quorum
            return {"rank": self.rank, "n_mons": self.n_mons,
                    "leader": None if q is None else q.leader,
                    "election_epoch":
                        0 if q is None else q.election_epoch,
                    "committed": 0 if q is None else q.committed,
                    "readable": True if q is None else q.readable(),
                    "epoch": self.mon.osdmap.epoch}
        if cmd == "_forwarded":
            # leader-side unwrap of a peon-forwarded mutation: the
            # peon (a mon) asserts the original requester identity
            if not entity.startswith("mon."):
                raise cx.AuthError(f"{entity} may not forward")
            inner = dict(req["req"])
            orig = inner.pop("fwd_entity")
            return {"reply": self._handle(orig, inner)}
        if (self.quorum is not None and
                cmd in self.MUTATIONS + ("report_slow_ops", "health",
                                         "report_store_health",
                                         "report_perf",
                                         "cluster_stats",
                                         "balancer_eval")
                and self.quorum.leader != self.rank):
            # slow-op/perf rollup state is leader-local (transient
            # health + stats, not a quorum decree): reports AND their
            # queries both forward so they meet on the same mon no
            # matter which socket each caller happened to connect to
            return self._forward_to_leader(entity, req)
        drain_count = None
        if cmd == "pool_tier_remove" and \
                not bool(req.get("force", False)):
            # the OSD drain probes run OUTSIDE the mon lock: one
            # 2s-timeout wire call per OSD would otherwise stall
            # every other handler (heartbeats, boots, map fetches)
            # behind a single admin command.  The unlocked osdmap
            # reads are benign (worst case a stale up view — probes
            # fail conservative); existence/relationship are checked
            # FIRST so an invalid request fails instantly instead of
            # paying the probe sweep, and re-validated under the
            # lock before committing.
            m0 = self.mon.osdmap
            b0 = m0.pools.get(int(req["base"]))
            c0 = m0.pools.get(int(req["cache"]))
            if b0 is None or c0 is None:
                raise ValueError("tier remove: no such pool")
            if b0.read_tier != int(req["cache"]) or \
                    c0.tier_of != int(req["base"]):
                raise ValueError(
                    f"tier remove: pool {req['cache']} is not a "
                    f"tier of pool {req['base']}")
            drain_count = self._count_pool_objects(int(req["cache"]))
        with self._lock:
            if cmd == "report_slow_ops":
                # daemonized OSDs roll their OpTracker slow-op
                # summaries up into this mon's SLOW_OPS health check
                # (the reference mon's per-daemon health report
                # ingestion); under _lock — wire handlers run on
                # per-connection threads
                if not entity.startswith("osd."):
                    raise cx.AuthError(
                        f"{entity} may not report slow ops")
                self.mon.record_daemon_slow_ops(
                    entity, req.get("summary") or {})
                return {"ok": True}
            if cmd == "report_store_health":
                # boot-fsck damage rollup (STORE_DAMAGED): transient
                # leader-local health state like the slow-op reports
                if not entity.startswith("osd."):
                    raise cx.AuthError(
                        f"{entity} may not report store health")
                self.mon.record_store_damage(
                    entity, int(req.get("errors", 0)),
                    repaired=int(req.get("repaired", 0)))
                return {"ok": True}
            if cmd == "report_perf":
                # ClusterTelemetry stats ingestion (the mgr-module
                # PGMap/prometheus role): each daemon's heartbeat
                # ships its perf counters, OpTracker log2 histograms
                # and store utilization; the leader-local aggregator
                # merges them into cluster p50/p99/p999, io rates and
                # per-OSD utilization (leader-local like slow ops)
                if not (entity.startswith("osd.") or
                        entity.startswith("client.")):
                    raise cx.AuthError(
                        f"{entity} may not report perf")
                # reports are attributed to the AUTHENTICATED wire
                # entity, never a caller-chosen name — a client must
                # not be able to overwrite osd.0's utilization row
                self.mon.record_daemon_perf(
                    entity, req.get("report") or {})
                return {"ok": True}
            if cmd == "cluster_stats":
                # the aggregated cluster view (`ceph -s` io lines,
                # `ceph df`, `ceph osd df`, the cluster Prometheus
                # scrape text when {"metrics": True}), plus the
                # ClusterScope sub-queries: {"history": {...}} range-
                # queries the leader's metrics-history rings (`ceph
                # telemetry history`) and {"heat": {...}} merges the
                # per-OSD PG heat tables (`ceph pg heat`)
                cs = self.mon.cluster_stats
                hq = req.get("history")
                if hq is not None:
                    return cs.history.query(
                        str(hq.get("counter", "osd.io.wr_ops")),
                        daemon=hq.get("daemon"),
                        since=hq.get("since"),
                        until=hq.get("until"))
                heat_q = req.get("heat")
                if heat_q is not None:
                    pool = heat_q.get("pool")
                    top = heat_q.get("top")
                    return {
                        "pgs": cs.pg_heat(
                            pool=None if pool is None else int(pool),
                            top=None if top is None else int(top)),
                        "osds": cs.osd_heat(),
                    }
                out = cs.dump()
                if bool(req.get("metrics", False)):
                    out["prometheus"] = cs.render_prometheus()
                return out
            if cmd == "balancer_eval":
                # ClusterScope balancer ADVISOR: score the current
                # mapping from heat x utilization history and propose
                # upmap moves as a REPORT — dry-run only, nothing here
                # may touch the osdmap (asserted: epoch unchanged)
                from ..mgr.balancer_advisor import evaluate
                om = self.mon.osdmap
                epoch0 = om.epoch
                out = evaluate(
                    om, self.mon.cluster_stats,
                    max_moves=int(req.get("max_moves", 8)),
                    pool=req.get("pool"))
                assert om.epoch == epoch0, \
                    "balancer advisor mutated the osdmap"
                return out
            if cmd == "health":
                # PG_DEGRADED needs the batched mapper (a compile in
                # this daemon) — opt-in via {"pgs": True}
                checks = self.mon.health(
                    include_pg_state=bool(req.get("pgs", False)))
                worst = "HEALTH_OK"
                if any(c.severity == "HEALTH_ERR" for c in checks):
                    worst = "HEALTH_ERR"
                elif checks:
                    worst = "HEALTH_WARN"
                return {"status": worst,
                        "checks": [{"code": c.code,
                                    "severity": c.severity,
                                    "summary": c.summary}
                                   for c in checks]}
            if cmd == "get_ticket":
                service = req["service"]
                ticket, key_box = self.tickets.grant(entity, service)
                return {"ticket": ticket, "key_box": key_box}
            if cmd == "get_map":
                if self.quorum is not None and \
                        not self.quorum.readable():
                    # minority-side mon: the read lease expired and a
                    # majority may be committing epochs this rank
                    # cannot see — STALL (IOError = retryable) rather
                    # than serve a stale map as fresh; the client's
                    # mon failover rotates to a majority mon
                    raise IOError(
                        f"{self.entity}: no quorum read lease "
                        f"(possible minority partition) — map reads "
                        f"stalled, retry another mon")
                return self.map_blob()
            if cmd == "osd_boot":
                osd = int(req["osd"])
                if entity != f"osd.{osd}":
                    raise cx.AuthError(
                        f"{entity} cannot boot osd.{osd}")
                if not self.mon.osd_boot(osd):
                    # flap dampening: a markdown-storm OSD is HELD
                    # down for its backoff; the daemon's heartbeat
                    # keeps re-announcing and eventually lands
                    return {"epoch": self.mon.osdmap.epoch,
                            "held": True,
                            "hold": self.mon.flap_status(osd)}
                return {"epoch": self.mon.osdmap.epoch}
            if cmd == "osd_set_flag":
                if not self.mon.set_flag(str(req["flag"]), True):
                    raise IOError("set flag: no quorum")
                return {"epoch": self.mon.osdmap.epoch,
                        "flags": sorted(self.mon.osdmap.flags)}
            if cmd == "osd_unset_flag":
                if not self.mon.set_flag(str(req["flag"]), False):
                    raise IOError("unset flag: no quorum")
                return {"epoch": self.mon.osdmap.epoch,
                        "flags": sorted(self.mon.osdmap.flags)}
            if cmd == "report_failure":
                if not entity.startswith("osd."):
                    raise cx.AuthError("only OSDs report failures")
                marked = self.mon.report_failure(int(req["target"]),
                                                 int(entity[4:]))
                return {"marked_down": marked,
                        "epoch": self.mon.osdmap.epoch}
            if cmd == "mark_out":
                inc = self.mon.next_incremental()
                inc.new_weight[int(req["osd"])] = 0
                if not self.mon.commit_incremental(inc):
                    # IOError = retryable at the client (mon_call
                    # backs off and retries/rotates): a quorum round
                    # that transiently failed must NOT ack with an
                    # unchanged epoch as if it committed
                    raise IOError("mark_out: no quorum")
                return {"epoch": self.mon.osdmap.epoch}
            if cmd == "mark_in":
                inc = self.mon.next_incremental()
                inc.new_weight[int(req["osd"])] = 0x10000
                if not self.mon.commit_incremental(inc):
                    raise IOError("mark_in: no quorum")
                return {"epoch": self.mon.osdmap.epoch}
            if cmd == "pool_create":
                # `ceph osd pool create` (OSDMonitor::prepare_new_pool):
                # the new pool rides one committed incremental, so every
                # map subscriber learns it atomically
                m = self.mon.osdmap
                spec = {"name": req["name"],
                        "type": int(req.get("type", 1)),
                        "size": int(req.get("size", 3)),
                        "min_size": int(req.get("min_size", 2)),
                        "pg_num": int(req.get("pg_num", 16)),
                        "crush_rule": int(req.get("crush_rule", 0)),
                        "erasure_code_profile":
                            req.get("erasure_code_profile", "")}
                existing = next((p for p in m.pools.values()
                                 if p.name == req["name"]), None)
                if existing is not None:
                    # idempotent on an identical spec (a retried
                    # request whose reply was lost must not report a
                    # committed create as failed); a DIFFERENT spec
                    # under the same name is a genuine conflict
                    same = all(getattr(existing, k) == v
                               for k, v in spec.items())
                    if same:
                        return {"pool_id": existing.id,
                                "epoch": m.epoch, "existed": True}
                    raise ValueError(
                        f"pool {req['name']!r} already exists "
                        "with a different spec")
                # NEVER reuse a deleted pool's id (data exposure:
                # surviving objects/snap state would leak into the
                # new pool) — allocate past the high-water mark
                pid = max(m.pool_id_max, max(m.pools, default=0)) + 1
                inc = self.mon.next_incremental()
                inc.new_pools[pid] = spec
                if not self.mon.commit_incremental(inc):
                    raise IOError("pool create: no quorum")
                return {"pool_id": pid, "epoch": m.epoch,
                        "existed": False}
            if cmd == "pool_rm":
                m = self.mon.osdmap
                pid = next((p.id for p in m.pools.values()
                            if p.name == req["name"]), None)
                if pid is None:
                    # idempotent: a retried rm whose first reply was
                    # lost already succeeded
                    return {"pool_id": None, "epoch": m.epoch,
                            "existed": False}
                inc = self.mon.next_incremental()
                inc.old_pools.append(pid)
                if not self.mon.commit_incremental(inc):
                    raise IOError("pool rm: no quorum")
                # the dead pool's committed snap state goes with it
                self.mon.config_set(f"pool.{pid}.snaps",
                                    {"seq": 0, "snaps": {}})
                return {"pool_id": pid, "epoch": m.epoch,
                        "existed": True}
            if cmd == "pool_tier_add":
                # 'osd tier add base cache + cache-mode writeback'
                # (OSDMonitor prepare_command tier add role): tier
                # wiring is committed MAP state, a quorum incremental
                m = self.mon.osdmap
                base, cache = int(req["base"]), int(req["cache"])
                mode = req.get("mode", "writeback")
                if mode != "writeback":
                    raise ValueError(
                        f"cache mode {mode!r} not implemented "
                        f"(writeback only)")
                if base == cache:
                    raise ValueError("tier add: base == cache")
                if base not in m.pools or cache not in m.pools:
                    raise ValueError("tier add: no such pool")
                if m.pools[cache].type != 1:     # POOL_REPLICATED
                    raise ValueError(
                        "cache tier must be a replicated pool")
                if m.pools[base].type != 1:
                    # whole-object COPY_FROM would read one EC shard
                    # as the object; refuse rather than corrupt
                    raise ValueError(
                        "tiering over an EC base pool unsupported")
                if m.pools[base].read_tier >= 0 or \
                        m.pools[base].tier_of >= 0 or \
                        m.pools[cache].tier_of >= 0 or \
                        m.pools[cache].read_tier >= 0:
                    # no re-tiering and no tier CHAINS
                    raise ValueError("tier add: pool already tiered")
                snaps = self.mon.config_get(
                    f"pool.{base}.snaps") or {}
                if snaps.get("snaps") or m.pools[base].snaps:
                    # tier routing would run COW against the cache
                    # pool's empty snap context and skip clones (the
                    # snap SEQ may outlive deleted snapshots; only
                    # LIVE snapshots make tiering unsafe)
                    raise ValueError(
                        "tiering over a snapshotted pool unsupported")
                inc = self.mon.next_incremental()
                inc.new_pool_tier[cache] = {"tier_of": base,
                                            "cache_mode": mode}
                inc.new_pool_tier[base] = {"read_tier": cache,
                                           "write_tier": cache}
                if not self.mon.commit_incremental(inc):
                    raise IOError("tier add: no quorum")
                return {"epoch": self.mon.osdmap.epoch}
            if cmd == "pool_tier_remove":
                # server-side gate (OSDMonitor 'osd tier remove'
                # role): the mon — the commit point — verifies the
                # tier RELATIONSHIP and that the cache pool is
                # drained, closing the TOCTOU where only the client
                # checked and a racing write could strand
                # acknowledged data out of the read path
                m = self.mon.osdmap
                base, cache = int(req["base"]), int(req["cache"])
                bp, cp = m.pools.get(base), m.pools.get(cache)
                if bp is None or cp is None:
                    raise ValueError("tier remove: no such pool")
                if bp.read_tier != cache or cp.tier_of != base:
                    raise ValueError(
                        f"tier remove: pool {cache} is not a tier "
                        f"of pool {base}")
                if drain_count is not None:
                    held = drain_count
                    if held:
                        # IOError: surfaces as IOError at the client
                        # (retryable operator condition, like the
                        # no-quorum refusal), unlike the ValueError
                        # config mistakes above
                        raise IOError(
                            f"tier remove: cache pool still holds "
                            f"~{held} objects (down/unreachable "
                            f"daemons count as holding) — drain "
                            f"first (tier_agent_work + evict), or "
                            f"force")
                inc = self.mon.next_incremental()
                inc.new_pool_tier[cache] = {"tier_of": -1,
                                            "cache_mode": ""}
                inc.new_pool_tier[base] = {"read_tier": -1,
                                           "write_tier": -1}
                if not self.mon.commit_incremental(inc):
                    raise IOError("tier remove: no quorum")
                return {"epoch": self.mon.osdmap.epoch}
            if cmd == "pool_snap_create":
                # pool snapshot state is COMMITTED mon state (the
                # pg_pool_t::snap_seq + snaps role, committed through
                # the quorum's config decree path)
                pid = int(req["pool"])
                if self.mon.osdmap.pools.get(pid) is not None and \
                        self.mon.osdmap.pools[pid].write_tier >= 0:
                    raise ValueError(
                        "pool snapshots on a tiered base pool "
                        "unsupported")
                cur = self.mon.config_get(f"pool.{pid}.snaps") or \
                    {"seq": 0, "snaps": {}}
                # retry-idempotent (mon_call resends after a lost
                # reply): an already-present name returns its existing
                # seq instead of minting a duplicate id
                for s, n in cur["snaps"].items():
                    if n == req["name"]:
                        return {"snap_seq": int(s)}
                seq = int(cur["seq"]) + 1
                snaps = dict(cur["snaps"])
                snaps[str(seq)] = req["name"]
                if not self.mon.config_set(
                        f"pool.{pid}.snaps",
                        {"seq": seq, "snaps": snaps}):
                    raise IOError("snap create: no quorum")
                return {"snap_seq": seq}
            if cmd == "pool_snap_remove":
                pid = int(req["pool"])
                cur = self.mon.config_get(f"pool.{pid}.snaps") or \
                    {"seq": 0, "snaps": {}}
                snaps = {s: n for s, n in cur["snaps"].items()
                         if n != req["name"]}
                if not self.mon.config_set(
                        f"pool.{pid}.snaps",
                        {"seq": int(cur["seq"]), "snaps": snaps}):
                    raise IOError("snap remove: no quorum")
                return {"snaps": snaps}
            if cmd == "pool_snap_ls":
                pid = int(req["pool"])
                return self.mon.config_get(f"pool.{pid}.snaps") or \
                    {"seq": 0, "snaps": {}}
            if cmd == "config_set":
                # central config db (ConfigMonitor role): committed
                # through the quorum's decree path like every other
                # mon mutation
                if not self.mon.config_set(req["key"], req["value"]):
                    raise IOError("config set: no quorum")
                return {"ok": True}
            if cmd == "config_get":
                return {"value": self.mon.config_get(req["key"])}
            if cmd == "status":
                m = self.mon.osdmap
                return {"epoch": m.epoch,
                        "n_up": int(sum(m.osd_up[:m.max_osd])),
                        "n_osds": m.max_osd}
            raise ValueError(f"unknown mon command {cmd!r}")

    def run_forever(self) -> None:
        try:
            while True:
                time.sleep(0.5)
        except KeyboardInterrupt:
            pass


# ------------------------------------------------------------- osd daemon ---

class OSDDaemon:
    """OSD process: durable FileStore + scheduler + wire server +
    heartbeats + replicated fan-out + primary recovery."""

    def __init__(self, osd_id: int, cluster_dir: str):
        self.id = osd_id
        self.dir = cluster_dir
        self.entity = f"osd.{osd_id}"
        # span attribution for cross-process trace assembly
        _trace.set_service(self.entity)
        self.keyring = cx.Keyring.load(
            os.path.join(cluster_dir, f"keyring.osd.{osd_id}"))
        spec = json.load(open(os.path.join(cluster_dir, "cluster.json")))
        store_path = os.path.join(cluster_dir, f"osd.{osd_id}.store")
        # objectstore backend selection (the reference's osd_objectstore
        # option, src/common/options.cc): bluestore is the flagship
        # block-device extent store, filestore the log-structured one
        backend = spec.get("objectstore", "bluestore")
        # daemons skip the full csum walk at mount by default (the
        # reference ships bluestore_fsck_on_mount=false: restart
        # latency must not scale with store size); opt in via the spec
        fsck_on_mount = bool(spec.get("fsck_on_mount", False))
        if backend == "bluestore":
            from .bluestore import BlueStore
            self.store = BlueStore(
                store_path, fsync=bool(spec.get("fsync", True)),
                device_bytes=int(spec.get("bluestore_device_bytes",
                                          1 << 28)),
                min_alloc=int(spec.get("bluestore_min_alloc_size",
                                       4096)),
                compression=spec.get(
                    "bluestore_compression_algorithm") or None,
                fsck_on_mount=fsck_on_mount)
        elif backend == "memstore":
            from .objectstore import MemStore
            self.store = MemStore()
        else:
            from .filestore import FileStore
            self.store = FileStore(
                store_path, fsync=bool(spec.get("fsync", True)),
                fsck_on_mount=fsck_on_mount)
        # power-loss boot fsck (the CrashDev pipeline): a BlockDevice
        # power cut dropped a POWER_LOSS marker in the store tree —
        # quarantine torn objects BEFORE serving (fsck repair=True
        # drops their onode rows; peering recovery re-replicates) and
        # report the count up the heartbeat so the mon raises
        # STORE_DAMAGED.  The count clears on a later clean fsck
        # (`ceph daemon osd.N store_fsck [repair]`).
        from .blockdev import (clear_power_loss_markers,
                               power_loss_markers)
        self.store_fsck_errors = 0
        self.store_fsck_repaired = 0
        self._store_reported = 0
        if power_loss_markers(store_path):
            bad = self.store.fsck(repair=True)
            self.store_fsck_errors = len(bad)
            self.store_fsck_repaired = len(bad)
            clear_power_loss_markers(store_path)
        from ..common.options import config as _config
        from ..msg.scheduler import MClockScheduler, QoS, tenant_class
        cfg = _config()
        lim = float(cfg.get("osd_mclock_scheduler_client_lim"))
        self.sched = MClockScheduler(tenant_default=QoS(
            reservation=float(
                cfg.get("osd_mclock_scheduler_client_res")),
            weight=float(cfg.get("osd_mclock_scheduler_client_wgt")),
            limit=lim if lim > 0 else float("inf")))
        # per-tenant QoS overrides from the cluster spec (the
        # osd_mclock_scheduler_client_* per-client profiles): tenants
        # named here get their own (r, w, l); unnamed tenants vivify
        # with the config defaults above
        for t, q in (spec.get("qos_tenants") or {}).items():
            tlim = float(q.get("lim", 0.0))
            self.sched.set_qos(tenant_class(t), QoS(
                reservation=float(q.get("res", 0.0)),
                weight=float(q.get("wgt", 1.0)),
                limit=tlim if tlim > 0 else float("inf")))
        self._sched_lock = LockdepLock("osd.sched", recursive=False)
        # durable per-PG op logs (process-tier PGLog, daemon_pglog.py)
        from .daemon_pglog import DurablePGLog
        self._pglogs: Dict[Tuple[int, int], DurablePGLog] = {}
        self._pglog_lock = LockdepLock("osd.pglog", recursive=False)
        # per-PG write serialization (the reference's PG lock): version
        # assignment + log append + apply must be atomic per PG across
        # the thread-per-connection wire server
        self._pg_locks: Dict[Tuple[int, int], LockdepLock] = {}
        self._peers: Dict[int, WireClient] = {}
        self._peer_lock = LockdepLock("osd.peer", recursive=False)
        self._mon: Optional[WireClient] = None
        self._map: Dict[str, Any] = {}
        self._stop = threading.Event()
        # watch/notify state (src/osd/Watch.cc role): in-memory and
        # connection-equivalent — watches die with the daemon, exactly
        # as the reference's die with the session; clients re-register
        self._watch_lock = LockdepLock("osd.watch", recursive=False)
        self._watchers: Dict[Tuple, Dict[int, list]] = {}
        self._watch_next = 1
        self._notify_state: Dict[int, Dict[str, Any]] = {}
        # in-OSD object classes (ClassHandler, shared with the sim)
        self._class_handler = None
        self.server = WireServer(
            os.path.join(cluster_dir, f"osd.{osd_id}.sock"),
            self.entity, self.keyring, self._handle,
            inject_socket_failures=int(
                spec.get("ms_inject_socket_failures", 0)))
        # per-daemon admin socket (`ceph daemon osd.N dump_historic_ops
        # | perf dump | ...` — each OSD process owns its tracker state;
        # instantiate the tracker eagerly so its perf group and dump
        # surfaces exist before the first tracked op arrives)
        _op_tracker()
        self.admin = AdminServer()
        # `ceph daemon osd.N store_fsck [repair]` — the on-demand
        # store consistency walk (and the operator's way to clear a
        # STORE_DAMAGED report after recovery healed the quarantine)
        self.admin.register("store_fsck", self._admin_store_fsck)
        self.admin.serve(os.path.join(cluster_dir,
                                      f"osd.{osd_id}.asok"))
        self._hb_misses: Dict[int, int] = {}
        self._slow_reported = 0       # last slow-op count sent to mon
        # messenger sessions (the reference's Session + pg-log reqid
        # dup detection, collapsed to one table): a client carries a
        # session id + per-session op seq across RECONNECTS, so a
        # write whose reply was lost to a cut/drop is replayed and
        # applied AT MOST ONCE — the replay returns the cached reply.
        # (entity, sid) -> {"last": applied seq high-water,
        #                   "replies": {seq: reply}, "touched": ts}
        self._session_lock = LockdepLock("osd.sessions",
                                         recursive=False)
        self._sessions: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.session_resets = 0       # unknown-sid resumes observed
        self._pc_session = _perf("osd.session")
        # OUTBOUND peer sessions: this daemon's own (session, seq)
        # stamps for mutating peer traffic (replica sub-writes,
        # recovery pushes), so the receiving daemon's dup table
        # covers daemon->daemon mutations with the same at-most-once
        # contract clients get — _peer_req is the stamping chokepoint
        # (lint CTL802)
        self._peer_sess_lock = LockdepLock("osd.peer_sessions",
                                           recursive=False)
        self._peer_sessions: Dict[int, Dict[str, Any]] = {}
        # io accounting (the osd_perf_counters rd/wr families): the
        # ClusterStats aggregator turns successive heartbeat reports
        # of these into per-OSD/per-pool io rates for `ceph -s`
        self._pc_io = _perf("osd.io")
        self._perf_reported = 0.0     # last report_perf wall time
        # per-PG client heat (pool HitSet role), counted at the same
        # _account_io chokepoint as the osd.io counters so the mon's
        # heat<->osd.io agreement check holds; wall clock on this tier
        from .pg_heat import PGHeatTracker
        from .osd_service import _heat_half_life
        self.heat = PGHeatTracker(half_life=_heat_half_life(),
                                  clock=time.time)
        # recovery/backfill reservations (the reference's AsyncReserver
        # pair + osd_max_backfills): LOCAL = this OSD driving a PG's
        # recovery as primary, REMOTE = this OSD receiving a recovery/
        # backfill stream as member/target.  Held counts are capped by
        # osd_max_backfills; peaks are exposed on `status` so chaos
        # tests can assert the cap was never exceeded.
        # slots are LEASES (grant timestamps), not bare counters: a
        # holder that dies mid-recovery (primary kill9 between
        # reserve and release, a client crash, a lost grant reply
        # re-executed by the one-shot stream retry) would otherwise
        # leak its slot until this daemon restarts and wedge every
        # later recovery under the cap — expired grants purge on the
        # next reserve/release/status touch
        self._resv_lock = LockdepLock("osd.resv", recursive=False)
        self._resv: Dict[str, List[float]] = {"local": [],
                                              "remote": []}
        self._resv_peak = {"local": 0, "remote": 0}
        self._pc_resv = _perf("osd.recovery")

    _RESV_TTL_S = 60.0

    def _resv_purge(self, role: str) -> None:
        """Drop expired leases (caller holds _resv_lock)."""
        floor = time.monotonic() - self._RESV_TTL_S
        ts = self._resv[role]
        expired = 0
        while ts and ts[0] < floor:
            ts.pop(0)
            expired += 1
        if expired:
            self._pc_resv.inc(f"{role}_expired", expired)

    def _resv_held(self) -> Dict[str, int]:
        with self._resv_lock:
            for role in self._resv:
                self._resv_purge(role)
            return {r: len(ts) for r, ts in self._resv.items()}

    # ----------------------------------------------------------- mon I/O --
    def _mon_socks(self) -> List[str]:
        return mon_sockets(self.dir)

    def mon_client(self) -> WireClient:
        """Any live mon will do (mutations forward to the leader
        server-side); fail over across the quorum."""
        if self._mon is None:
            last: Optional[Exception] = None
            for sock in self._mon_socks():
                mon_ent = os.path.basename(sock)[:-len(".sock")]
                try:
                    self._mon = WireClient(
                        sock, self.entity,
                        secret=self.keyring.secret(self.entity),
                        peer=mon_ent)
                    break
                except (OSError, IOError, cx.AuthError) as e:
                    last = e
            if self._mon is None:
                raise IOError(f"no mon reachable: {last}")
        return self._mon

    def peer_client(self, osd: int) -> WireClient:
        with self._peer_lock:
            c = self._peers.get(osd)
            if c is not None:
                return c
        mon = self.mon_client()
        grant = mon.call({"cmd": "get_ticket",
                          "service": f"osd.{osd}"})
        key = cx.open_key_box(self.keyring.secret(self.entity),
                              grant["key_box"])
        from ..common.options import config
        c = WireClient(os.path.join(self.dir, f"osd.{osd}.sock"),
                       self.entity, ticket=grant["ticket"],
                       session_key=key, timeout=5.0,
                       peer=f"osd.{osd}",
                       # intra-cluster data mode (the reference's
                       # ms_cluster_mode, its own knob — sealing
                       # client streams must not silently downgrade
                       # peer links or vice versa): crc by default,
                       # which is what lets replica sub-writes carry
                       # the one-pass trusted-csum handoff
                       mode=str(config().get("osd_cluster_wire_mode")))
        with self._peer_lock:
            self._peers[osd] = c
        return c

    def drop_peer(self, osd: int) -> None:
        with self._peer_lock:
            c = self._peers.pop(osd, None)
        if c:
            c.close()

    def boot(self) -> None:
        """Announce up + fetch the map (MOSDBoot).  Retries with a
        fresh mon connection: a transient drop (mon restarting,
        injected socket failure) at boot must not kill the daemon.
        Exponential backoff with per-daemon jitter — N OSDs booting
        against one recovering mon must not stampede in lockstep."""
        last: Optional[Exception] = None
        backoff = ExpBackoff(base=0.1, cap=1.0, seed=self.id)
        for attempt in range(5):
            try:
                mon = self.mon_client()
                mon.call({"cmd": "osd_boot", "osd": self.id})
                self._map = mon.call({"cmd": "get_map"})
                return
            except (OSError, IOError) as e:
                last = e
                if self._mon is not None:
                    try:
                        self._mon.close()
                    except OSError:
                        pass
                    self._mon = None
                backoff.sleep(attempt)
        raise IOError(f"osd.{self.id}: boot failed ({last})")

    def _pglog(self, coll: Tuple[int, int]):
        from .daemon_pglog import DurablePGLog
        with self._pglog_lock:
            log = self._pglogs.get(coll)
            if log is None:
                log = self._pglogs[coll] = DurablePGLog(self.store,
                                                        coll)
            return log

    def _pg_lock(self, coll: Tuple[int, int]) -> LockdepLock:
        with self._pglog_lock:
            lk = self._pg_locks.get(coll)
            if lk is None:
                lk = self._pg_locks[coll] = LockdepLock(
                    f"osd.pg.{coll[0]}.{coll[1]}",
                    recursive=False)
            return lk

    # ------------------------------------------------------------ serving --
    def _run_sched(self, op: Callable[[], Any], klass: str) -> Any:
        """Every op passes through the mClock scheduler — and the
        scheduler now actually ARBITRATES: the op is parked in the
        queue and connection threads cooperatively drain it in
        dmClock tag order, so under contention (many connections
        enqueueing at once) a reserved tenant's ops are dispatched
        ahead of a noisy tenant's backlog regardless of arrival
        order.  The old shape enqueued and immediately dequeued under
        one lock — the queue was empty between calls and QoS never
        reordered anything.

        A thread may execute ANOTHER connection's op (the one the
        tags say goes first) and have its own executed elsewhere;
        results route back through per-op completion events.  The
        caller's trace context is captured at enqueue so the
        dispatch span lands under the op's own osd.op span, whichever
        thread runs it."""
        mark_active("dispatched_device", osd=self.id, klass=klass)
        tctx = _trace.tracer().current_ctx() if _trace.enabled() \
            else None
        entry = {"fn": op, "tctx": tctx, "klass": klass,
                 "done": threading.Event(), "result": None,
                 "exc": None}
        with self._sched_lock:
            self.sched.enqueue(entry, klass=klass)
        while not entry["done"].is_set():
            with self._sched_lock:
                item = None if entry["done"].is_set() \
                    else self.sched.dequeue()
            if item is None:
                # our op was claimed by another thread (or just
                # finished): wait for its completion
                entry["done"].wait()
                break
            _klass, e = item
            # dispatch-stage span under the EXECUTED op's own trace
            # context (child of its osd.op span; null when untraced)
            try:
                with _trace.linked_span("osd.dispatch", e["tctx"],
                                        osd=self.id,
                                        klass=e["klass"]):
                    e["result"] = e["fn"]()
            except BaseException as ex:
                e["exc"] = ex
            e["done"].set()
            if e is entry:
                break
        if entry["exc"] is not None:
            raise entry["exc"]
        return entry["result"]

    def _check_pool_live(self, coll) -> None:
        """Refuse mutations into pools the fetched map says are
        DELETED (same gate as _purge_dead_pools): acking a write the
        next heartbeat will purge is silent data loss.  Pools newer
        than this OSD's map (id above its pool_id_max) are accepted —
        the map is merely stale."""
        pool_id_max = int(self._map.get("pool_id_max", 0))
        if not pool_id_max:
            return
        pid = int(coll[0])
        if pid <= pool_id_max and \
                pid not in {int(p["id"])
                            for p in self._map.get("pools", [])}:
            raise IOError(f"pool {pid} does not exist (deleted)")

    # wire data-path commands that get a TrackedOp (control traffic —
    # maps, watches, pg queries — stays untracked: high-rate, never the
    # ops an operator hunts with dump_historic_ops)
    _TRACKED_CMDS = frozenset((
        "put_shard", "get_shard", "delete_shard", "setattr_shard",
        "getattr_shard", "stat_shard", "digest_shard", "copy_from",
        "put_object", "delete_object", "exec_cls"))

    # mutations covered by (session, seq) dup detection: a replay of
    # an already-applied op must not apply a second time.  The bulk
    # recovery frames and the stray purge joined in CTLint v2
    # (a replayed old bulk push interleaving with a newer write has
    # the same clobber hazard the per-object table was built for)
    _REPLAY_CMDS = frozenset((
        "put_shard", "put_object", "delete_shard", "delete_object",
        "setattr_shard", "copy_from", "exec_cls",
        "put_objects", "delete_objects", "delete_shards"))

    _SESSION_REPLY_WINDOW = 64        # cached replies per session
    _MAX_SESSIONS = 256               # LRU cap across clients

    # ------------------------------------------------------- sessions --
    def _session_state(self, entity: str, sid: str) -> Dict[str, Any]:
        """Find-or-create under _session_lock (caller holds it)."""
        key = (entity, sid)
        st = self._sessions.get(key)
        if st is None:
            if len(self._sessions) >= self._MAX_SESSIONS:
                oldest = min(self._sessions,
                             key=lambda k:
                             self._sessions[k]["touched"])
                del self._sessions[oldest]
            st = self._sessions[key] = {"last": 0, "replies": {},
                                        "touched": time.monotonic()}
        st["touched"] = time.monotonic()
        return st

    def _session_hello(self, entity: str,
                       req: Dict[str, Any]) -> Dict[str, Any]:
        """Session establishment/resume on (re)connect: the client
        announces its session id and the highest seq it has USED; the
        server answers whether it still holds the session.  A resume
        (seq > 0) against an unknown sid is a detected STALE SESSION
        — this daemon restarted or evicted it — and both sides reset:
        the server starts fresh state here, the client learns its
        dedup history is gone (its durable-idempotent full-rewrite
        contract covers re-applies) and re-establishes session-scoped
        state such as watches."""
        sid = str(req["session"])
        with self._session_lock:
            known = (entity, sid) in self._sessions
            st = self._session_state(entity, sid)
            if not known and int(req.get("seq", 0)) > 0:
                self.session_resets += 1
                self._pc_session.inc("resets")
            return {"known": known, "last_applied": st["last"]}

    _MISS = object()

    class _InFlight:
        """Marker parked in the reply window while the FIRST arrival
        of a seq is still applying: a replay that races it (client
        socket timeout + retry while the apply is merely slow) must
        WAIT for that apply rather than start a second one — two
        concurrent applies of one seq could interleave with a newer
        write and clobber it."""

        __slots__ = ("event",)

        def __init__(self) -> None:
            self.event = threading.Event()

    def _session_check(self, entity: str, sid: str, seq: int) -> Any:
        """_MISS when the op must apply (an in-flight marker is
        parked first); otherwise the recorded reply.  Dedup is
        strictly against the RETAINED reply window: a seq below the
        window's floor is applied again (ops on one session run
        CONCURRENTLY over per-object paths, so ``seq <= last`` cannot
        distinguish 'applied long ago' from 'arrived out of order' —
        and the client's full-rewrite semantics make a beyond-window
        re-apply idempotent, exactly the reference's bounded pg-log
        dup window contract)."""
        with self._session_lock:
            st = self._session_state(entity, sid)
            ent = st["replies"].get(seq)
            if ent is None:
                st["replies"][seq] = self._InFlight()
                return self._MISS
            if not isinstance(ent, self._InFlight):
                self._pc_session.inc("replay_dups")
                return ent
            ev = ent.event
        # the first arrival is still applying: wait it out (outside
        # the lock — the apply needs it), then return ITS outcome
        ev.wait(30.0)
        with self._session_lock:
            st = self._sessions.get((entity, sid))
            ent = None if st is None else st["replies"].get(seq)
            if ent is None or isinstance(ent, self._InFlight):
                # first apply failed (aborted) or is still stuck:
                # surface a retryable error — the caller's resend
                # machinery comes back through a fresh check
                raise IOError(f"session {sid}: seq {seq} first "
                              f"apply did not complete")
            self._pc_session.inc("replay_dups")
            return ent

    def _session_record(self, entity: str, sid: str, seq: int,
                        reply: Any) -> None:
        with self._session_lock:
            st = self._session_state(entity, sid)
            prev = st["replies"].get(seq)
            st["replies"][seq] = reply
            st["last"] = max(st["last"], seq)
            self._pc_session.inc("applied")
            live = [s for s, e in st["replies"].items()
                    if not isinstance(e, self._InFlight)]
            while len(live) > self._SESSION_REPLY_WINDOW:
                # evict completed replies only: an in-flight marker
                # must survive until its apply resolves
                oldest = min(live)
                del st["replies"][oldest]
                live.remove(oldest)
        if isinstance(prev, self._InFlight):
            prev.event.set()          # wake replay waiters

    def _session_abort(self, entity: str, sid: str, seq: int) -> None:
        """First apply raised: clear the marker so a resend can apply
        afresh, and wake any replay waiting on it."""
        with self._session_lock:
            st = self._sessions.get((entity, sid))
            ent = None if st is None else st["replies"].get(seq)
            if isinstance(ent, self._InFlight):
                del st["replies"][seq]
        if isinstance(ent, self._InFlight):
            ent.event.set()

    def _handle(self, entity: str, req: Dict[str, Any]) -> Any:
        cmd = req["cmd"]
        inj = faults.fire("daemon.hang_op", cmd=cmd)
        if inj is not None:
            # stalled dispatch: ops pile up behind this connection's
            # thread; the OpTracker complaint window / peer heartbeats
            # are what notice
            time.sleep(float(inj.get("seconds", 0.5)))
        if faults.fire("daemon.crash_op", cmd=cmd) is not None:
            # process death mid-op: no reply, no cleanup — exactly the
            # thrasher's kill -9; durable state must carry the cluster
            os._exit(17)
        if cmd == "session_hello":
            return self._session_hello(entity, req)
        sid, seq = req.get("session"), req.get("seq")
        if sid is not None and seq is not None and \
                cmd in self._REPLAY_CMDS:
            cached = self._session_check(entity, str(sid), int(seq))
            if cached is not self._MISS:
                return cached          # replayed op: applied once
            try:
                reply = self._handle_tracked(entity, req)
            except BaseException:
                self._session_abort(entity, str(sid), int(seq))
                raise
            self._session_record(entity, str(sid), int(seq), reply)
            return reply
        return self._handle_tracked(entity, req)

    def _handle_tracked(self, entity: str, req: Dict[str, Any]) -> Any:
        cmd = req["cmd"]
        if cmd not in self._TRACKED_CMDS:
            return self._handle_inner(entity, req)
        tr = _op_tracker()
        top = tr.create(cmd, service=self.entity, client=entity,
                        oid=req.get("oid"))
        top.mark_event("reached_osd", osd=self.id,
                       klass=req.get("klass", "client"))
        error = None
        try:
            # daemon-side op span, LINKED under the trace context the
            # client stamped into the wire request meta (``tctx``) —
            # this is where a cross-process trace enters this daemon;
            # peer fan-outs below stamp THIS span as their parent, so
            # replica daemons' spans land as grandchildren
            with _trace.linked_span("osd.op", req.get("tctx"),
                                    osd=self.id, cmd=cmd) as span:
                if span.trace_id and top.tracked:
                    top.tags["trace_id"] = span.trace_id
                with tr.track(top):
                    reply = self._handle_inner(entity, req)
                self._account_io(entity, req, reply)
                return reply
        except BaseException as e:
            error = type(e).__name__
            raise
        finally:
            tr.finish(top, error=error)

    _WR_CMDS = frozenset(("put_shard", "put_object", "setattr_shard",
                          "copy_from"))
    _RD_CMDS = frozenset(("get_shard", "getattr_shard", "stat_shard",
                          "digest_shard"))

    def _account_io(self, entity: str, req: Dict[str, Any],
                    reply: Any) -> None:
        """Per-daemon (and per-pool) rd/wr op+byte counters — the
        sensor the `ceph -s` client io line aggregates from.  Only
        CLIENT-facing ops count: replica fan-outs and recovery
        pushes re-enter this handler from peer OSDs, and counting
        them would inflate "client io" by the replication factor
        (the PGMap client-vs-recovery distinction)."""
        if entity.startswith("osd.") or \
                req.get("klass") == "background_recovery":
            return
        cmd = req["cmd"]
        coll = req.get("coll")
        pool = int(coll[0]) if coll else -1
        pg = int(coll[1]) if coll is not None and len(coll) > 1 else -1
        if cmd in self._WR_CMDS:
            nbytes = len(req.get("data") or b"")
            self._pc_io.inc("wr_ops")
            self._pc_io.inc("wr_bytes", nbytes)
            if pool >= 0:
                self._pc_io.inc(f"pool.{pool}.wr_ops")
                self._pc_io.inc(f"pool.{pool}.wr_bytes", nbytes)
                if pg >= 0:
                    self.heat.record(pool, pg, "wr", nbytes=nbytes)
        elif cmd in self._RD_CMDS:
            if isinstance(reply, wire.BulkReply):
                nbytes = len(reply.data)
            else:
                nbytes = len(reply) if isinstance(
                    reply, (bytes, bytearray, memoryview)) else 0
            self._pc_io.inc("rd_ops")
            self._pc_io.inc("rd_bytes", nbytes)
            if pool >= 0:
                self._pc_io.inc(f"pool.{pool}.rd_ops")
                self._pc_io.inc(f"pool.{pool}.rd_bytes", nbytes)
                if pg >= 0:
                    self.heat.record(pool, pg, "rd", nbytes=nbytes)
        elif cmd in ("delete_shard", "delete_object"):
            self._pc_io.inc("wr_ops")
            if pool >= 0:
                self._pc_io.inc(f"pool.{pool}.wr_ops")
                if pg >= 0:
                    self.heat.record(pool, pg, "wr")

    def _handle_inner(self, entity: str, req: Dict[str, Any]) -> Any:
        cmd = req["cmd"]
        klass = req.get("klass", "client")
        tenant = req.get("tenant")
        if tenant and klass == "client":
            # tenant identity propagated from S3 auth through the
            # objecter: client ops dispatch under the tenant's OWN
            # dmClock class (auto-vivified with the
            # osd_mclock_scheduler_client_* defaults, or the spec's
            # qos_tenants override)
            from ..msg.scheduler import tenant_class
            klass = tenant_class(str(tenant))
        if cmd in ("put_shard", "put_object", "delete_object",
                   "setattr_shard"):
            self._check_pool_live(req["coll"])
        if cmd == "put_shard":
            coll = tuple(req["coll"])
            from .objectstore import Transaction

            def put():
                # trusted csums from the wire's one-pass verify scan
                # (socket SG frame or shm ring): the store writes the
                # payload WITHOUT re-scanning it — its per-block blob
                # csums are the very values that just verified these
                # bytes.  copy=False: the buffer is a per-frame view
                # nobody mutates; write_full must not materialize it.
                txn = Transaction().write_full(
                    coll, req["oid"], req["data"],
                    csums=req.get("_csums"), copy=False)
                for ak, av in (req.get("attrs") or {}).items():
                    txn.setattr(coll, req["oid"], ak, av)
                lg = req.get("log")
                if not lg:
                    self.store.apply_transaction(txn)
                    return True
                with self._pg_lock(coll):
                    # replica-side log append in the SAME txn; the
                    # replica only advances last_complete when it was
                    # current through the primary's previous version —
                    # otherwise the entry lands but the gap stays
                    # visible to peering (missing-set semantics)
                    log = self._pglog(coll)
                    v = tuple(lg["version"])
                    prev = tuple(lg.get("prev", (0, 0)))
                    log.append_txn(
                        txn, v, req["oid"],
                        advance_lc=log.last_complete >= prev)
                    self.store.apply_transaction(txn)
                return True
            return self._run_sched(put, klass)
        if cmd == "setattr_shard":
            coll = tuple(req["coll"])
            from .objectstore import Transaction

            def sa():
                txn = Transaction()
                for ak, av in req["attrs"].items():
                    txn.setattr(coll, req["oid"], ak, av)
                self.store.apply_transaction(txn)
                return True
            return self._run_sched(sa, klass)
        if cmd == "getattr_shard":
            coll = tuple(req["coll"])
            def rd():
                try:
                    return self.store.getattr(coll, req["oid"],
                                              req["key"])
                except (IOError, KeyError):
                    return None
            return self._run_sched(rd, klass)
        if cmd == "get_shard":
            coll = tuple(req["coll"])
            def read():
                rg = req.get("ranges")
                rwc = None if rg else getattr(
                    self.store, "read_with_csums", None)
                try:
                    if rwc is not None:
                        # full-object read with the store-trusted
                        # blob csums alongside (RingReply): the
                        # reply chokepoint folds them into the frame
                        # crc / ring doorbell, so the get reply
                        # leaves this daemon with ZERO send scans
                        data, cs = rwc(coll, req["oid"])
                        return wire.BulkReply(data, cs)
                    data = self.store.read(coll, req["oid"])
                except IOError:
                    return None
                if rg:
                    # sub-shard ranged read: only the requested byte
                    # ranges cross the wire (a regenerating-code
                    # helper ships its repair sub-chunks, not the
                    # whole shard — the Clay minimum-bandwidth fetch)
                    data = b"".join(bytes(data[int(o):int(o) + int(n)])
                                    for o, n in rg)
                return data
            return self._run_sched(read, klass)
        if cmd == "getattrs_shard":
            # all requested attrs in ONE round trip (the recovery
            # geometry probe used to cost one blocking call per key)
            coll = tuple(req["coll"])

            def rda():
                out = {}
                for akey in req["keys"]:
                    try:
                        out[akey] = self.store.getattr(
                            coll, req["oid"], akey)
                    except (IOError, KeyError):
                        out[akey] = None
                return out
            return self._run_sched(rda, klass)
        if cmd == "get_objects":
            # bulk recovery pull: one scatter-gather frame for a
            # whole chunk of objects ({oid: bytes|None}).  The reply
            # is BYTE-CAPPED server-side (an uncapped 64-object chunk
            # of 8 MiB objects would exceed the 256 MiB wire frame
            # limit and fail the member's recovery forever): oids the
            # budget excludes are simply OMITTED — absent, not None —
            # and the puller re-requests them next round
            coll = tuple(req["coll"])

            def read_many():
                out = {}
                nbytes = 0
                rwc = getattr(self.store, "read_with_csums", None)
                for oid in req["oids"]:
                    if out and nbytes >= self._RECOVERY_CHUNK_BYTES:
                        break     # omitted: the caller re-requests
                    try:
                        if rwc is not None:
                            # trusted csums per object: same-host
                            # recovery pulls ride the reply ring
                            # with zero send scans (RingReply)
                            data, cs = rwc(coll, oid)
                        else:
                            data, cs = self.store.read(coll, oid), \
                                None
                        nbytes += len(data)
                        out[oid] = wire.BulkReply(data, cs)
                    except IOError:
                        out[oid] = None
                return out
            return self._run_sched(read_many, klass)
        if cmd == "put_objects":
            # bulk recovery push: the whole chunk lands in ONE
            # transaction (apply is atomic per store barrier)
            coll = tuple(req["coll"])
            self._check_pool_live(coll)
            from .objectstore import Transaction

            def put_many():
                txn = Transaction()
                for oid, data in req["objs"]:
                    txn.write_full(coll, oid, data)
                self.store.apply_transaction(txn)
                return len(req["objs"])
            return self._run_sched(put_many, klass)
        if cmd == "delete_objects":
            coll = tuple(req["coll"])
            from .objectstore import Transaction

            def rm_many():
                txn = Transaction()
                for oid in req["oids"]:
                    if self.store.exists(coll, oid):
                        txn.remove(coll, oid)
                if len(txn):
                    self.store.apply_transaction(txn)
                return len(req["oids"])
            return self._run_sched(rm_many, klass)
        if cmd == "reserve_recovery":
            role = str(req.get("role", "remote"))
            if role not in self._resv:
                raise ValueError(f"unknown reservation role {role!r}")
            granted = self._reserve(role)
            return {"granted": granted,
                    "held": self._resv_held()[role]}
        if cmd == "release_recovery":
            role = str(req.get("role", "remote"))
            if role in self._resv:
                self._release(role)
            return {"held": self._resv_held().get(role, 0)}
        if cmd == "delete_shard":
            coll = tuple(req["coll"])
            from .objectstore import Transaction

            def rm():
                txn = Transaction()
                if self.store.exists(coll, req["oid"]):
                    txn.remove(coll, req["oid"])
                lg = req.get("log")
                if not lg:
                    if len(txn):
                        self.store.apply_transaction(txn)
                    return True
                with self._pg_lock(coll):
                    # replica half of a logged delete: the OP_DELETE
                    # entry rides the same txn as the removal (mirror
                    # of put_shard), so recovery can never resurrect
                    # the object from a log that lacks its delete
                    from .pglog import OP_DELETE
                    log = self._pglog(coll)
                    v = tuple(lg["version"])
                    prev = tuple(lg.get("prev", (0, 0)))
                    log.append_txn(
                        txn, v, req["oid"], op=OP_DELETE,
                        advance_lc=log.last_complete >= prev)
                    self.store.apply_transaction(txn)
                return True
            return self._run_sched(rm, klass)
        if cmd == "copy_from":
            # PrimaryLogPG copy-from (src/osd/PrimaryLogPG.cc
            # do_copy_from role): the DESTINATION primary pulls the
            # source object server-side — possibly from another OSD —
            # and commits it locally + to replicas as a logged write;
            # the client never carries the payload
            coll = tuple(req["coll"])
            self._check_pool_live(coll)
            src_coll = tuple(req["src_coll"])

            def read_src():
                src_oid = req["src_oid"]
                if req.get("src_osd") in (None, self.id):
                    try:
                        return self.store.read(src_coll, src_oid)
                    except IOError:
                        return None
                return self._peer_req(int(req["src_osd"]),
                                      _trace.stamp(
                                          {"cmd": "get_shard",
                                           "coll": list(src_coll),
                                           "oid": src_oid}))
            data = read_src()
            if data is None:
                raise IOError(f"copy_from: source "
                              f"{req['src_oid']!r} unreadable")
            fwd = {"cmd": "put_object", "coll": list(coll),
                   "oid": req["oid"], "data": bytes(data),
                   "replicas": req["replicas"], "klass": klass}
            return self._handle(entity, fwd)
        if cmd == "delete_object":
            # replicated primary delete: version + OP_DELETE log entry
            # + removal in ONE txn, fanned out to replicas — the
            # PrimaryLogPG delete shape; without this, a down replica
            # resurrects the object on log-driven recovery
            coll = tuple(req["coll"])
            from .objectstore import Transaction
            from .pglog import OP_DELETE
            with self._pg_lock(coll):
                log = self._pglog(coll)
                prev = log.log.head
                version = log.next_version(
                    int(self._map.get("epoch", prev[0] or 1)))

                def rm_primary():
                    txn = Transaction()
                    if self.store.exists(coll, req["oid"]):
                        txn.remove(coll, req["oid"])
                    log.append_txn(txn, version, req["oid"],
                                   op=OP_DELETE)
                    self.store.apply_transaction(txn)
                self._run_sched(rm_primary, klass)
                acks = 1
                for peer in req["replicas"]:
                    if peer == self.id:
                        continue
                    # replica sub-delete through the _peer_req
                    # chokepoint: trace-stamped AND (session, seq)-
                    # stamped (at-most-once on the replica)
                    if self._peer_req(peer, _trace.stamp({
                            "cmd": "delete_shard", "coll": list(coll),
                            "oid": req["oid"], "klass": klass,
                            "log": {"version": list(version),
                                    "prev": list(prev)}})) is not None:
                        acks += 1
            return {"acks": acks, "version": list(version)}
        if cmd == "put_object":
            # replicated primary: assign the version, persist object +
            # log entry in ONE txn, fan the versioned write out to
            # replicas (PrimaryLogPG::execute_ctx -> issue_repop shape)
            coll = tuple(req["coll"])
            from .objectstore import Transaction
            with self._pg_lock(coll):      # PG lock: serialize writes
                log = self._pglog(coll)
                prev = log.log.head
                version = log.next_version(
                    int(self._map.get("epoch", prev[0] or 1)))

                def put_primary():
                    txn = Transaction().write_full(
                        coll, req["oid"], req["data"],
                        csums=req.get("_csums"), copy=False)
                    for ak, av in (req.get("attrs") or {}).items():
                        txn.setattr(coll, req["oid"], ak, av)
                    log.append_txn(txn, version, req["oid"])
                    self.store.apply_transaction(txn)
                self._run_sched(put_primary, klass)
                acks = 1
                for peer in req["replicas"]:
                    if peer == self.id:
                        continue
                    # replica sub-write through the _peer_req
                    # chokepoint: carries the trace context of THIS
                    # daemon's active osd.op span (replica spans link
                    # as children, the >= 3-process trace shape) AND
                    # a (session, seq) stamp (at-most-once replay)
                    if self._peer_req(peer, _trace.stamp({
                            "cmd": "put_shard", "coll": list(coll),
                            "oid": req["oid"], "data": req["data"],
                            # the primary's verify-trusted csums fold
                            # into the peer frame crc (no re-scan on
                            # this send) and become the replica's
                            # trusted handoff in turn
                            "_csums": req.get("_csums"),
                            "klass": klass, "attrs": req.get("attrs"),
                            "log": {"version": list(version),
                                    "prev": list(prev)}})) is not None:
                        acks += 1
            return {"acks": acks, "version": list(version)}
        if cmd == "list_pg":
            coll = tuple(req["coll"])
            return self.store.list_objects(coll)
        if cmd == "delete_shards":
            # bulk stray purge (the client fanout's supersession
            # sweep): many (coll, oid) removals in one RTT instead of
            # one delete_shard call per shard
            from .objectstore import Transaction
            removed = 0
            for c, oid in req["items"]:
                c = tuple(c)
                if self.store.exists(c, oid):
                    self.store.apply_transaction(
                        Transaction().remove(c, oid))
                    removed += 1
            return removed
        if cmd == "count_pool":
            # non-meta objects this OSD holds for one pool, across
            # all its PG collections (the mon's tier-remove drain
            # gate: one RTT per OSD instead of pg_num listings)
            pid = int(req["pool"])
            n = 0
            for c in self.store.list_collections():
                if c[0] == pid:
                    n += sum(1 for o in self.store.list_objects(c)
                             if not o.startswith("meta:"))
            return n
        if cmd == "pg_info":
            # GetInfo: this replica's log bounds + applied version
            return self._pglog(tuple(req["coll"])).info()
        if cmd == "pg_log":
            # GetLog: authoritative entries after a version
            log = self._pglog(tuple(req["coll"]))
            return {"entries": [(list(v), o, op) for v, o, op in
                                log.entries_after(tuple(req["after"]))],
                    "head": list(log.log.head)}
        if cmd == "log_sync":
            # merge the authority's tail + advance last_complete
            # (PGLog::merge_log after recovery completes)
            coll = tuple(req["coll"])
            from .objectstore import Transaction
            log = self._pglog(coll)
            txn = Transaction()
            log.merge_tail_txn(
                txn,
                [(tuple(v), o, op) for v, o, op in req["entries"]],
                tuple(req["head"]))
            self.store.apply_transaction(txn)
            return True
        if cmd == "digest_shard":
            coll = tuple(req["coll"])
            try:
                return self.store.stat(coll, req["oid"])["csum"]
            except (IOError, KeyError):
                return None
        if cmd == "watch_register":
            # Watch role (src/osd/Watch.cc): the object's PRIMARY
            # keeps the watcher registry; each watcher gets a cookie
            # and a pending-notification queue it polls (this wire is
            # request/reply, so delivery is poll-based rather than
            # connection-push)
            wk = (tuple(req["coll"]), req["oid"])
            with self._watch_lock:
                cookie = self._watch_next
                self._watch_next += 1
                self._watchers.setdefault(wk, {})[cookie] = []
            return {"cookie": cookie}
        if cmd == "watch_unregister":
            wk = (tuple(req["coll"]), req["oid"])
            with self._watch_lock:
                self._watchers.get(wk, {}).pop(int(req["cookie"]),
                                               None)
            return {"ok": True}
        if cmd == "watch_poll":
            wk = (tuple(req["coll"]), req["oid"])
            with self._watch_lock:
                q = self._watchers.get(wk, {}).get(int(req["cookie"]))
                if q is None:
                    # daemon restarted / watch expired: the client
                    # must re-register (the reference's watch timeout)
                    return {"gone": True, "events": []}
                events, q[:] = list(q), []
            return {"events": events}
        if cmd == "notify":
            wk = (tuple(req["coll"]), req["oid"])
            payload = req.get("payload", b"")
            with self._watch_lock:
                nid = self._watch_next
                self._watch_next += 1
                watchers = self._watchers.get(wk, {})
                for cookie, q in watchers.items():
                    q.append([nid, payload])
                # snapshot INSIDE the lock: `watchers` aliases the
                # live dict and concurrent register/unregister would
                # race the iteration
                w_list = sorted(watchers)
                if watchers:
                    # zero-watcher notifies allocate NO wait state:
                    # the notifier returns early and nothing would
                    # ever pop the entry
                    self._notify_state[nid] = {"want": set(watchers),
                                               "acks": {}}
            return {"notify_id": nid, "watchers": w_list}
        if cmd == "notify_ack":
            with self._watch_lock:
                st = self._notify_state.get(int(req["notify_id"]))
                if st is not None:
                    st["acks"][int(req["cookie"])] = req.get("ack")
            return {"ok": True}
        if cmd == "notify_wait":
            # gather acks until every watcher answered or timeout —
            # non-answering watchers are reported pending (the Notify
            # timeout shape); each connection has its own server
            # thread, so blocking here is fine
            nid = int(req["notify_id"])
            deadline = time.monotonic() + float(req.get("timeout",
                                                        3.0))
            while True:
                with self._watch_lock:
                    st = self._notify_state.get(nid)
                    if st is None:
                        return {"acks": {}, "pending": []}
                    if set(st["acks"]) >= st["want"] or \
                            time.monotonic() >= deadline:
                        self._notify_state.pop(nid, None)
                        return {"acks": {str(c): a for c, a in
                                         st["acks"].items()},
                                "pending": sorted(st["want"] -
                                                  set(st["acks"]))}
                time.sleep(0.02)
        if cmd == "exec_cls":
            # CEPH_OSD_OP_CALL over the wire: the method runs INSIDE
            # the primary OSD through the SAME ClassHandler the sim
            # tier uses (cluster/class_handler.py), then re-executes
            # on each replica — cls methods are deterministic
            # functions of (object state, input), so re-execution IS
            # state-machine replication and replicas converge
            coll = tuple(req["coll"])
            self._check_pool_live(coll)
            if self._class_handler is None:
                from .class_handler import ClassHandler
                self._class_handler = ClassHandler()

            def run_cls():
                out = self._class_handler.call(
                    self.store, coll, req["oid"], req["cls"],
                    req["method"], req.get("payload", b""))
                for rep in req.get("replicas", []):
                    if rep == self.id:
                        continue
                    try:
                        self._peer_req(rep, _trace.stamp({
                            "cmd": "exec_cls", "coll": list(coll),
                            "oid": req["oid"], "cls": req["cls"],
                            "method": req["method"],
                            "payload": req.get("payload", b""),
                            "replicas": []}))
                    except (OSError, IOError):
                        pass      # stale replica heals via recovery
                return out
            return self._run_sched(run_cls, klass)
        if cmd == "stat_shard":
            # size/digest without payload transfer (rados_stat role)
            coll = tuple(req["coll"])
            try:
                st = self.store.stat(coll, req["oid"])
                return {"size": st["size"]}
            except (IOError, KeyError):
                return None
        if cmd == "scrub_pg":
            return self._scrub_pg(tuple(req["coll"]), req["members"],
                                  bool(req.get("repair", False)))
        if cmd == "recover_pg":
            return self._recover_pg(tuple(req["coll"]), req["members"],
                                    req.get("strays") or [])
        if cmd == "ping":
            return {"osd": self.id, "alive": True}
        if cmd == "status":
            with self._session_lock:
                n_sessions = len(self._sessions)
            resv = {"held": self._resv_held(),
                    "peak": dict(self._resv_peak)}
            with self._sched_lock:
                sched = {"dequeued": dict(self.sched.stats),
                         "queued": len(self.sched),
                         "classes": sorted(self.sched.qos)}
            return {"osd": self.id,
                    "objects": sum(
                        len(self.store.list_objects(c))
                        for c in self.store.list_collections()),
                    "injected_failures": self.server.injected,
                    "sessions": n_sessions,
                    "session_resets": self.session_resets,
                    "recovery_reservations": resv,
                    "scheduler": sched}
        if cmd == "fsck":
            return [list(map(str, b)) for b in self.store.fsck()]
        raise ValueError(f"unknown osd command {cmd!r}")

    def _peer_stamp(self, m: int) -> Dict[str, Any]:
        """Draw one (session, seq) replay stamp for a mutating
        request bound for peer ``m`` — the daemon-side twin of the
        client's ``_next_stamp`` (sid kept across reconnects)."""
        with self._peer_sess_lock:
            st = self._peer_sessions.get(m)
            if st is None:
                st = self._peer_sessions[m] = {
                    "sid": f"osd{self.id}-{secrets.token_hex(8)}",
                    "seq": 0}
            st["seq"] += 1
            return {"session": st["sid"], "seq": st["seq"]}

    def _peer_req(self, m: int, req: Dict[str, Any]):
        """One guarded peer call (None on failure).  Mutating
        commands are stamped with this daemon's per-peer
        (session, seq) so the receiver applies them at most once —
        every daemon->daemon mutation must route through here (or
        carry its own stamp): the CTL802 chokepoint contract."""
        if req.get("cmd") in self._REPLAY_CMDS and \
                "session" not in req:
            req = dict(req, **self._peer_stamp(m))
        try:
            return self.peer_client(m).call(req)
        except (OSError, IOError):
            self.drop_peer(m)
            return None

    # ---------------------------------------------- recovery reservations --
    def _reserve(self, role: str) -> bool:
        """One reservation lease under the osd_max_backfills cap;
        False = denied (the caller defers and requeues, never
        waits)."""
        from ..common.options import config
        cap = int(config().get("osd_max_backfills"))
        with self._resv_lock:
            self._resv_purge(role)
            if len(self._resv[role]) >= cap:
                self._pc_resv.inc(f"{role}_denials")
                return False
            self._resv[role].append(time.monotonic())
            held = len(self._resv[role])
            self._resv_peak[role] = max(self._resv_peak[role], held)
        self._pc_resv.inc(f"{role}_grants")
        self._pc_resv.set(f"{role}_held", held)
        return True

    def _release(self, role: str) -> None:
        with self._resv_lock:
            self._resv_purge(role)
            if self._resv[role]:
                self._resv[role].pop(0)
            held = len(self._resv[role])
        self._pc_resv.set(f"{role}_held", held)

    # ------------------------------------------------- bulk object moves --
    _RECOVERY_CHUNK_OBJS = 64
    _RECOVERY_CHUNK_BYTES = 64 << 20

    def _pull_objects(self, coll, src: int,
                      oids: List[str]) -> Dict[str, Any]:
        """{oid: bytes|None} from ONE holder — scatter-gather
        ``get_objects`` frames instead of a blocking round trip per
        object (the per-object `_pull_object` loop this replaces was
        the wire tier's recovery bottleneck).  The server byte-caps
        each reply and OMITS overflow oids; the loop re-requests the
        omissions until everything is answered or a round makes no
        progress (which reads as failure — None — for the rest)."""
        out: Dict[str, Any] = {}
        pending = list(oids)
        while pending:
            chunk = pending[:self._RECOVERY_CHUNK_OBJS]
            if src == self.id:
                for oid in chunk:
                    try:
                        out[oid] = self.store.read(coll, oid)
                    except IOError:
                        out[oid] = None
                pending = pending[len(chunk):]
                continue
            r = self._peer_req(src, _trace.stamp({
                "cmd": "get_objects", "coll": list(coll),
                "oids": chunk, "klass": "background_recovery"}))
            if not r:
                for oid in pending:
                    out.setdefault(oid, None)
                break
            out.update(r)
            pending = [o for o in pending if o not in out]
        return out

    def _push_objects(self, coll, dst: int, items) -> int:
        """Push [(oid, data)] to one member in bounded
        ``put_objects`` frames; returns objects landed."""
        from .objectstore import Transaction
        n = i = 0
        while i < len(items):
            chunk, nbytes = [], 0
            while i < len(items) and \
                    len(chunk) < self._RECOVERY_CHUNK_OBJS and \
                    nbytes < self._RECOVERY_CHUNK_BYTES:
                chunk.append(items[i])
                nbytes += len(items[i][1])
                i += 1
            if dst == self.id:
                txn = Transaction()
                for oid, data in chunk:
                    txn.write_full(coll, oid, data)
                self.store.apply_transaction(txn)
                n += len(chunk)
            elif self._peer_req(dst, _trace.stamp({
                    "cmd": "put_objects", "coll": list(coll),
                    "objs": [[oid, data] for oid, data in chunk],
                    "klass": "background_recovery"})) is not None:
                n += len(chunk)
        return n

    def _move_objects(self, coll, src: int, dst: int,
                      oids: List[str]) -> int:
        """Bulk pull from ``src`` + bulk push to ``dst``; returns
        objects moved (missing pulls and failed pushes both count
        against completeness — the caller must not advance
        last_complete past them)."""
        pulled = self._pull_objects(coll, src, oids)
        items = [(oid, pulled[oid]) for oid in oids
                 if pulled.get(oid) is not None]
        return self._push_objects(coll, dst, items)

    def _pull_object(self, coll, oid, holders) -> Optional[bytes]:
        for h in holders:
            if h == self.id:
                try:
                    return self.store.read(coll, oid)
                except IOError:
                    continue
            d = self._peer_req(h, _trace.stamp(
                {"cmd": "get_shard",
                 "coll": list(coll), "oid": oid,
                 "klass": "background_recovery"}))
            if d is not None:
                return d
        return None

    def _push_object(self, coll, oid, data, m) -> bool:
        from .objectstore import Transaction
        if m == self.id:
            self.store.apply_transaction(
                Transaction().write_full(coll, oid, data))
            return True
        return self._peer_req(m, _trace.stamp({
            "cmd": "put_shard", "coll": list(coll), "oid": oid,
            "data": data,
            "klass": "background_recovery"})) is not None

    def _recover_pg(self, coll: Tuple[int, int],
                    members: List[int],
                    strays: Optional[List[int]] = None
                    ) -> Dict[str, Any]:
        """Reservation gate around one PG's recovery: LOCAL slot on
        this primary, REMOTE slot on every other member — acquired
        all-or-nothing with rollback (never wait while holding, so
        concurrent primaries cannot deadlock); any denial returns
        ``{"deferred": True}`` for the caller's requeue loop.  This is
        the osd_max_backfills contract: concurrent PG recoveries
        saturate spare bandwidth without unbounded fan-in on one OSD,
        and client QoS survives because every recovery op already
        rides the background_recovery dmClock class."""
        me = self.id
        if not self._reserve("local"):
            return {"deferred": True, "by": me}
        got: List[int] = []
        try:
            for m in members:
                if m == me:
                    continue
                r = self._peer_req(m, {"cmd": "reserve_recovery",
                                       "role": "remote"})
                if r is None:
                    # UNREACHABLE member: no slot to take and no
                    # reason to defer — the recovery pass itself
                    # marks it incomplete (deferring here would let
                    # one dead-but-in-map member block every
                    # reachable member's recovery forever)
                    continue
                if not r.get("granted"):
                    return {"deferred": True, "by": m}
                got.append(m)
            return self._recover_pg_inner(coll, members, strays)
        finally:
            for m in got:
                self._peer_req(m, {"cmd": "release_recovery",
                                   "role": "remote"})
            self._release("local")

    def _recover_pg_inner(self, coll: Tuple[int, int],
                          members: List[int],
                          strays: Optional[List[int]] = None
                          ) -> Dict[str, Any]:
        """Primary-driven PG recovery running the PeeringState shape
        over the wire (GetInfo -> GetLog -> GetMissing -> Recovering
        or Backfilling, src/osd/PeeringState.h:561):

        1. GetInfo: every member reports its log bounds +
           last_complete (pg_info).  ``strays`` — OSDs OUTSIDE the
           current acting set — are consulted as info/log SOURCES
           only (the reference's past-interval/stray peering): a
           write that landed on a substitute member during a map
           flap must not become unreachable when the map heals and
           that member drops out of the set — without stray infos
           the newest log (and its objects) would be invisible to
           every future recovery pass.
        2. GetLog: the authority is the info-holder with the newest
           head; a stale primary first catches ITSELF up from it.
        3. GetMissing: per MEMBER (never a stray), if the
           authoritative log still covers its last_complete, recover
           by LOG DELTA — only the objects the log names after that
           version (deletes applied as deletes); otherwise fall back
           to BACKFILL (full listing diff, the pre-peering path).
        4. Recovered members merge the authority's log tail and
           advance last_complete (log_sync).
        Stats record which path each member took so chaos tests can
        assert delta vs backfill.
        """
        from .pglog import OP_DELETE
        me = self.id
        log = self._pglog(coll)
        infos: Dict[int, Dict] = {me: log.info()}
        stray_set = set(strays or []) - set(members)
        peers = [m for m in members if m != me] + \
            [s for s in sorted(stray_set) if s != me]
        for m in peers:
            inf = self._peer_req(m, {"cmd": "pg_info",
                                     "coll": list(coll)})
            if inf is not None:
                infos[m] = inf
        # a stray with an EMPTY log never held this PG — drop it so
        # the member loop below doesn't try to "recover" it
        for s in list(stray_set):
            if s in infos and tuple(infos[s]["head"]) == (0, 0):
                infos.pop(s)
        # authority = newest head (member or stray)
        auth = max(infos, key=lambda m: tuple(infos[m]["head"]))
        auth_head = tuple(infos[auth]["head"])
        stats: Dict[str, Any] = {"authority": auth, "mode": {},
                                 "delta_objects": 0,
                                 "backfill_objects": 0,
                                 "deletes_applied": 0, "copied": 0}

        def sync_member(m, entries, head):
            if m == me:
                from .objectstore import Transaction
                txn = Transaction()
                log.merge_tail_txn(txn, entries, head)
                self.store.apply_transaction(txn)
                return True
            return self._peer_req(m, {
                "cmd": "log_sync", "coll": list(coll),
                "entries": [(list(v), o, op) for v, o, op in entries],
                "head": list(head)}) is not None

        def auth_entries_after(v):
            if auth == me:
                return log.entries_after(v)
            r = self._peer_req(auth, {"cmd": "pg_log",
                                      "coll": list(coll),
                                      "after": list(v)})
            if r is None:
                return None
            return [(tuple(vv), o, op) for vv, o, op in r["entries"]]

        def listing_of(m):
            """None on a FAILED peer listing — an unreachable peer
            must read as 'unknown', never as 'holds nothing': a
            failure collapsed into an empty set once let a backfill
            pass copy nothing, then stamp the member current
            (last_complete = auth head with neither data nor log) —
            after which every future pass called it clean and the
            objects were unreachable to recovery forever.  The
            server-side twin of the CTL603 lost-object class."""
            if m == me:
                return set(o for o in self.store.list_objects(coll)
                           if not o.startswith("meta:"))
            r = self._peer_req(m, {"cmd": "list_pg",
                                   "coll": list(coll)})
            if r is None:
                return None
            return set(o for o in r if not o.startswith("meta:"))

        auth_listing = None
        for m in sorted(infos, key=lambda x: x != auth):
            if m == auth or m in stray_set:
                # strays are log/data SOURCES, never recovery
                # targets: the map does not want data there
                continue
            # recovery baseline: last_complete CLAMPED to the
            # member's own log head.  lc > head is impossible in a
            # healthy log (they advance together in one txn), so a
            # member showing it was stamped current by a broken past
            # pass (the swallowed-failure bug above) — trusting the
            # lie would read it as clean forever; clamping makes the
            # delta path re-copy from its true position and HEALS it
            lc = min(tuple(infos[m]["last_complete"]),
                     tuple(infos[m]["head"]))
            if lc >= auth_head:
                stats["mode"][str(m)] = "clean"
                continue
            covered = tuple(infos[auth]["tail"]) <= lc
            entries = auth_entries_after(lc) if covered else None
            complete = True       # every needed object moved
            if entries is not None:
                stats["mode"][str(m)] = "delta"
                # latest op per object wins (missing-set semantics of
                # PGLog::missing_since, over the fetched entries);
                # movement is BULK scatter-gather — one get_objects /
                # put_objects / delete_objects frame per bounded
                # chunk, not a blocking round trip per object
                latest: Dict[str, int] = {}
                for v, obj, op in entries:
                    latest[obj] = op
                dels = sorted(o for o, op in latest.items()
                              if op == OP_DELETE)
                copies = sorted(o for o, op in latest.items()
                                if op != OP_DELETE)
                stats["delta_objects"] += len(latest)
                if dels:
                    if m == me:
                        for obj in dels:
                            self._local_delete(coll, obj)
                    elif self._peer_req(m, _trace.stamp(
                            {"cmd": "delete_objects",
                             "coll": list(coll),
                             "oids": dels})) is None:
                        complete = False
                    stats["deletes_applied"] += len(dels)
                moved = self._move_objects(coll, auth, m, copies)
                stats["copied"] += moved
                if moved < len(copies):
                    complete = False
            else:
                stats["mode"][str(m)] = "backfill"
                if auth_listing is None:
                    auth_listing = listing_of(auth)
                if auth_listing is None:
                    # the AUTHORITY listing failed: nothing provable
                    # for this member, and nothing cacheable either
                    stats["mode"][str(m)] += "-incomplete"
                    continue
                have = listing_of(m)
                if have is None:
                    # an unreachable MEMBER means this pass proved
                    # nothing about it — never advance last_complete
                    # (the cached authority listing stays valid for
                    # the remaining members)
                    stats["mode"][str(m)] += "-incomplete"
                    continue
                objs = sorted(auth_listing - have)
                stats["backfill_objects"] += len(objs)
                moved = self._move_objects(coll, auth, m, objs)
                stats["copied"] += moved
                if moved < len(objs):
                    complete = False
                entries = auth_entries_after(lc)
                if entries is None:
                    # the log fetch failed: the data may have moved
                    # but the member's log view is unproven —
                    # last_complete must not advance past it
                    complete = False
                    entries = []
            # advance last_complete ONLY when every object landed —
            # a partial pass must stay visible to the next peering
            # round, or the gap is masked forever
            if complete:
                sync_member(m, entries, auth_head)
            else:
                stats["mode"][str(m)] += "-incomplete"
        return stats

    def _local_delete(self, coll, oid) -> None:
        from .objectstore import Transaction
        if self.store.exists(coll, oid):
            self.store.apply_transaction(
                Transaction().remove(coll, oid))

    def _scrub_pg(self, coll: Tuple[int, int], members: List[int],
                  repair: bool) -> Dict[str, Any]:
        """Cross-replica scrub over the wire (pg_scrubber role): every
        member digests every object; mismatching or absent copies are
        inconsistencies.  With ``repair`` the majority digest's bytes
        overwrite the minority (scrub repair)."""
        listings = {m: set() for m in members}
        for m in members:
            if m == self.id:
                listings[m] = set(
                    o for o in self.store.list_objects(coll)
                    if not o.startswith("meta:"))
            else:
                r = self._peer_req(m, {"cmd": "list_pg",
                                       "coll": list(coll)})
                listings[m] = set(o for o in (r or [])
                                  if not o.startswith("meta:"))
        universe = set().union(*listings.values())
        inconsistent: List[Dict[str, Any]] = []
        repaired = 0
        for oid in sorted(universe):
            digests: Dict[int, Optional[int]] = {}
            for m in members:
                if oid not in listings[m]:
                    digests[m] = None
                    continue
                if m == self.id:
                    try:
                        digests[m] = self.store.stat(coll,
                                                     oid)["csum"]
                    except (IOError, KeyError):
                        digests[m] = None
                else:
                    digests[m] = self._peer_req(
                        m, _trace.stamp(
                            {"cmd": "digest_shard",
                             "coll": list(coll), "oid": oid}))
            present = [d for d in digests.values() if d is not None]
            if not present or len(set(present)) == 1 and \
                    len(present) == len(members):
                continue
            # STRICT majority digest — on a tie (e.g. size-2 pool,
            # 1-vs-1) there is no safe repair source: report the
            # inconsistency but never overwrite either copy
            counts: Dict[int, int] = {}
            for d in present:
                counts[d] = counts.get(d, 0) + 1
            best = max(counts, key=counts.get)
            strict = counts[best] * 2 > len(members)
            bad = [m for m, d in digests.items() if d != best] \
                if strict else []
            inconsistent.append({
                "oid": oid, "bad_members": bad,
                "majority": best if strict else None,
                "no_majority": not strict})
            if repair and strict:
                holders = [m for m, d in digests.items() if d == best]
                data = self._pull_object(coll, oid, holders)
                if data is not None:
                    for m in bad:
                        if self._push_object(coll, oid, data, m):
                            repaired += 1
        return {"objects": len(universe),
                "inconsistent": inconsistent, "repaired": repaired}

    # --------------------------------------------------------- heartbeats --
    def _purge_dead_pools(self) -> None:
        """Map-driven PG teardown (the reference removes a deleted
        pool's PGs when the map lands): drop collections whose pool is
        gone from the fetched map.  Gated on the monotonic pool-id
        high-water mark so a collection created by a put that RACED
        this OSD's stale map (its pool id is above the fetched
        pool_id_max) is never mistaken for deleted-pool debris."""
        pool_id_max = int(self._map.get("pool_id_max", 0))
        if not pool_id_max:
            return               # pre-upgrade mon: no purge authority
        epoch = int(self._map.get("epoch", 0))
        if epoch == getattr(self, "_last_purge_epoch", -1):
            return               # nothing changed: skip the store scan
        self._last_purge_epoch = epoch
        live = {int(p["id"]) for p in self._map.get("pools", [])}
        from .objectstore import Transaction
        for coll in list(self.store.list_collections()):
            pid = coll[0]
            if pid in live or pid > pool_id_max:
                continue
            with self._pg_lock(tuple(coll)):
                txn = Transaction()
                for oid in self.store.list_objects(coll):
                    txn.remove(coll, oid)
                if len(txn):
                    self.store.apply_transaction(txn)
            with self._pglog_lock:
                self._pglogs.pop(tuple(coll), None)

    def _admin_store_fsck(self, args: Dict[str, Any]) -> Dict[str, Any]:
        """Admin-socket store fsck: walk every object (csum + layout
        checks); ``repair`` quarantines inconsistencies so recovery
        re-replicates them.  Updates the health rollup state the
        heartbeat reports to the mon."""
        repair = str(args.get("repair", "")).lower() in (
            "1", "true", "yes", "repair")
        bad = self.store.fsck(repair=repair)
        if repair:
            self.store_fsck_repaired += len(bad)
            self.store_fsck_errors = 0      # quarantined = consistent
        else:
            self.store_fsck_errors = len(bad)
        return {"backend": type(self.store).__name__,
                "errors": [[list(map(int, c)), o] for c, o in bad],
                "n_errors": len(bad),
                "repaired": len(bad) if repair else 0}

    def _report_store_health(self) -> None:
        """Roll boot-fsck damage up to the mon (STORE_DAMAGED).  Sent
        when nonzero, plus one zero report to clear the mon entry
        once a clean fsck resets the count — the _report_slow_ops
        pattern."""
        n = self.store_fsck_errors
        if n == 0 and not self._store_reported:
            return
        try:
            self.mon_client().call({
                "cmd": "report_store_health", "osd": self.id,
                "errors": n, "repaired": self.store_fsck_repaired})
            self._store_reported = n
        except (OSError, IOError):
            self._mon = None

    _UTIL_SCAN_INTERVAL_S = 5.0

    def _store_util(self) -> Dict[str, Any]:
        """Store utilization snapshot for the ClusterStats rollup:
        allocator-backed used/total bytes (BlueStore) plus per-pool
        object counts from the collection listing.  The object scan
        is O(store) so it runs at most every _UTIL_SCAN_INTERVAL_S;
        between scans the cached snapshot rides the (cheap, 1 s)
        perf-counter reports."""
        now = time.monotonic()
        cached = getattr(self, "_util_cache", None)
        if cached is not None and \
                now - cached[0] < self._UTIL_SCAN_INTERVAL_S:
            return cached[1]
        util: Dict[str, Any] = {"bytes": 0, "total_bytes": 0,
                                "objects": 0, "pools": {}}
        st = self.store
        alloc = getattr(st, "alloc", None)
        if alloc is not None:
            free = int(alloc.free_blocks)
            util["bytes"] = (st.n_blocks - free) * st.min_alloc
            util["total_bytes"] = st.device_bytes
        try:
            for coll in st.list_collections():
                # data shards only (the count_pool convention):
                # pglog/meta rows are bookkeeping, not user objects
                pid = int(coll[0])
                row = util["pools"].setdefault(
                    pid, {"objects": 0, "bytes": 0})
                for o in st.list_objects(coll):
                    if o.startswith("meta:"):
                        continue
                    util["objects"] += 1
                    row["objects"] += 1
                    try:
                        # per-pool BYTE accounting (onode sizes, the
                        # PGMap per-pool STORED figure): this is what
                        # lets `ceph df` quote bytes per pool — and a
                        # rebuild bench quote bytes-remaining —
                        # instead of the allocator-level '-'
                        row["bytes"] += int(
                            st.stat(coll, o)["size"])
                    except (IOError, KeyError):
                        pass      # torn object mid-fsck: count 0
        except (OSError, IOError):
            pass          # a store mid-fsck must not kill the report
        self._util_cache = (now, util)
        return util

    def _report_perf(self) -> None:
        """Ship this daemon's perf counters (histograms included) and
        store utilization to the mon's ClusterStats aggregator — the
        telemetry half of the heartbeat, next to the slow-op and
        store-health rollups."""
        now = time.time()
        if now - self._perf_reported < 1.0:
            return        # cheap cadence floor under fast heartbeats
        # heat BEFORE perf: _account_io bumps osd.io first and the
        # heat ledger second, so snapshotting in this order keeps
        # heat <= osd.io at every instant — the mon's agreement
        # assert depends on it
        heat = self.heat.dump()
        report = {"perf": _perf().dump_typed(), "heat": heat,
                  "util": self._store_util(), "ts": now}
        try:
            self.mon_client().call({"cmd": "report_perf",
                                    "osd": self.id,
                                    "report": report})
            self._perf_reported = now
        except (OSError, IOError):
            self._mon = None

    def _report_slow_ops(self) -> None:
        """Roll this process's slow-op summary up to the mon (PR 1's
        known gap: daemon trackers were only visible on their own
        asok).  Sent when nonzero, plus one zero report to clear the
        mon entry once the window drains."""
        try:
            s = _op_tracker().slow_ops_summary()
        except Exception:
            return
        n = int(s.get("num", 0))
        if n == 0 and not self._slow_reported:
            return
        try:
            self.mon_client().call({"cmd": "report_slow_ops",
                                    "osd": self.id, "summary": s})
            self._slow_reported = n
        except (OSError, IOError):
            self._mon = None

    def _heartbeat_loop(self, interval: float, grace: int) -> None:
        # the OUTER catch is the thread's survival contract: this
        # loop is the daemon's only path back into the map (boot
        # re-announce, failure reports, map fetch) — ANY exception
        # that kills it leaves an alive daemon marked down FOREVER,
        # so non-IO surprises (encoding errors on a mangled reply, a
        # handler bug) must log and retry next round, the same rule
        # the mon election loop follows.
        while not self._stop.is_set():
            time.sleep(interval)
            try:
                self._heartbeat_once(grace)
            except Exception as e:
                from ..common.log import dout
                dout("osd", 5, f"osd.{self.id} heartbeat round "
                               f"failed: {e!r}")
                self._mon = None

    def _heartbeat_once(self, grace: int) -> None:
        try:
            self._map = self.mon_client().call({"cmd": "get_map"})
        except (OSError, IOError):
            self._mon = None
            return
        self._report_slow_ops()
        self._report_store_health()
        self._report_perf()
        self._purge_dead_pools()
        up = self._map.get("osd_up", [])
        # spuriously marked down (missed heartbeats during a stall
        # or injected drops) but clearly alive: re-announce — the
        # reference OSD re-sends MOSDBoot when it sees itself down
        # in a newer map (OSD::_committed_osd_maps)
        if self.id < len(up) and not up[self.id]:
            try:
                self.mon_client().call(
                    {"cmd": "osd_boot", "osd": self.id})
            except (OSError, IOError):
                self._mon = None
        for peer in range(len(up)):
            if peer == self.id or not up[peer]:
                continue
            try:
                self.peer_client(peer).call({"cmd": "ping"})
                self._hb_misses[peer] = 0
            except (OSError, IOError):
                self.drop_peer(peer)
                self._hb_misses[peer] = \
                    self._hb_misses.get(peer, 0) + 1
                if self._hb_misses[peer] >= grace:
                    try:
                        self.mon_client().call(
                            {"cmd": "report_failure", "target": peer})
                    except (OSError, IOError):
                        self._mon = None

    def run_forever(self, hb_interval: float = 0.5,
                    hb_grace: int = 2) -> None:
        # boot must not be fatal: with socket-failure injection (or a
        # mon mid-restart) every call of a boot attempt can drop, and
        # a daemon that EXITS on that leaves a bound-but-dead socket
        # refusing connections forever — the reference OSD retries
        # mon contact indefinitely, so do we
        backoff = ExpBackoff(base=0.2, cap=2.0, seed=self.id)
        attempt = 0
        while True:
            try:
                self.boot()
                break
            except (OSError, IOError):
                backoff.sleep(attempt)
                attempt += 1
        t = threading.Thread(target=self._heartbeat_loop,
                             args=(hb_interval, hb_grace), daemon=True)
        t.start()
        try:
            while True:
                time.sleep(0.5)
        except KeyboardInterrupt:
            pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph-tpu-daemon")
    ap.add_argument("role", choices=["mon", "osd"])
    ap.add_argument("--cluster-dir", required=True)
    ap.add_argument("--id", type=int, default=0)
    ap.add_argument("--hb-interval", type=float, default=0.5)
    args = ap.parse_args(argv)
    if args.role == "mon":
        d = MonDaemon(args.cluster_dir, rank=args.id)
        d.run_forever()
    else:
        d = OSDDaemon(args.id, args.cluster_dir)
        d.run_forever(hb_interval=args.hb_interval)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
