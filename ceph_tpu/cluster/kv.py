"""KeyValueDB — the ordered-KV abstraction (src/kv/ role).

The reference wraps RocksDB behind `KeyValueDB` (src/kv/KeyValueDB.h,
RocksDBStore.cc; memdb for tests): prefixed keyspaces, atomic write
batches, ordered iteration and prefix scans.  The mon store
(MonitorDBStore) and BlueStore's metadata both sit on this seam.  Here:
a sorted in-memory implementation with the same contract — enough to
back the monitor's durable state and to keep the seam real for a future
native backend.
"""
from __future__ import annotations

import bisect
import struct
import threading
import zlib
from typing import Dict, Iterator, List, Optional, Tuple


class WriteBatch:
    """Atomic mutation set (KeyValueDB::Transaction role)."""

    def __init__(self):
        self.ops: List[Tuple[str, str, str, Optional[bytes]]] = []

    def set(self, prefix: str, key: str, value: bytes) -> "WriteBatch":
        self.ops.append(("set", prefix, key, bytes(value)))
        return self

    def rm(self, prefix: str, key: str) -> "WriteBatch":
        self.ops.append(("rm", prefix, key, None))
        return self

    def rm_prefix(self, prefix: str) -> "WriteBatch":
        self.ops.append(("rm_prefix", prefix, "", None))
        return self


def rm_object_rows(db: "MemDB", batch: WriteBatch, main_prefix: str,
                   objkey: str) -> None:
    """Queue removal of one object's main metadata row plus every
    ``objkey + "\\x00" + key`` xattr/omap row — the quarantine/remove
    row shape BlueStore and FileStore share (their KV layouts agree
    on the ``<objkey>\\0<key>`` scheme, so the scan lives once)."""
    batch.rm(main_prefix, objkey)
    start = objkey + "\x00"
    for prefix in ("xattr", "omap"):
        for k, _ in db.iterate(prefix, start=start):
            if not k.startswith(start):
                break
            batch.rm(prefix, k)


class MemDB:
    """Sorted dict KeyValueDB (src/kv/memdb role)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._data: Dict[Tuple[str, str], bytes] = {}
        self._keys: List[Tuple[str, str]] = []     # sorted
        self.batches_applied = 0

    # ------------------------------------------------------------- write --
    def submit(self, batch: WriteBatch) -> None:
        with self._lock:
            for op, prefix, key, value in batch.ops:
                if op == "set":
                    k = (prefix, key)
                    if k not in self._data:
                        bisect.insort(self._keys, k)
                    self._data[k] = value
                elif op == "rm":
                    k = (prefix, key)
                    if k in self._data:
                        del self._data[k]
                        i = bisect.bisect_left(self._keys, k)
                        del self._keys[i]
                elif op == "rm_prefix":
                    doomed = [k for k in self._keys if k[0] == prefix]
                    for k in doomed:
                        del self._data[k]
                    self._keys = [k for k in self._keys
                                  if k[0] != prefix]
            self.batches_applied += 1

    def set(self, prefix: str, key: str, value: bytes) -> None:
        self.submit(WriteBatch().set(prefix, key, value))

    # -------------------------------------------------------------- read --
    def get(self, prefix: str, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get((prefix, key))

    def exists(self, prefix: str, key: str) -> bool:
        return self.get(prefix, key) is not None

    def iterate(self, prefix: str, start: str = ""
                ) -> Iterator[Tuple[str, bytes]]:
        """Ordered iteration within a prefix from `start` (the
        KeyValueDB iterator contract)."""
        with self._lock:
            i = bisect.bisect_left(self._keys, (prefix, start))
            snapshot = []
            while i < len(self._keys) and self._keys[i][0] == prefix:
                k = self._keys[i]
                snapshot.append((k[1], self._data[k]))
                i += 1
        return iter(snapshot)

    def keys(self, prefix: str) -> List[str]:
        return [k for k, _ in self.iterate(prefix)]

    def state_digest(self) -> int:
        """crc32 over the full sorted (prefix, key, value) state —
        cheap whole-store equality for crash-consistency checks (two
        replay orders converged iff their digests match).  Length
        framing keeps adjacent fields from aliasing."""
        with self._lock:
            h = 0
            for k in self._keys:
                v = self._data[k]
                p = k[0].encode()
                key = k[1].encode()
                h = zlib.crc32(struct.pack("<III", len(p), len(key),
                                           len(v)), h)
                h = zlib.crc32(p + key + v, h)
            return h
