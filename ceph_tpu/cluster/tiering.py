"""Cache tiering: HitSet access tracking + tier agent (the last
src/osd/ feature-plane rows — HitSet.h, TierAgentState/PrimaryLogPG
agent_work, osd_types pg_hit_set_history_t).

Reference shape: a CACHE pool fronts a BASE pool; the OSD records
object accesses into per-PG HitSets (bloom / explicit) rotated on a
period, keeping the last N; the tier agent uses hit-set membership as
the temperature signal to EVICT clean cold objects when the cache
fills, and FLUSHES dirty objects back to the base pool; a read miss in
the cache PROMOTES the object from base.

Implemented as a proxy over the cluster simulator (the
objecter-with-cache-pool view librados clients get):

  * ``BloomHitSet`` / ``ExplicitHitSet`` — the HitSet impl family
    (src/osd/HitSet.h: BloomHitSet :146, ExplicitHashHitSet :250).
  * ``HitSetHistory`` — rotation by op-count period, last N kept
    (pool options hit_set_count / hit_set_period).
  * ``CacheTier`` — read/write proxy + agent_work(): flush dirty,
    evict cold-clean down to the target size (target_max_objects /
    cache_target_full_ratio roles).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

import numpy as np

from ..ops import hashing


class BloomHitSet:
    """Fixed-size Bloom filter over object names (BloomHitSet role).
    False positives over-estimate warmth (safe: keeps objects cached);
    never false-negative."""

    def __init__(self, bits: int = 4096, k: int = 4):
        self.bits = bits
        self.k = k
        self._bf = np.zeros(bits, dtype=bool)
        self.inserts = 0

    def _idx(self, name: str):
        h1 = hashing.str_hash_rjenkins(name.encode())
        h2 = hashing.str_hash_rjenkins((name + "#").encode()) | 1
        return [((h1 + i * h2) & 0xFFFFFFFF) % self.bits
                for i in range(self.k)]

    def insert(self, name: str) -> None:
        self._bf[self._idx(name)] = True
        self.inserts += 1

    def contains(self, name: str) -> bool:
        return bool(self._bf[self._idx(name)].all())


class ExplicitHitSet:
    """Exact membership (ExplicitHashHitSet role)."""

    def __init__(self):
        self._names: Set[str] = set()
        self.inserts = 0

    def insert(self, name: str) -> None:
        self._names.add(name)
        self.inserts += 1

    def contains(self, name: str) -> bool:
        return name in self._names


class HitSetHistory:
    """Rotating stack of recent hit sets (pg_hit_set_history_t)."""

    def __init__(self, count: int = 4, period_ops: int = 64,
                 kind: str = "bloom"):
        self.count = count
        self.period_ops = period_ops
        self.kind = kind
        self._current = self._make()
        self._ops = 0
        self.history: List[object] = []

    def _make(self):
        return BloomHitSet() if self.kind == "bloom" else ExplicitHitSet()

    def record(self, name: str) -> None:
        self._current.insert(name)
        self._ops += 1
        if self._ops >= self.period_ops:
            self.rotate()

    def rotate(self) -> None:
        self.history.append(self._current)
        if len(self.history) > self.count:
            self.history.pop(0)
        self._current = self._make()
        self._ops = 0

    def temperature(self, name: str) -> int:
        """How many recent hit sets saw this object (0..count+1)."""
        t = int(self._current.contains(name))
        return t + sum(1 for hs in self.history if hs.contains(name))


class CacheTier:
    """Cache-pool proxy over the simulator (tier agent included)."""

    def __init__(self, sim, cache_pool_id: int, base_pool_id: int, *,
                 target_max_objects: int = 16, hit_set_count: int = 4,
                 hit_set_period_ops: int = 64, hit_set_type: str = "bloom"):
        self.sim = sim
        self.cache = cache_pool_id
        self.base = base_pool_id
        self.target_max_objects = target_max_objects
        self.hitsets = HitSetHistory(hit_set_count, hit_set_period_ops,
                                     hit_set_type)
        self.dirty: Set[str] = set()
        self.stats = {"promotions": 0, "flushes": 0, "evictions": 0,
                      "cache_hits": 0, "cache_misses": 0}

    # ------------------------------------------------------------- state --
    def _in_cache(self, name: str) -> bool:
        return (self.cache, name) in self.sim.objects

    def cached_objects(self) -> List[str]:
        return sorted(n for (pid, n) in self.sim.objects
                      if pid == self.cache and "@" not in n)

    # --------------------------------------------------------------- I/O --
    def write(self, name: str, data: bytes) -> None:
        """Writes land in the cache tier and mark the object dirty
        (writeback mode)."""
        self.sim.put(self.cache, name, data)
        self.dirty.add(name)
        self.hitsets.record(name)

    def read(self, name: str) -> bytes:
        self.hitsets.record(name)
        if self._in_cache(name):
            self.stats["cache_hits"] += 1
            return self.sim.get(self.cache, name)
        # read miss: promote from base (proxy + promote policy)
        self.stats["cache_misses"] += 1
        data = self.sim.get(self.base, name)
        self.sim.put(self.cache, name, data)
        self.stats["promotions"] += 1
        return data

    # -------------------------------------------------------------- agent --
    def flush(self, name: str) -> None:
        """Write a dirty cache object back to the base tier."""
        if name in self.dirty:
            self.sim.put(self.base, name, self.sim.get(self.cache, name))
            self.dirty.discard(name)
            self.stats["flushes"] += 1

    def evict(self, name: str) -> None:
        """Drop a CLEAN object from the cache (flush first if dirty)."""
        self.flush(name)
        if self._in_cache(name):
            self.sim.delete(self.cache, name)
            self.stats["evictions"] += 1

    def agent_work(self) -> Dict[str, int]:
        """One agent pass (PrimaryLogPG::agent_work role): flush all
        dirty objects, then evict the COLDEST clean objects until the
        cache is back at target_max_objects.  Coldness = hit-set
        temperature, coldest first; ties evict lexicographically."""
        for name in sorted(self.dirty):
            self.flush(name)
        cached = self.cached_objects()
        excess = len(cached) - self.target_max_objects
        if excess > 0:
            by_temp = sorted(cached,
                             key=lambda n: (self.hitsets.temperature(n),
                                            n))
            for name in by_temp[:excess]:
                self.evict(name)
        return dict(self.stats)
