"""Cluster admin commands — the `ceph daemon` / `ceph tell` surface.

Registers cluster-level commands on an AdminServer (common/admin.py)
over a live sim/mon, mirroring the reference's most-used admin and mon
commands: status, df, osd tree, pg dump, scrub, snapshot listing,
health.  Everything returns JSON-able structures so the socket serving
path works unchanged.
"""
from __future__ import annotations

from typing import Any, Dict


def register_cluster_commands(server, sim, mon=None) -> None:
    m = sim.osdmap

    def status(args: Dict[str, Any]) -> Any:
        n = m.max_osd
        ex = m.osd_exists[:n]
        return {
            "epoch": m.epoch,
            "osds": {"total": int(ex.sum()),
                     "up": int((ex & m.osd_up[:n]).sum()),
                     "in": int(sum(1 for i in range(n)
                                   if ex[i] and m.osd_weight[i]))},
            "pools": {pid: {"name": p.name, "pg_num": p.pg_num,
                            "size": p.size, "type": p.type}
                      for pid, p in sorted(m.pools.items())},
            "objects": sum(1 for (pid, n2) in sim.objects
                           if "@" not in n2),
        }

    def df(args: Dict[str, Any]) -> Any:
        out: Dict[int, Dict[str, int]] = {}
        for (pid, name), info in sim.objects.items():
            if "@" in name:
                continue
            s = out.setdefault(pid, {"objects": 0, "bytes": 0})
            s["objects"] += 1
            s["bytes"] += info.size
        for pid in m.pools:
            out.setdefault(pid, {"objects": 0, "bytes": 0})
        return out

    def osd_tree(args: Dict[str, Any]) -> Any:
        from ..placement.treedump import tree_dump
        return tree_dump(m.crush)

    def pg_dump(args: Dict[str, Any]) -> Any:
        """Reports both the raw up sets AND the acting overlays
        (pg_temp/primary_temp) — during recovery the acting set is
        what serves I/O."""
        pid = int(args["pool"])
        pool = m.pools[pid]
        up, prim = m.map_pgs_batch(pid)
        out = {}
        for i in range(len(up)):
            row = {"up": [int(v) for v in up[i]],
                   "primary": int(prim[i])}
            if (pid, i) in m.pg_temp or (pid, i) in m.primary_temp:
                u2, p2, acting, actp = m.pg_to_up_acting_osds(pid, i)
                row["acting"] = acting
                row["acting_primary"] = actp
            out[i] = row
        return {"pool": pid, "pgs": out}

    def scrub(args: Dict[str, Any]) -> Any:
        from .scrub_machine import ScrubMachine, ScrubReservations
        pid = int(args["pool"])
        pool = m.pools[pid]
        pgs = sorted({sim.object_pg(pool, n)
                      for (p2, n) in sim.objects
                      if p2 == pid and "@" not in n})
        res = ScrubReservations()
        out = []
        for pg in pgs:
            r = ScrubMachine(sim, pid, pg,
                             reservations=res).run_to_completion()
            out.append({"pg": f"{pid}.{pg}",
                        "objects": r.objects_scrubbed,
                        "chunks": r.chunks,
                        "inconsistent": r.inconsistent,
                        "missing": r.missing})
        return out

    def snap_ls(args: Dict[str, Any]) -> Any:
        pid = int(args["pool"])
        return {str(sid): name
                for sid, name in sorted(m.pools[pid].snaps.items())}

    server.register("status", status)
    server.register("df", df)
    server.register("osd tree", osd_tree)
    server.register("pg dump", pg_dump)
    server.register("scrub", scrub)
    server.register("snap ls", snap_ls)
    if mon is not None:
        server.register(
            "health",
            lambda a: [
                {"code": c.code, "severity": c.severity,
                 "summary": c.summary}
                for c in mon.health(sim)])
