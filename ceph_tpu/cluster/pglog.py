"""PGLog — the bounded per-PG op log enabling delta recovery.

Role of the reference's PGLog (src/osd/PGLog.{h,cc}; design
doc/dev/osd_internals/log_based_pg.rst): every PG mutation appends a
versioned entry; after a failure, a returning replica's missing set is
computed by comparing its last-applied version against the
authoritative log — objects touched since are recovered INDIVIDUALLY
(log-based delta recovery), and only a replica whose gap has been
trimmed past falls back to backfill (full object scan).

Versions are (epoch, seq) like the reference's eversion_t; the log is
bounded (min_entries/max_entries trim policy, matching
osd_min_pg_log_entries/osd_max_pg_log_entries semantics).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

OP_MODIFY = 1
OP_DELETE = 2

Version = Tuple[int, int]     # (epoch, seq) — eversion_t
ZERO: Version = (0, 0)


@dataclass(frozen=True)
class LogEntry:
    version: Version
    obj: str
    op: int = OP_MODIFY


@dataclass
class MissingSet:
    """Objects a replica lacks (PGLog::missing role): obj -> version
    it needs; `backfill` set when the log no longer covers the gap."""
    need: Dict[str, Version] = field(default_factory=dict)
    deleted: Set[str] = field(default_factory=set)
    backfill: bool = False


class PGLog:
    """Authoritative bounded op log for one PG."""

    def __init__(self, max_entries: int = 3000):
        self.entries: List[LogEntry] = []
        self.max_entries = max_entries
        self.head: Version = ZERO         # newest version
        self.tail: Version = ZERO         # version BEFORE oldest entry
        self._seq = 0

    def append(self, epoch: int, obj: str, op: int = OP_MODIFY
               ) -> LogEntry:
        self._seq += 1
        e = LogEntry((epoch, self._seq), obj, op)
        self.entries.append(e)
        self.head = e.version
        self.trim()
        return e

    def trim(self, keep: Optional[int] = None) -> None:
        """Drop oldest entries beyond the bound (PGLog::trim)."""
        limit = keep if keep is not None else self.max_entries
        while len(self.entries) > limit:
            dropped = self.entries.pop(0)
            self.tail = dropped.version

    def entries_after(self, version: Version) -> List[LogEntry]:
        return [e for e in self.entries if e.version > version]

    def covers(self, version: Version) -> bool:
        """Can a replica at `version` catch up from the log alone?"""
        return version >= self.tail

    def missing_since(self, last_complete: Version) -> MissingSet:
        """The returning replica's missing set (PGLog::merge_log +
        missing calc collapsed): latest op per object since
        last_complete; backfill when the gap is trimmed away."""
        if not self.covers(last_complete):
            return MissingSet(backfill=True)
        need: Dict[str, Version] = {}
        deleted: Set[str] = set()
        for e in self.entries_after(last_complete):
            if e.op == OP_DELETE:
                need.pop(e.obj, None)
                deleted.add(e.obj)
            else:
                need[e.obj] = e.version
                deleted.discard(e.obj)
        return MissingSet(need=need, deleted=deleted)
