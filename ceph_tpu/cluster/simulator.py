"""Single-process cluster simulator — the end-to-end slice.

A memstore-backed fake cluster (the role of src/os/memstore/ + vstart.sh
in the reference's test strategy, SURVEY.md §4): N simulated OSDs hold
shard payloads in dicts; placement runs through the real OSDMap pipeline
(batched CRUSH on device); EC pools stripe/encode through the real codec
registry (batched bit-plane matmuls on device).

EC objects use the reference's stripewise shard layout (stripe_info_t,
src/osd/ECUtil.h:28-60): an object of S stripes stores, on shard j, the
concatenation of its S chunk-j slices — so `write(offset, len)` is a
read-modify-write through ceph_tpu.cluster.ec_rmw (the ECBackend
start_rmw / ExtentCache pipeline, src/osd/ECBackend.cc:1876) and
recovery rebuilds whole shard files with stripe-batched decodes.

put(object) → ps hash → PG → up set → store shards on OSDs
get(object) → gather surviving shards → minimum_to_decode → decode
write(object, offset, data) → RMW partial-stripe overwrite
kill/out OSDs → remap diff (old vs new batched mapping) → recover_all
rebuilds lost shards via batched decode and re-places them — the
ECBackend recovery flow (src/osd/ECBackend.cc:757,433,462) collapsed
into array programs (BASELINE config #5).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..common import faults
from ..ec import instance as ec_registry
from ..ec.interface import ErasureCodeError
from ..ops import hashing
from ..placement.crush_map import ITEM_NONE
from .ec_rmw import ExtentCache, RmwPipeline, StripeInfo
from .objectstore import (ChecksumError, MemStore, ObjectStoreError,
                          Transaction)
from .osdmap import OSDMap, PGPool, POOL_ERASURE, POOL_REPLICATED
from .pglog import OP_DELETE, PGLog, Version, ZERO

ShardKey = Tuple[int, int, str, int]   # (pool, pg, object, shard)

# HBM budget for one recovery window-gather ([G, S, k+m, U] chunks of
# the rebuild sweep materialize at most this many bytes each)
REBUILD_GATHER_BUDGET = 1 << 30

# device-store faultpoints (the bluestore read-error-injection role,
# bluestore_debug_inject_read_err): armed by the thrasher, disarmed in
# production — each fire site is a single dict-miss check when off
faults.declare("device.eio",
               "a shard read returns EIO (None) — degraded-read "
               "decode / replica failover / recovery retry must "
               "absorb it (bluestore read-error injection role)")
faults.declare("device.read_corruption",
               "a shard read returns payload bytes with one bit "
               "flipped — models media corruption below the checksum "
               "tier; deep scrub's parity re-encode is the detector")


class _StoreView:
    """Dict-style view of a SimOSD's shards (test/debug surface):
    iteration, lookup and raw assignment mapped onto the transactional
    ObjectStore underneath."""

    def __init__(self, osd: "SimOSD"):
        self._osd = osd

    def _keys(self):
        st = self._osd.objectstore
        for coll in st.list_collections():
            for oid in st.list_objects(coll):
                shard_s, name = oid.split(":", 1)
                yield (coll[0], coll[1], name, int(shard_s))

    def __iter__(self):
        return self._keys()

    def __contains__(self, key: ShardKey) -> bool:
        return self._osd.objectstore.exists(*SimOSD._split(key))

    def __getitem__(self, key: ShardKey) -> np.ndarray:
        try:
            data = self._osd.objectstore.read(*SimOSD._split(key))
        except ChecksumError:
            raise                             # corruption stays loud
        except ObjectStoreError:
            raise KeyError(key) from None     # dict contract
        return np.frombuffer(data, dtype=np.uint8).copy()

    def __setitem__(self, key: ShardKey, data: np.ndarray) -> None:
        # raw store poke (tests/debug): no liveness check, like the
        # plain dict this view replaces
        coll, oid = SimOSD._split(key)
        self._osd.dev.evict(key)   # poke supersedes any staged copy
        self._osd.objectstore.apply_transaction(
            Transaction().write_full(
                coll, oid, np.asarray(data, dtype=np.uint8).tobytes()))


class SimOSD:
    """A fake OSD: a transactional checksummed ObjectStore (memstore
    backend, src/os/memstore/ + ObjectStore.h roles) plus liveness and
    an HBM staging tier for EC shard plane words (device_store.py —
    the ECBackend shard-store role, src/osd/ECBackend.cc:934,1015)."""

    def __init__(self, osd_id: int):
        self.id = osd_id
        self.objectstore = MemStore()
        self.store = _StoreView(self)
        from .device_store import DeviceShardCache
        # owner id keys the OSD-shard -> chip staging-affinity
        # accounting when the sharded data plane is active
        self.dev = DeviceShardCache(owner=osd_id)
        self.alive = True
        # power-loss bookkeeping (the device.power_loss sim-tier fire
        # site): a browned-out OSD runs fsck(repair=True) on its next
        # boot and reports quarantined objects up the heartbeat so
        # the mon raises STORE_DAMAGED
        self.power_lost = False
        self.fsck_errors = 0
        # last applied PG version per (pool, pg) — the replica-side
        # state delta recovery compares against the authoritative log
        self.last_complete: Dict[Tuple[int, int], Version] = {}

    @staticmethod
    def _split(key: ShardKey):
        pool, pg, name, shard = key
        return (pool, pg), f"{shard}:{name}"

    def put(self, key: ShardKey, data: np.ndarray) -> None:
        if not self.alive:
            raise IOError(f"osd.{self.id} is dead")
        coll, oid = self._split(key)
        if faults.fire("device.power_loss", osd=self.id) is not None:
            # sim-tier power cut mid-write: a TORN shard lands with a
            # stale checksum and the OSD browns out — the durable
            # store is left in exactly the state boot-time
            # fsck(repair=True) exists to quarantine
            payload = np.asarray(data, dtype=np.uint8).tobytes()
            self.objectstore.apply_transaction(
                Transaction().write_full(coll, oid, payload))
            self.objectstore.corrupt(coll, oid)
            self.crash()
            self.alive = False
            self.power_lost = True
            raise IOError(f"osd.{self.id}: power loss mid-write")
        self.objectstore.apply_transaction(
            Transaction().write_full(
                coll, oid, np.asarray(data, dtype=np.uint8).tobytes()))
        self.dev.evict(key)      # byte write supersedes staged copy

    def get(self, key: ShardKey) -> Optional[np.ndarray]:
        if not self.alive:
            return None
        if faults.fire("device.eio", osd=self.id) is not None:
            return None      # injected EIO: same face as a bad csum
        dirty = self.dev.dirty_get(key)
        if dirty is not None:
            # dirty staged entry IS the authoritative copy (WAL role):
            # host readers get a readback of the device words, as bytes
            return np.asarray(dirty).view(np.uint8)
        coll, oid = self._split(key)
        try:
            data = self.objectstore.read(coll, oid)
        except ChecksumError:
            return None      # EIO: serve nothing, not bad bytes
        except ObjectStoreError:
            return None
        if data and faults.fire("device.read_corruption",
                                osd=self.id) is not None:
            # sub-checksum media corruption: one flipped bit in a COPY
            # (the durable bytes stay intact; deep scrub catches the
            # served lie via parity re-encode)
            buf = bytearray(data)
            buf[0] ^= 0x01
            return np.frombuffer(bytes(buf), dtype=np.uint8)
        # read-only view over the immutable bytes: shard readers never
        # mutate in place, and skipping the copy halves read traffic
        return np.frombuffer(data, dtype=np.uint8)

    def delete(self, key: ShardKey) -> None:
        if self.power_lost:
            # a browned-out daemon's durable store is FROZEN until it
            # reboots: the supersession sweeps that normally tidy
            # stale copies on dead OSDs cannot reach in and hide the
            # torn state boot-time fsck exists to find — the delete
            # simply never happens on this store
            return
        self.dev.evict(key)
        coll, oid = self._split(key)
        if self.objectstore.exists(coll, oid):
            self.objectstore.apply_transaction(
                Transaction().remove(coll, oid))

    def has(self, key: ShardKey) -> bool:
        """Cheap presence+integrity probe (no payload readback): a
        dirty staged entry counts; else the durable object must exist
        and pass its (lazily re-verified) checksum."""
        if not self.alive:
            return False
        if self.dev.dirty_get(key) is not None:
            return True
        return self.objectstore.verify(*self._split(key))

    def probe(self, key: ShardKey) -> int:
        """Presence + SIZE probe (the MissingLoc role extended with
        pg_info sizes): -1 when absent/dead/corrupt, else the shard's
        byte size — recovery plans its minimal fetch set from probes
        without moving a payload byte."""
        if not self.alive:
            return -1
        d = self.dev.dirty_get(key)
        if d is not None:
            return int(d.size)
        coll, oid = self._split(key)
        if not self.objectstore.verify(coll, oid):
            return -1
        try:
            return int(self.objectstore.stat(coll, oid)["size"])
        except ObjectStoreError:
            return -1

    def get_ranges(self, key: ShardKey,
                   ranges) -> Optional[np.ndarray]:
        """Sub-shard ranged read: only the requested (offset, length)
        byte ranges leave this OSD — the messenger-honest form of a
        regenerating-code helper read (Clay's repair sub-chunks)."""
        r = self.get(key)
        if r is None:
            return None
        return np.concatenate([r[int(o):int(o) + int(n)]
                               for o, n in ranges])

    # -------------------------------------------------- device staging --
    def _csum(self, coll, oid) -> Optional[int]:
        try:
            return self.objectstore.stat(coll, oid)["csum"]
        except ObjectStoreError:
            return None

    def put_device(self, key: ShardKey, arr,
                   data_bytes: Optional[bytes] = None) -> None:
        """Stage shard plane words in HBM.  ``data_bytes`` (the same
        bytes, host-side) is written through to the durable store when
        given; None defers durability to flush_device() (staged mode)."""
        if not self.alive:
            raise IOError(f"osd.{self.id} is dead")
        coll, oid = self._split(key)
        if data_bytes is not None:
            self.objectstore.apply_transaction(
                Transaction().write_full(coll, oid, data_bytes))
            self.dev.put(key, arr, self._csum(coll, oid))
        else:
            self.dev.put(key, arr, None)

    def get_device(self, key: ShardKey):
        """Shard as a device array: HBM hit, else upload from the
        durable bytes (checksum-verified) and stage for next time."""
        if not self.alive:
            return None
        if faults.fire("device.eio", osd=self.id) is not None:
            return None      # injected EIO on the device read path
        coll, oid = self._split(key)
        arr = self.dev.get(key, self._csum(coll, oid))
        if arr is not None:
            return arr
        try:
            data = self.objectstore.read(coll, oid)
        except (ChecksumError, ObjectStoreError):
            return None
        import jax.numpy as jnp
        from .device_store import as_ref
        # shard files are whole words (chunk % 32 == 0): upload in the
        # staged at-rest domain (int32 plane words)
        ref = as_ref(jnp.asarray(np.frombuffer(data, dtype="<i4")))
        self.dev.put(key, ref, self._csum(coll, oid))
        return ref

    def flush_device(self) -> int:
        """Write every dirty staged shard through to the durable store
        (the deferred-write/WAL flush). Returns shards flushed."""
        n = 0
        for key, arr in self.dev.dirty_items():
            coll, oid = self._split(key)
            self.objectstore.apply_transaction(
                Transaction().write_full(
                    coll, oid, np.asarray(arr).tobytes()))
            self.dev.mark_clean(key, self._csum(coll, oid))
            n += 1
        return n

    def crash(self) -> None:
        """Process death: unflushed staging (HBM) is lost; durable
        bytes survive — exactly a WAL-less deferred write's fate."""
        for key, _ in self.dev.dirty_items():
            self.dev.evict(key)


@dataclass
class ObjectInfo:
    """Client-side record of a written object."""
    size: int
    chunk_size: int          # per-stripe chunk bytes (EC) / size (rep)
    n_stripes: int = 1
    # --- SnapSet role (src/osd/osd_types.h SnapSet + SnapMapper) ---
    born_seq: int = 0        # pool snap_seq when the object appeared
    snap_seq: int = 0        # pool snap_seq at the last write
    clones: List[int] = field(default_factory=list)   # ascending ids
    clone_snaps: Dict[int, List[int]] = field(default_factory=dict)
    clone_sizes: Dict[int, int] = field(default_factory=dict)


class SimShardIO:
    """In-process ShardIO: the simulator half of the PGBackend seam
    (cluster/ec_backend.py).  Sub-writes ride each SimOSD's async
    queue -> mClock -> dispatch (the MOSDECSubOpWrite shape,
    src/osd/ECBackend.cc:1976); failed/homeless sub-ops purge stale
    copies so no older shard version is ever servable, and successes
    supersede strays (peering-time supersession)."""

    def __init__(self, sim: "ClusterSim", pool_id: int):
        self.sim = sim
        self.pool_id = pool_id

    def _pool(self):
        return self.sim.osdmap.pools[self.pool_id]

    def up_set(self, pg: int) -> List[int]:
        return self.sim.pg_up(self._pool(), pg)

    def fanout(self, writes):
        from ..msg.scheduler import CLASS_CLIENT
        sim = self.sim
        subs, committed = [], []
        for w in writes:
            op = {"kind": "put_dev",
                  "key": (self.pool_id, w.pg, w.name, w.shard),
                  "klass": CLASS_CLIENT, "data": w.bytes_fn()}
            try:
                op_id, ev = sim.services[w.target].call_async(
                    op, obj=w.ref)
            except IOError:
                self.purge_shard(w.pg, w.shard, w.name, None)
                continue
            subs.append((w, op_id, ev))
        for w, op_id, ev in subs:
            try:
                sim.services[w.target].wait_async(op_id, ev)
            except IOError:
                self.purge_shard(w.pg, w.shard, w.name, None)
                continue
            for o in sim.osds:      # success supersedes stale copies
                if o.id != w.target:
                    o.delete((self.pool_id, w.pg, w.name, w.shard))
            committed.append(w)
        return committed

    def purge_shard(self, pg: int, shard: int, name: str,
                    keep_target) -> None:
        for o in self.sim.osds:
            if o.id != keep_target:
                o.delete((self.pool_id, pg, name, shard))

    def get_shard_ref(self, pg: int, shard: int, name: str):
        up = self.up_set(pg)
        return self.sim._read_shard_dev(self.pool_id, pg, name,
                                        shard, up)

    def get_shard_bytes(self, pg: int, shard: int,
                        name: str) -> Optional[bytes]:
        up = self.up_set(pg)
        p = self.sim._read_shard(self.pool_id, pg, name, shard, up)
        return None if p is None else p.tobytes()

    def getattr(self, pg: int, name: str, shard: int,
                key: str) -> Optional[bytes]:
        info = self.sim.objects.get((self.pool_id, name))
        if info is None:
            return None
        vals = {"size": info.size, "S": info.n_stripes,
                "U": info.chunk_size}
        v = vals.get(key)
        return None if v is None else str(v).encode()


class ClusterSim:
    """OSDMap + memstore OSDs + codec data path, in one process."""

    def __init__(self, osdmap: OSDMap):
        self.osdmap = osdmap
        self.osds = [SimOSD(i) for i in range(osdmap.max_osd)]
        # every shard op flows queue -> mClock -> dispatch (the
        # ms_fast_dispatch/OpScheduler wiring; see osd_service.py);
        # services stop when the sim is dropped (finalizer) or
        # shutdown() is called — dispatcher threads must not accumulate
        # across many sims in one process
        from .osd_service import OSDService
        self.services = [OSDService(o) for o in self.osds]
        import weakref
        self._finalizer = weakref.finalize(
            self, ClusterSim._stop_services, self.services)
        self.codecs: Dict[int, object] = {}
        self._ec_backends: Dict[int, object] = {}
        self._tier_state: Dict[int, Dict] = {}
        from ..common.perf_counters import perf as _tier_perf
        self._pc_tier = _tier_perf("osd.tier")
        self.objects: Dict[Tuple[int, str], ObjectInfo] = {}
        self.ec_profiles: Dict[str, Dict[str, str]] = {}
        self.extent_cache = ExtentCache()
        self._rmw: Dict[int, RmwPipeline] = {}
        # authoritative per-PG op logs (PGLog role)
        self.pg_logs: Dict[Tuple[int, int], PGLog] = {}
        # snap -> object names reverse index (SnapMapper role)
        self.snap_index: Dict[Tuple[int, int], Set[str]] = {}
        # SnapSets of deleted heads (whiteouts): clones outlive them
        self.snapsets: Dict[Tuple[int, str], ObjectInfo] = {}
        # per-object watch registrations (Watch/Notify role)
        self._watches: Dict[Tuple[int, str], Dict[int, object]] = {}
        self._next_watch = 1
        # HBM staging flush policy: "eager" writes shard bytes through
        # to the durable store inside the op (non-staged semantics);
        # "staged" defers durability to flush_all() (deferred-write/WAL
        # shape — a crash before flush loses the staged writes)
        self.staging_flush = "eager"
        # (session, seq) -> [commit_count, recorded completion]: the
        # cluster-side half of the objecter's replay contract (the
        # pg-log reqid dup table role).  commit_count is the replay-
        # idempotency ORACLE: under a correct dedup it can never pass
        # 1 — the netsplit thrasher asserts exactly that.
        self._reqids: Dict[Tuple[str, int], List] = {}
        self.reqid_double_commits = 0

    @staticmethod
    def _stop_services(services) -> None:
        # signal every dispatcher + close queues first (wakes blocked
        # pops), then join — teardown stays O(50ms), not O(N * 50ms)
        for s in services:
            try:
                s.dispatcher._stop.set()
                s.in_q.close()
            except Exception:
                pass
        for s in services:
            try:
                s.dispatcher._thread.join(0.5)
            except Exception:
                pass

    def shutdown(self) -> None:
        """Stop dispatcher threads and close queues (idempotent)."""
        self._finalizer()

    # ------------------------------------------------- replay dedup --
    def reqid_cached(self, reqid: Tuple[str, int]):
        """[completion] when this op already committed durably (the
        replay must NOT re-apply), else None.  Returned boxed so a
        None completion stays distinguishable from a miss."""
        ent = self._reqids.get(tuple(reqid))
        return None if ent is None else [ent[1]]

    def reqid_commit(self, reqid: Tuple[str, int], result) -> None:
        """Record a durable commit of one logical op.  A second commit
        for the same reqid is the exact bug the session-replay
        machinery exists to prevent — counted, and asserted zero by
        the netsplit invariant set."""
        ent = self._reqids.get(tuple(reqid))
        if ent is None:
            self._reqids[tuple(reqid)] = [1, result]
            return
        ent[0] += 1
        self.reqid_double_commits += 1

    def reqid_stats(self) -> Dict[str, int]:
        return {"tracked": len(self._reqids),
                "double_commits": self.reqid_double_commits}

    def _log(self, pool_id: int, pg: int) -> PGLog:
        log = self.pg_logs.get((pool_id, pg))
        if log is None:
            log = self.pg_logs[(pool_id, pg)] = PGLog()
        return log

    def _log_write(self, pool_id: int, pg: int, name: str,
                   stored_osds) -> None:
        """Append a MODIFY entry and advance last_complete on the
        OSDs that durably applied this write and were current through
        the previous head (see _advance_lc)."""
        log = self._log(pool_id, pg)
        prev_head = log.head
        e = log.append(self.osdmap.epoch, name)
        self._advance_lc(pool_id, pg, stored_osds, prev_head,
                         e.version)

    def _advance_lc(self, pool_id: int, pg: int, osds, prev_head,
                    version) -> None:
        """Advance last_complete on OSDs that durably applied the log
        entry `version` — but only those already complete through the
        PREVIOUS head (the reference's last_complete contract):
        bumping an OSD with an unrecovered hole past the hole would
        hide every entry it missed from delta recovery, leaving the
        dropped shards unrepaired forever (latent data loss once
        enough other copies fail).  A lagging OSD catches up through
        recover_delta instead."""
        for o in osds:
            if self.osds[o].last_complete.get((pool_id, pg),
                                              ZERO) >= prev_head:
                self.osds[o].last_complete[(pool_id, pg)] = version

    # ------------------------------------------------------------- pools --
    def create_ec_profile(self, name: str, profile: Dict[str, str]) -> None:
        """Validates by instantiating the plugin, like the mon
        (src/mon/OSDMonitor.cc:7349-7444).  jax-plugin profiles that
        name no layout get the cluster default (bitsliced: shards at
        rest are the plane words the masked-XOR kernel consumes — the
        jerasure-packet-layout-at-rest property,
        src/erasure-code/jerasure/ErasureCodeJerasure.cc:162)."""
        from ..common.options import config
        profile = dict(profile)
        plugin = profile.get("plugin",
                             config().get("erasure_code_default_plugin"))
        if plugin == "jax" and "layout" not in profile:
            profile["layout"] = config().get(
                "erasure_code_default_layout")
        ec_registry().factory(plugin, profile)
        self.ec_profiles[name] = profile

    def codec_for(self, pool: PGPool):
        codec = self.codecs.get(pool.id)
        if codec is None:
            from ..common.options import config
            prof = self.ec_profiles[pool.erasure_code_profile]
            codec = ec_registry().factory(
                prof.get("plugin",
                         config().get("erasure_code_default_plugin")),
                prof)
            self.codecs[pool.id] = codec
        return codec

    def ec_backend(self, pool_id: int):
        """The shared ECBackend engine over this sim's SimShardIO —
        the SAME class the wire client drives (the PGBackend seam,
        src/osd/PGBackend.cc:571)."""
        be = self._ec_backends.get(pool_id)
        if be is None:
            from .ec_backend import ECBackend
            pool = self.osdmap.pools[pool_id]
            be = ECBackend(self.codec_for(pool),
                           SimShardIO(self, pool_id))
            self._ec_backends[pool_id] = be
        return be

    def _sinfo(self, pool: PGPool) -> StripeInfo:
        codec = self.codec_for(pool)
        return StripeInfo(codec.get_data_chunk_count(), pool.stripe_unit)

    def _pipeline(self, pool: PGPool) -> RmwPipeline:
        p = self._rmw.get(pool.id)
        if p is None:
            p = RmwPipeline(self.codec_for(pool), pool.stripe_unit,
                            cache=self.extent_cache)
            self._rmw[pool.id] = p
        return p

    # ---------------------------------------------------------- placement --
    def object_pg(self, pool: PGPool, name: str) -> int:
        ps = hashing.str_hash_rjenkins(name.encode())
        return pool.raw_pg_to_pg(ps)

    def pg_up(self, pool: PGPool, pg: int) -> List[int]:
        """Acting/up set for a PG, cached per map epoch (the client's
        cached-OSDMap target calc, Objecter::_calc_target — placement
        is recomputed only when the map changes)."""
        cache = getattr(self, "_up_cache", None)
        if cache is None or cache[0] != self.osdmap.epoch:
            cache = self._up_cache = (self.osdmap.epoch, {})
        hit = cache[1].get((pool.id, pg))
        if hit is not None:
            return hit
        up, _, acting, _ = self.osdmap.pg_to_up_acting_osds(pool.id, pg)
        out = acting or up
        cache[1][(pool.id, pg)] = out
        return out

    # ------------------------------------------------------- shard access --
    def _device_staging(self, codec=None) -> bool:
        """HBM staging applies when enabled AND the pool's codec has a
        device data path (jax/bitmatrix plugins); layered codecs
        (lrc/shec/clay) keep the host path."""
        from ..common.options import config
        if not config().get("osd_device_staging"):
            return False
        # the staged data plane runs in the int32 word domain (no
        # u8<->i32 bitcasts — see plugin_jax.encode_words_device);
        # codecs without word-domain kernels use the host path
        return codec is None or (
            hasattr(codec, "encode_words_device") and
            getattr(codec, "layout", None) == "bitsliced")

    def _shard_sources(self, up: List[int], shard: int) -> List[int]:
        tgt = up[shard] if shard < len(up) else ITEM_NONE
        return ([tgt] if tgt != ITEM_NONE else []) + \
            [o.id for o in self.osds]

    def _read_shard(self, pool_id: int, pg: int, name: str, shard: int,
                    up: List[int]) -> Optional[np.ndarray]:
        """Up set first, then any live OSD (stale-map/pre-recovery).
        Reads travel through the OSD's queue/scheduler front end; a
        dropped op (msg.drop_op injection) reads as source-unavailable
        and fails over to the next holder."""
        for o in self._shard_sources(up, shard):
            try:
                p = self.services[o].get((pool_id, pg, name, shard))
            except IOError:
                continue
            if p is not None:
                return p
        return None

    def _write_shard(self, pool_id: int, pg: int, name: str, shard: int,
                     up: List[int],
                     payload: np.ndarray) -> Optional[int]:
        """Place one host-byte shard on its mapped home (the staged
        device path fans out through the ECBackend/SimShardIO seam
        instead)."""
        tgt = up[shard] if shard < len(up) else ITEM_NONE
        if tgt == ITEM_NONE:
            # degraded write: the shard is homeless.  Stale copies of
            # the PREVIOUS version must not survive — the any-live-OSD
            # read fallback would otherwise mix shard versions and
            # decode garbage (the real system prevents this with
            # per-shard versions + peering; the simulator's equivalent
            # is deleting the outdated copy).
            for o in self.osds:
                o.delete((pool_id, pg, name, shard))
            return None
        try:
            # the op enters through the target's queue -> mClock ->
            # dispatch (stale-purge sweeps below stay direct: they model
            # peering-time supersession, not messenger traffic)
            self.services[tgt].put((pool_id, pg, name, shard), payload)
        except IOError:
            # undetected-dead target: same as homeless — purge stale
            # copies so no older version can be served
            for o in self.osds:
                o.delete((pool_id, pg, name, shard))
            return None
        # a successful write also supersedes any stray stale copies
        for o in self.osds:
            if o.id != tgt:
                o.delete((pool_id, pg, name, shard))
        return tgt

    def _read_shard_dev(self, pool_id: int, pg: int, name: str,
                        shard: int, up: List[int]):
        """Device-domain shard read: HBM staging tier first (upload on
        miss), same source order as _read_shard.  Sources are
        pre-filtered by the host-side presence probe — the MissingLoc
        role (src/osd/MissingLoc.h: peering tells the primary exactly
        which OSDs hold a shard; it never polls the whole cluster)."""
        key = (pool_id, pg, name, shard)
        for o in self._shard_sources(up, shard):
            if not self.osds[o].has(key):
                continue
            try:
                a = self.services[o].get_device(key)
            except IOError:
                continue       # dropped op: next holder
            if a is not None:
                return a
        return None

    @staticmethod
    def _to_words(a, S: int, k: int, U: int):
        """Any payload form -> [S, k, U/4] int32 plane words (the
        staged at-rest domain).  Host bytes reinterpret for free; a
        device u8 array needs a bitcast dispatch (fine at small sizes;
        bulk clients hand words directly — see put_many_from_device)."""
        import jax.numpy as jnp
        W = U // 4
        if isinstance(a, np.ndarray):
            return jnp.asarray(
                np.ascontiguousarray(a).view(np.int32).reshape(S, k, W))
        if a.dtype == jnp.int32:
            return a if a.shape == (S, k, W) else a.reshape(S, k, W)
        import jax
        u8 = a if a.shape == (S, k, U) else a.reshape(S, k, U)
        return jax.lax.bitcast_convert_type(
            u8.reshape(S, k, W, 4), jnp.int32)

    def _place_shards_dev(self, pool_id: int, pg: int, name: str,
                          up: List[int], codec, payload, S: int,
                          U: int,
                          dchunks_host: Optional[np.ndarray] = None
                          ) -> List[int]:
        """Encode + fan out one object's shards through the shared
        ECBackend engine (encode dispatch -> zero-copy column refs ->
        SimShardIO sub-op fan-out).  Eager flush takes durable bytes
        from ``dchunks_host`` when the caller already has them, else
        from one readback per buffer."""
        from .ec_backend import ObjectGeom
        be = self.ec_backend(pool_id)
        geom = ObjectGeom(S * be.k * U, S, U)
        writes = be.encode_to_writes(
            {name: pg}, [name], payload, geom,
            durable=(self.staging_flush == "eager"),
            d_host=dchunks_host)
        acked = be.submit_loose(writes)
        return [t for _, t in sorted(acked.get(name, {}).items())]

    def _gather_decode_dev(self, pool: PGPool, name: str,
                           info: ObjectInfo, pg: int, up: List[int]):
        """Assemble the object payload in the device domain through
        the shared ECBackend engine: gather staged shard refs, decode
        missing data chunks with the masked-XOR kernel, stitch columns
        — ~one dispatch per stage over shared packed buffers (shared
        by get / get_to_device; the handle_sub_read_reply -> decode
        flow, src/osd/ECBackend.cc:1183).  Returns the int32
        [S, k, U/4] word-domain stripe view on device (untrimmed — see
        assemble_object; bytes == the u8 view, little-endian)."""
        from .ec_backend import ObjectGeom
        be = self.ec_backend(pool.id)
        U, S = info.chunk_size, info.n_stripes
        files = {}
        for shard in range(be.n):
            r = self._read_shard_dev(pool.id, pg, name, shard, up)
            if r is not None and r.size >= S * U:
                files[shard] = r
        try:
            return be.assemble_object_words(
                files, ObjectGeom(info.size, S, U))
        except IOError:
            raise IOError(f"object {name}: unrecoverable "
                          f"(only shards {sorted(files)})") from None

    def _new_info(self, pool: PGPool, name: str, size: int, chunk: int,
                  n_str: int = 1) -> ObjectInfo:
        """Fresh ObjectInfo carrying over snapshot lineage (SnapSet) —
        including from a deleted head's whiteout record."""
        prev = self.objects.get((pool.id, name))
        reborn = prev is None and \
            (pool.id, name) in self.snapsets
        if prev is None:
            prev = self.snapsets.pop((pool.id, name), None)
        # a recreated object's birth moves to NOW: snaps taken during
        # the deletion interval must read as absent, while older clones
        # stay resolvable (get_snap checks clones before born_seq)
        info = ObjectInfo(size, chunk, n_str,
                          born_seq=pool.snap_seq if (prev is None or
                                                     reborn)
                          else prev.born_seq,
                          snap_seq=pool.snap_seq)
        if prev is not None:
            info.clones = prev.clones
            info.clone_snaps = prev.clone_snaps
            info.clone_sizes = prev.clone_sizes
        return info

    # ---------------------------------------------------------- snapshots --
    def snap_create(self, pool_id: int, snap_name: str) -> int:
        """Pool snapshot: bump the pool's snap context
        (pg_pool_t::snap_seq + snaps; OSDMonitor prepare_pool_op).
        Clones appear lazily on the next write per object.

        Idempotent on name (both tiers agree): re-creating an existing
        snapshot name returns the existing id rather than minting a
        second snapshot — the reference refuses duplicates outright
        (EEXIST, OSDMonitor prepare_pool_op), and the process tier's
        mon_call retry path additionally needs same-name retries to
        land on one id."""
        pool = self.osdmap.pools[pool_id]
        if pool.write_tier >= 0:
            raise IOError("pool snapshots on a tiered base pool "
                          "unsupported (COW would run against the "
                          "cache pool's snap context)")
        for sid, nm in pool.snaps.items():
            if nm == snap_name:
                return sid
        pool.snap_seq += 1
        pool.snaps[pool.snap_seq] = snap_name
        return pool.snap_seq

    def snap_lookup(self, pool_id: int, snap_name: str) -> int:
        pool = self.osdmap.pools[pool_id]
        for sid, nm in pool.snaps.items():
            if nm == snap_name:
                return sid
        raise KeyError(f"no snapshot {snap_name!r} in pool {pool_id}")

    def _maybe_clone(self, pool: PGPool, name: str) -> None:
        """Copy-on-write: before the first mutation after a snapshot,
        preserve the head as a clone object (PrimaryLogPG
        make_writeable role) and index it in the SnapMapper."""
        info = self.objects.get((pool.id, name))
        if info is None or info.snap_seq >= pool.snap_seq:
            return
        covered = [s for s in sorted(pool.snaps)
                   if info.snap_seq < s <= pool.snap_seq]
        if not covered:
            info.snap_seq = pool.snap_seq
            return
        cid = pool.snap_seq
        data = self.get(pool.id, name)
        self.put(pool.id, f"{name}@{cid}", data)   # clone shards placed
        info.clones.append(cid)
        info.clone_snaps[cid] = covered
        info.clone_sizes[cid] = info.size
        info.snap_seq = pool.snap_seq
        pg = self.object_pg(pool, name)
        up = self.pg_up(pool, pg)
        prim = next((o for o in up if o != ITEM_NONE), None)
        for s in covered:
            self.snap_index.setdefault((pool.id, s), set()).add(name)
        if prim is not None:
            # omap mirror of the SnapMapper rows on the primary
            # (src/osd/SnapMapper.cc "SNA_" keyspace)
            st = self.osds[prim].objectstore
            txn = Transaction()
            meta_oid = "meta:snapmapper"
            if not st.exists((pool.id, pg), meta_oid):
                txn.touch((pool.id, pg), meta_oid)
            for s in covered:
                txn.omap_set((pool.id, pg), meta_oid,
                             f"SNA_{s:016x}_{name}", b"")
            st.apply_transaction(txn)

    def get_snap(self, pool_id: int, name: str, snap_id: int) -> bytes:
        """Read an object's state AT a snapshot: resolve through the
        SnapSet (clone covering the snap, else the unchanged head)."""
        pool = self.osdmap.pools[pool_id]
        info = self.objects.get((pool_id, name)) or \
            self.snapsets.get((pool_id, name))
        if info is None:
            raise KeyError(f"object {name} has no state at all")
        # clones first: they can cover snaps older than a rebirth
        for c in info.clones:
            if snap_id in info.clone_snaps.get(c, ()):
                return self.get(pool_id, f"{name}@{c}")
        if snap_id <= info.born_seq:
            raise KeyError(
                f"object {name} did not exist at snap {snap_id}")
        if (pool_id, name) not in self.objects:
            raise KeyError(f"object {name} deleted before snap "
                           f"{snap_id} saw further writes")
        return self.get(pool_id, name)

    def snap_rollback(self, pool_id: int, name: str, snap_id: int) -> None:
        """Restore the head to its state at the snapshot (rollback op;
        the current head is itself preserved by COW first)."""
        data = self.get_snap(pool_id, name, snap_id)
        self.put(pool_id, name, data)

    def snap_objects(self, pool_id: int, snap_id: int) -> List[str]:
        """SnapMapper query surface: objects with a clone for snap."""
        return sorted(self.snap_index.get((pool_id, snap_id), ()))

    def snap_remove(self, pool_id: int, snap_id: int) -> int:
        """Delete a pool snapshot and TRIM: clones covering no
        remaining snap are purged (the snap-trimmer role).  Returns
        the number of clone objects removed."""
        pool = self.osdmap.pools[pool_id]
        pool.snaps.pop(snap_id, None)
        trimmed = 0
        for name in self.snap_index.pop((pool_id, snap_id), set()):
            info = self.objects.get((pool_id, name)) or \
                self.snapsets.get((pool_id, name))
            if info is None:
                continue
            for c in list(info.clones):
                snaps = info.clone_snaps.get(c, [])
                if snap_id in snaps:
                    snaps.remove(snap_id)
                if not snaps:
                    info.clones.remove(c)
                    info.clone_snaps.pop(c, None)
                    info.clone_sizes.pop(c, None)
                    self.delete(pool_id, f"{name}@{c}")
                    trimmed += 1
            if not info.clones and \
                    (pool_id, name) not in self.objects:
                self.snapsets.pop((pool_id, name), None)
        return trimmed

    # ---------------------------------------------------------- pg split --
    def reshard_pool(self, pool_id: int, new_pg_num: int,
                     bump_epoch: bool = True,
                     old_pg_num: Optional[int] = None) -> Dict[str, int]:
        """PG split/merge: change pg_num and MOVE every object whose
        placement group changed to its new home (the role of Ceph's
        incremental PG splitting, pg_num/pgp_num bumps + PastIntervals;
        collapsed here to one batched reshard pass).  Snapshot clones
        move with their heads' namespaces.

        Safety: an old-home shard copy is deleted ONLY once its new
        home durably holds it — a shard whose target is unmapped or
        dead stays where it is (degraded, recoverable later), never
        destroyed.  ``old_pg_num`` lets mon-backed callers reshard
        AFTER the map change committed (the old geometry can no longer
        be read off the pool then)."""
        pool = self.osdmap.pools[pool_id]
        if old_pg_num is None:
            old_pg_num = pool.pg_num
        if new_pg_num == old_pg_num and pool.pg_num == new_pg_num:
            return {"objects_moved": 0, "shards_moved": 0,
                    "shards_stranded": 0}
        names = [n for (pid, n) in self.objects if pid == pool_id]
        # old pgs under the OLD geometry, regardless of current state
        cur = (pool.pg_num, pool.pgp_num)
        pool.pg_num = pool.pgp_num = old_pg_num
        old_pgs = {n: self.object_pg(pool, n) for n in names}
        pool.pg_num, pool.pgp_num = cur
        pool.pg_num = new_pg_num
        pool.pgp_num = new_pg_num
        if bump_epoch:
            # standalone sims advance the epoch directly; mon-backed
            # callers commit an incremental instead (a direct bump
            # would gap the mon's incremental stream)
            self.osdmap.bump_epoch()
        stats = {"objects_moved": 0, "shards_moved": 0,
                 "shards_stranded": 0}
        n_shards = pool.size
        for n in names:
            new_pg = self.object_pg(pool, n)
            old_pg = old_pgs[n]
            if new_pg == old_pg:
                continue
            new_up = self.pg_up(pool, new_pg)
            moved = 0
            placed_members: Set[int] = set()
            for shard in range(n_shards):
                payload = None
                for osd in self.osds:         # any holder of the shard
                    p = osd.get((pool_id, old_pg, n, shard))
                    if p is not None:
                        payload = p
                        break
                if payload is None:
                    continue
                placed_this = False
                if pool.type == POOL_REPLICATED:
                    for osd_id in [o for o in new_up if o != ITEM_NONE]:
                        try:
                            self.services[osd_id].put_recovery(
                                (pool_id, new_pg, n, shard), payload)
                        except IOError:
                            continue          # undetected-dead member
                        placed_members.add(osd_id)
                        placed_this = True
                        moved += 1
                else:
                    tgt = new_up[shard] if shard < len(new_up) \
                        else ITEM_NONE
                    if tgt != ITEM_NONE and self.osds[tgt].alive:
                        try:
                            self.services[tgt].put_recovery(
                                (pool_id, new_pg, n, shard), payload)
                            placed_members.add(tgt)
                            placed_this = True
                            moved += 1
                        except IOError:
                            pass
                if not placed_this:
                    # mapped home unavailable: park the shard under its
                    # NEW pg key on ANY live OSD so the any-live-OSD
                    # read fallback and recover_all can still find it
                    # (old-pg keys are invisible to the new geometry)
                    for osd in self.osds:
                        if not osd.alive:
                            continue
                        try:
                            self.services[osd.id].put_recovery(
                                (pool_id, new_pg, n, shard), payload)
                            placed_this = True
                            stats["shards_stranded"] += 1
                            break
                        except IOError:
                            continue
                if placed_this:
                    for osd in self.osds:      # old copy superseded
                        osd.delete((pool_id, old_pg, n, shard))
                # else: NO live OSD anywhere — the old-pg copy is the
                # only copy; leave it untouched
            if moved:
                stats["objects_moved"] += 1
                stats["shards_moved"] += moved
                # only members that durably RECEIVED shards advance
                # (a skipped member must stay delta-recoverable)
                self._log_write(pool_id, new_pg, n, placed_members)
        return stats

    # ------------------------------------------------------ object classes --
    def exec_cls(self, pool_id: int, name: str, cls: str, method: str,
                 inp: bytes = b"") -> bytes:
        """Execute a registered object-class method INSIDE the primary
        OSD against the object (the CEPH_OSD_OP_CALL path through
        ClassHandler, src/osd/ClassHandler.cc)."""
        from ..placement.crush_map import ITEM_NONE
        if not hasattr(self, "class_handler"):
            from .class_handler import ClassHandler
            self.class_handler = ClassHandler()
        pool = self.osdmap.pools[pool_id]
        if pool.type == POOL_ERASURE:
            # the reference likewise rejects class ops needing
            # omap/xattr state on EC pools (pool requires_*)
            raise IOError("object classes require a replicated pool")
        pg = self.object_pg(pool, name)
        up = self.pg_up(pool, pg)
        prim = next((o for o in up if o != ITEM_NONE), None)
        if prim is None:
            raise IOError(f"{name}: no primary for cls call")
        return self.class_handler.call(
            self.osds[prim].objectstore, (pool_id, pg), f"0:{name}",
            cls, method, inp)

    # -------------------------------------------------------- watch/notify --
    def watch(self, pool_id: int, name: str, callback) -> int:
        """Register interest in an object (Watch role,
        src/osd/Watch.cc); ``callback(notify_id, payload) -> ack``."""
        wid = self._next_watch
        self._next_watch += 1
        self._watches.setdefault((pool_id, name), {})[wid] = callback
        return wid

    def unwatch(self, pool_id: int, name: str, watch_id: int) -> None:
        self._watches.get((pool_id, name), {}).pop(watch_id, None)

    def notify(self, pool_id: int, name: str,
               payload: bytes = b"") -> Dict[int, object]:
        """Deliver to every watcher, gather acks (Notify role); a
        raising watcher is recorded as a timeout (None ack)."""
        nid = self._next_watch
        self._next_watch += 1
        acks: Dict[int, object] = {}
        for wid, cb in list(self._watches.get((pool_id, name),
                                              {}).items()):
            try:
                acks[wid] = cb(nid, payload)
            except Exception:
                acks[wid] = None
        return acks

    # --------------------------------------------------------------- I/O --
    # ------------------------------------------------- cache-tier ops --
    def tier_add(self, base_id: int, cache_id: int,
                 mode: str = "writeback") -> None:
        """Wire a cache pool over a base pool (pg_pool_t tier_of /
        read_tier / write_tier; OSDMonitor 'osd tier add' +
        'tier cache-mode')."""
        base, cache = self.osdmap.pools[base_id], \
            self.osdmap.pools[cache_id]
        if mode != "writeback":
            raise IOError(f"cache mode {mode!r} not implemented "
                          f"(writeback only)")
        if base_id == cache_id:
            raise IOError("tier add: base == cache")
        if base.read_tier >= 0 or base.tier_of >= 0 or \
                cache.tier_of >= 0 or cache.read_tier >= 0:
            # no re-tiering AND no chains: a pool that is itself a
            # cache (or already fronted) would misroute puts/reads
            raise IOError("tier add: pool already tiered")
        if cache.type != POOL_REPLICATED:
            raise IOError("cache tier must be a replicated pool")
        if base.type != POOL_REPLICATED:
            # the whole-object COPY_FROM op path would read one shard
            # of an EC object as if it were the object — refuse rather
            # than corrupt (EC-base tiering needs a sharded copy path;
            # tracked gap)
            raise IOError("tiering over an EC base pool unsupported")
        if base.snaps:
            # tier routing would run COW against the cache pool's
            # empty snap context and silently skip clones (seq may
            # outlive deleted snapshots; live snaps are the hazard)
            raise IOError("tiering over a snapshotted pool "
                          "unsupported")
        cache.tier_of = base_id
        cache.cache_mode = mode
        base.read_tier = cache_id
        base.write_tier = cache_id
        self._tier_hits(base_id)

    def tier_remove(self, base_id: int, cache_id: int) -> None:
        """Unwire a tier.  Refused until the cache pool is DRAINED
        (flush dirty + evict) — the reference's 'osd tier remove'
        refuses too, because unwiring with data still in the cache
        strands acknowledged writes out of the read path."""
        cached = [nm for (pid, nm) in self.objects if pid == cache_id]
        if cached:
            raise IOError(f"tier remove: cache pool still holds "
                          f"{len(cached)} objects — drain first "
                          f"(tier_agent_work + evict)")
        self.osdmap.pools[cache_id].tier_of = -1
        self.osdmap.pools[cache_id].cache_mode = ""
        self.osdmap.pools[base_id].read_tier = -1
        self.osdmap.pools[base_id].write_tier = -1

    def copy_from(self, dst_pool: int, dst_name: str,
                  src_pool: int, src_name: str) -> List[int]:
        """The COPY_FROM op (src/osd/PrimaryLogPG.cc:5886): the
        destination reads the source object server-side and commits
        it as a normal logged write — the building block of tier
        promote/flush and rbd clone flatten.  Raw (tier-routing
        bypassed): callers ARE the tier machinery."""
        data = self._get_raw(src_pool, src_name)
        return self._put_raw(dst_pool, dst_name, data)

    def _tier_hits(self, base_id: int):
        st = self._tier_state.setdefault(base_id, None)
        if st is None:
            from .tiering import HitSetHistory
            st = self._tier_state[base_id] = {
                "dirty": set(), "hits": HitSetHistory()}
        return st

    def tier_promote(self, base_id: int, name: str) -> None:
        """Promote on read-miss through the op engine
        (PrimaryLogPG::promote_object, :3932): COPY_FROM base ->
        cache; the promoted copy starts CLEAN."""
        pool = self.osdmap.pools[base_id]
        self.copy_from(pool.read_tier, name, base_id, name)
        self._pc_tier.inc("promote_ops")

    def tier_flush(self, base_id: int, name: str) -> None:
        """Writeback flush: dirty cache object demotes to the base
        tier as a COPY_FROM (agent_flush -> do_copy_from shape)."""
        pool = self.osdmap.pools[base_id]
        self.copy_from(base_id, name, pool.write_tier, name)
        self._tier_hits(base_id)["dirty"].discard(name)
        self._pc_tier.inc("flush_ops")

    def tier_evict(self, base_id: int, name: str) -> None:
        """Evict a CLEAN cache object (agent_evict): dirty objects
        must flush first."""
        st = self._tier_hits(base_id)
        if name in st["dirty"]:
            raise IOError(f"{name}: dirty, flush before evict")
        pool = self.osdmap.pools[base_id]
        self.delete(pool.read_tier, name)
        self._pc_tier.inc("evict_ops")

    def tier_agent_work(self, base_id: int,
                        target_objects: int = 0) -> Dict[str, int]:
        """The tier agent pass: flush every dirty object, then evict
        cold clean ones down to ``target_objects`` (agent_work)."""
        st = self._tier_hits(base_id)
        pool = self.osdmap.pools[base_id]
        cache_id = pool.read_tier
        stats = {"flushed": 0, "evicted": 0}
        for name in sorted(st["dirty"]):
            self.tier_flush(base_id, name)
            stats["flushed"] += 1
        cached = [nm for (pid, nm) in list(self.objects)
                  if pid == cache_id]
        if target_objects and len(cached) > target_objects:
            cold = sorted(cached,
                          key=lambda nm:
                          st["hits"].temperature(nm))
            for nm in cold[:len(cached) - target_objects]:
                self.tier_evict(base_id, nm)
                stats["evicted"] += 1
        return stats

    def put(self, pool_id: int, name: str, data: bytes) -> List[int]:
        pool = self.osdmap.pools[pool_id]
        if pool.write_tier >= 0 and "@" not in name:
            # writeback cache: the write LANDS in the cache tier and
            # marks the object dirty; the base copy goes stale until
            # the agent/flush demotes (PrimaryLogPG writeback mode)
            placed = self._put_raw(pool.write_tier, name, data)
            st = self._tier_hits(pool_id)
            st["dirty"].add(name)
            st["hits"].record(name)
            return placed
        return self._put_raw(pool_id, name, data)

    def _put_raw(self, pool_id: int, name: str,
                 data: bytes) -> List[int]:
        pool = self.osdmap.pools[pool_id]
        if "@" not in name:
            self._maybe_clone(pool, name)
        pg = self.object_pg(pool, name)
        up = self.pg_up(pool, pg)
        if pool.type == POOL_REPLICATED:
            payload = np.frombuffer(data, dtype=np.uint8)
            placed = []
            for o in up:
                if o == ITEM_NONE:
                    continue
                try:
                    self.services[o].put((pool_id, pg, name, 0), payload)
                except IOError:
                    continue     # undetected-dead OSD (fail_osd state)
                placed.append(o)
            if not placed:
                # nothing landed: the write FAILED — do not destroy the
                # previous version or record the new one
                raise IOError(f"object {name}: no replica writable")
            # supersede stale replicas (incl. on down OSDs) so a revived
            # OSD can never serve an older version — see _write_shard
            for o in self.osds:
                if o.id not in placed:
                    o.delete((pool_id, pg, name, 0))
            self.objects[(pool_id, name)] = self._new_info(
                pool, name, len(data), len(data))
            self._log_write(pool_id, pg, name, placed)
            return placed
        codec = self.codec_for(pool)
        k, mm = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
        si = self._sinfo(pool)
        n_str = max(1, si.stripe_count(len(data)))
        buf = np.zeros(n_str * si.stripe_width, dtype=np.uint8)
        buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        dchunks = buf.reshape(n_str, k, si.chunk_size)
        if self._device_staging(codec):
            # device data plane: ONE host->device upload of the object
            # (the np buffer reinterprets as words for free in
            # _to_words), one word-domain encode dispatch, shard
            # columns staged zero-copy in each target's HBM tier (the
            # at-rest layout IS the kernel operand layout —
            # ECBackend.cc:934 / jerasure packet role)
            placed = self._place_shards_dev(
                pool_id, pg, name, up, codec, buf,
                n_str, si.chunk_size, dchunks_host=dchunks)
        else:
            placed = []
            parity = np.asarray(codec.encode_chunks_batch(dchunks))
            full = np.concatenate([dchunks, parity], axis=1)  # [S,k+m,U]
            for shard in range(k + mm):
                tgt = self._write_shard(pool_id, pg, name, shard, up,
                                        full[:, shard].reshape(-1))
                if tgt is not None:
                    placed.append(tgt)
        self.extent_cache.invalidate_object((pool_id, name))
        self.objects[(pool_id, name)] = self._new_info(
            pool, name, len(data), si.chunk_size, n_str)
        self._log_write(pool_id, pg, name, set(placed))
        return placed

    def _gather_stripes(self, pool: PGPool, name: str, info: ObjectInfo,
                        stripes: List[int]) -> Dict[int, np.ndarray]:
        """Materialize OLD data chunks [k, U] for the given stripes,
        decoding degraded ones (batched per erasure signature)."""
        codec = self.codec_for(pool)
        k, mm = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
        U = info.chunk_size
        pg = self.object_pg(pool, name)
        up = self.pg_up(pool, pg)
        shard_files: Dict[int, Optional[np.ndarray]] = {}
        for shard in range(k + mm):
            f = self._read_shard(pool.id, pg, name, shard, up)
            if f is not None and len(f) >= info.n_stripes * U:
                shard_files[shard] = f
        avail = set(shard_files)
        out: Dict[int, np.ndarray] = {}
        missing_data = [c for c in range(k) if c not in avail]
        if not missing_data:
            for s in stripes:
                out[s] = np.stack([
                    shard_files[c][s * U:(s + 1) * U] for c in range(k)])
            return out
        try:
            plan = sorted(codec.minimum_to_decode(set(range(k)), avail))
        except ErasureCodeError:
            raise IOError(f"object {name}: unrecoverable "
                          f"(only shards {sorted(avail)})")
        sub = np.stack([
            np.stack([shard_files[c][s * U:(s + 1) * U] for c in plan])
            for s in stripes])                       # [S, n_plan, U]
        dec = np.asarray(codec.decode_chunks_batch(
            plan, sub, missing_data))                # [S, n_miss, U]
        for j, s in enumerate(stripes):
            chunks = np.zeros((k, U), dtype=np.uint8)
            for c in range(k):
                if c in avail:
                    chunks[c] = shard_files[c][s * U:(s + 1) * U]
            for i, c in enumerate(missing_data):
                chunks[c] = dec[j, i]
            out[s] = chunks
        return out

    def get(self, pool_id: int, name: str) -> bytes:
        pool = self.osdmap.pools[pool_id]
        if pool.read_tier >= 0 and "@" not in name:
            # read through the cache tier: hit serves from cache;
            # miss PROMOTES through the op engine (COPY_FROM base ->
            # cache) and then serves the promoted copy
            st = self._tier_hits(pool_id)
            if (pool.read_tier, name) in self.objects:
                st["hits"].record(name)
                return self._get_raw(pool.read_tier, name)
            if (pool_id, name) not in self.objects:
                raise KeyError(f"object {name} not found")
            self.tier_promote(pool_id, name)
            st["hits"].record(name)
            return self._get_raw(pool.read_tier, name)
        return self._get_raw(pool_id, name)

    def _get_raw(self, pool_id: int, name: str) -> bytes:
        pool = self.osdmap.pools[pool_id]
        info = self.objects[(pool_id, name)]
        pg = self.object_pg(pool, name)
        up = self.pg_up(pool, pg)
        if pool.type == POOL_REPLICATED:
            sources = [o for o in up if o != ITEM_NONE] + \
                [o.id for o in self.osds]
            for o in sources:
                try:
                    payload = self.services[o].get(
                        (pool_id, pg, name, 0))
                except IOError:
                    continue   # dropped op: replica failover
                if payload is not None:
                    return payload.tobytes()[:info.size]
            raise IOError(f"object {name}: no replica available")
        if self._device_staging(self.codec_for(pool)):
            view = self._gather_decode_dev(pool, name, info, pg, up)
            return np.asarray(view).tobytes()[:info.size]
        stripes = list(range(info.n_stripes))
        chunks = self._gather_stripes(pool, name, info, stripes)
        buf = np.concatenate([chunks[s].reshape(-1) for s in stripes])
        return buf.tobytes()[:info.size]

    def flush_all(self) -> int:
        """Flush every OSD's dirty HBM staging to the durable store."""
        return sum(o.flush_device() for o in self.osds)

    # ---------------------------------------------- device-client I/O --
    def put_from_device(self, pool_id: int, name: str, arr,
                        size: Optional[int] = None) -> List[int]:
        """EC put whose payload is ALREADY a device array (uint8 [n]) —
        the TPU-native client shape: data produced by an on-device
        pipeline is striped/encoded/staged without ever visiting the
        host.  Same placement, logging and staging semantics as put().
        """
        import jax.numpy as jnp
        pool = self.osdmap.pools[pool_id]
        if pool.type != POOL_ERASURE:
            raise IOError("put_from_device requires an EC pool")
        codec = self.codec_for(pool)
        n = int(arr.size) if size is None else int(size)
        if not self._device_staging(codec):
            # layered codec / staging off: one readback, host path
            return self.put(pool_id, name,
                            np.asarray(arr).tobytes()[:n])
        if "@" not in name:
            self._maybe_clone(pool, name)
        pg = self.object_pg(pool, name)
        up = self.pg_up(pool, pg)
        si = self._sinfo(pool)
        n_str = max(1, si.stripe_count(n))
        pad = n_str * si.stripe_width - int(arr.size)
        a = jnp.asarray(arr, jnp.uint8)
        if pad > 0:
            a = jnp.pad(a.reshape(-1), (0, pad))
        placed = self._place_shards_dev(pool_id, pg, name, up, codec,
                                        a, n_str, si.chunk_size)
        self.extent_cache.invalidate_object((pool_id, name))
        self.objects[(pool_id, name)] = self._new_info(
            pool, name, n, si.chunk_size, n_str)
        self._log_write(pool_id, pg, name, set(placed))
        return placed

    def put_many(self, pool_id: int, names: List[str],
                 datas: List[bytes]) -> Dict[str, List[int]]:
        """Batched HOST-bytes EC put — the simulator half of the
        objecter's batched put path: same-stripe-class objects share
        ONE encode dispatch (sharded across the mesh when the
        parallel data plane is on), with per-object placement,
        logging and true sizes.  Grouping by stripe class keeps a
        mixed batch from write-amplifying small objects to the
        largest member's geometry (same stance as the wire client's
        put_many).  Non-EC pools and non-device codecs fall back to
        per-object put()."""
        pool = self.osdmap.pools[pool_id]
        codec = self.codec_for(pool) \
            if pool.type == POOL_ERASURE else None
        if codec is None or not self._device_staging(codec) or \
                pool.write_tier >= 0:
            # non-EC, non-device codec, or a tiered pool: per-object
            # put() owns the writeback-cache routing — the batched
            # path writing the base directly would leave stale cache
            # copies serving reads (tier_add refuses EC bases today,
            # so this is defense in depth)
            return {n: self.put(pool_id, n, d)
                    for n, d in zip(names, datas)}
        from .ec_backend import ObjectGeom
        si = self._sinfo(pool)
        k, U = codec.get_data_chunk_count(), si.chunk_size
        stripe = si.stripe_width
        be = self.ec_backend(pool_id)
        if len(set(names)) != len(names):
            # duplicate names: the LAST occurrence wins, matching the
            # sequential per-object fallback — class-grouped encode
            # order must not decide which payload survives
            winner = {nm: i for i, nm in enumerate(names)}
            keep = sorted(winner.values())
            names = [names[i] for i in keep]
            datas = [datas[i] for i in keep]
        by_class: Dict[int, List[int]] = {}
        for i, d in enumerate(datas):
            by_class.setdefault(
                max(1, si.stripe_count(len(d))), []).append(i)
        results: Dict[str, List[int]] = {}
        eager = self.staging_flush == "eager"
        for S, idxs in sorted(by_class.items()):
            gnames = [names[i] for i in idxs]
            gdatas = [datas[i] for i in idxs]
            buf = np.zeros(len(gnames) * S * stripe, dtype=np.uint8)
            for j, d in enumerate(gdatas):
                buf[j * S * stripe:j * S * stripe + len(d)] = \
                    np.frombuffer(d, dtype=np.uint8)
            pg_of: Dict[str, int] = {}
            for nm in gnames:
                if "@" not in nm:
                    self._maybe_clone(pool, nm)
                pg_of[nm] = self.object_pg(pool, nm)
            writes = be.encode_to_writes(     # ONE dispatch per class
                pg_of, gnames, buf, ObjectGeom(S * stripe, S, U),
                durable=eager,
                sizes={nm: len(d) for nm, d in zip(gnames, gdatas)},
                d_host=buf.reshape(len(gnames) * S, k, U))
            acked = be.submit_loose(writes)
            for nm, d in zip(gnames, gdatas):
                placed = [t for _, t in
                          sorted(acked.get(nm, {}).items())]
                self.extent_cache.invalidate_object((pool_id, nm))
                self.objects[(pool_id, nm)] = self._new_info(
                    pool, nm, len(d), U, S)
                self._log_write(pool_id, pg_of[nm], nm, set(placed))
                results[nm] = placed
        return results

    def put_many_from_device(self, pool_id: int, names: List[str],
                             batch) -> Dict[str, List[int]]:
        """Batched EC ingest: N same-size objects as ONE device array
        [N, S, k, U] (or [N, S*k*U]), encoded in a single dispatch and
        staged as range refs into the shared buffers.  The device-side
        analog of the framework's batching stance everywhere else
        (ParallelPGMapper -> one pjit): amortizes per-dispatch cost
        over the whole batch; placement/logging run per object."""
        import jax.numpy as jnp
        pool = self.osdmap.pools[pool_id]
        codec = self.codec_for(pool)
        if not self._device_staging(codec):
            out = {}
            for i, nm in enumerate(names):
                out[nm] = self.put(pool_id, nm,
                                   np.asarray(batch[i]).tobytes())
            return out
        si = self._sinfo(pool)
        k = codec.get_data_chunk_count()
        U = si.chunk_size
        N = len(names)
        a = jnp.asarray(batch)
        itemsize = 4 if a.dtype == jnp.int32 else 1
        obj_bytes = int(a.size) * itemsize // N
        S = si.stripe_count(obj_bytes)
        if S * si.stripe_width != obj_bytes:
            raise IOError("put_many_from_device needs stripe-aligned "
                          "objects")
        a = self._to_words(a, N * S, k, U)
        from .ec_backend import ObjectGeom
        be = self.ec_backend(pool_id)
        pg_of: Dict[str, int] = {}
        for name in names:
            if "@" not in name:
                self._maybe_clone(pool, name)
            pg_of[name] = self.object_pg(pool, name)
        writes = be.encode_to_writes(      # ONE dispatch, all N
            pg_of, names, a, ObjectGeom(obj_bytes, S, U),
            durable=(self.staging_flush == "eager"))
        acked = be.submit_loose(writes)
        results: Dict[str, List[int]] = {}
        for name in names:
            placed = [t for _, t in
                      sorted(acked.get(name, {}).items())]
            self.extent_cache.invalidate_object((pool_id, name))
            self.objects[(pool_id, name)] = self._new_info(
                pool, name, obj_bytes, U, S)
            self._log_write(pool_id, pg_of[name], name, set(placed))
            results[name] = placed
        return results

    def get_many_to_device(self, pool_id: int, names: List[str]):
        """Batched EC read: N same-geometry objects as ONE
        [N*S, k, U] device array — healthy members gather in a single
        assemble dispatch; DEGRADED members decode through the shared
        ECBackend's signature-grouped path (one kernel call per
        erasure signature, not per object)."""
        from .device_store import assemble_many
        pool = self.osdmap.pools[pool_id]
        codec = self.codec_for(pool)
        k = codec.get_data_chunk_count()
        refs_per_obj = []
        S = U = None
        for name in names:
            info = self.objects[(pool_id, name)]
            pg = self.object_pg(pool, name)
            up = self.pg_up(pool, pg)
            if S is None:
                S, U = info.n_stripes, info.chunk_size
            elif (info.n_stripes, info.chunk_size) != (S, U):
                raise IOError("get_many_to_device needs same-geometry "
                              "objects")
            refs = []
            for c in range(k):
                r = self._read_shard_dev(pool_id, pg, name, c, up)
                if r is None or r.size < S * U:
                    refs = None
                    break
                refs.append(r)
            if refs is None:
                # degraded member: decode individually
                refs_per_obj.append(None)
            else:
                refs_per_obj.append(refs)
        healthy = [r for r in refs_per_obj if r is not None]
        out = assemble_many(healthy, S, U // 4) if healthy else None
        if all(r is not None for r in refs_per_obj):
            return out
        # stitch healthy batch + degraded members: degraded objects
        # decode through the shared ECBackend signature-GROUPED path
        # (all objects in one PG share an erasure signature, so they
        # rebuild in one kernel call — not one dispatch per object)
        import jax.numpy as jnp
        from .ec_backend import ObjectGeom
        deg_items = []
        for name, refs in zip(names, refs_per_obj):
            if refs is None:
                info = self.objects[(pool_id, name)]
                deg_items.append((self.object_pg(pool, name), name,
                                  ObjectGeom(info.size, S, U)))
        deg_words = iter(self.ec_backend(pool_id)
                         .read_many_words(deg_items))
        parts, hi = [], 0
        for name, refs in zip(names, refs_per_obj):
            if refs is None:
                parts.append(next(deg_words))
            else:
                parts.append(out[hi * S:(hi + 1) * S])
                hi += 1
        return jnp.concatenate(parts)

    def get_to_device(self, pool_id: int, name: str):
        """EC get returning the object as a device array — the
        consumer is an on-device pipeline; no host readback happens.
        Degraded chunks decode via the masked-XOR kernel in the same
        graph.  Stripe-aligned objects come back as their [S, k, U]
        stripe view (zero trim work; a flat view of >=2 GiB would need
        64-bit slice indices the TPU rejects); smaller or unaligned
        objects come back flat [size]."""
        pool = self.osdmap.pools[pool_id]
        if pool.type != POOL_ERASURE:
            raise IOError("get_to_device requires an EC pool")
        info = self.objects[(pool_id, name)]
        codec = self.codec_for(pool)
        if not self._device_staging(codec):
            import jax.numpy as jnp
            data = self.get(pool_id, name)       # host path, one upload
            return jnp.asarray(np.frombuffer(data, dtype=np.uint8))
        pg = self.object_pg(pool, name)
        up = self.pg_up(pool, pg)
        view = self._gather_decode_dev(pool, name, info, pg, up)
        total = 4 * int(view.shape[0]) * int(view.shape[1]) * \
            int(view.shape[2])
        if info.size == total:
            return view                 # [S, k, W] int32 word view
        if total < (1 << 31):
            import jax
            import jax.numpy as jnp
            u8 = jax.lax.bitcast_convert_type(view, jnp.uint8)
            return u8.reshape(-1)[:info.size]
        raise IOError(f"object {name}: unaligned size {info.size} on "
                      f">=2GiB object cannot be flattened on device; "
                      f"read the stripe view or use get()")

    def write(self, pool_id: int, name: str, offset: int,
              data: bytes) -> List[int]:
        """Partial overwrite.  EC pools run the RMW pipeline (requires
        FLAG_EC_OVERWRITES semantics); replicated pools splice bytes."""
        pool = self.osdmap.pools[pool_id]
        if "@" not in name:
            self._maybe_clone(pool, name)
        info = self.objects.get((pool_id, name))
        if pool.type == POOL_REPLICATED:
            old = self.get(pool_id, name) if info else b""
            size = max(len(old), offset + len(data))
            buf = bytearray(size)
            buf[:len(old)] = old
            buf[offset:offset + len(data)] = data
            return self.put(pool_id, name, bytes(buf))
        if info is None:
            info = ObjectInfo(0, pool.stripe_unit, 0,
                              born_seq=pool.snap_seq,
                              snap_seq=pool.snap_seq)
        pg = self.object_pg(pool, name)
        up = self.pg_up(pool, pg)
        codec = self.codec_for(pool)
        k, mm = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
        si = self._sinfo(pool)
        pipe = self._pipeline(pool)

        def read_stripe(idx: int) -> Optional[np.ndarray]:
            if idx >= info.n_stripes:
                return None
            got = self._gather_stripes(pool, name, info, [idx])
            return got.get(idx)

        new_chunks, new_size = pipe.write(
            (pool_id, name), info.size, offset, data, read_stripe)
        n_str = max(info.n_stripes, si.stripe_count(new_size))
        # grow shard files if the object extended
        placed: Set[int] = set()
        for shard in range(k + mm):
            f = self._read_shard(pool.id, pg, name, shard, up)
            U = si.chunk_size
            need = n_str * U
            buf = np.zeros(need, dtype=np.uint8)
            if f is not None:
                buf[:min(len(f), need)] = f[:need]
            for idx, chunks in new_chunks.items():
                buf[idx * U:(idx + 1) * U] = chunks[shard]
            tgt = self._write_shard(pool_id, pg, name, shard, up, buf)
            if tgt is not None:
                placed.add(tgt)
        self.objects[(pool_id, name)] = ObjectInfo(
            new_size, si.chunk_size, n_str)
        self._log_write(pool_id, pg, name, placed)
        return sorted(placed)

    def delete(self, pool_id: int, name: str) -> None:
        """Remove an object: shards purged from live OSDs, an OP_DELETE
        log entry recorded so lagging replicas apply it on delta
        recovery.  Snapshotted state survives as clones (the head
        whiteout semantics: clones trim with their snaps, not here).
        Tiered base pools delete BOTH copies (cache whiteout + base),
        or the next read would promote the object back to life."""
        pool = self.osdmap.pools[pool_id]
        if pool.write_tier >= 0 and "@" not in name:
            st = self._tier_hits(pool_id)
            st["dirty"].discard(name)
            if (pool.write_tier, name) in self.objects:
                self.delete(pool.write_tier, name)
            if (pool_id, name) not in self.objects:
                return
        if "@" not in name:
            self._maybe_clone(pool, name)
        info = self.objects.pop((pool_id, name), None)
        if info is None:
            return
        if info.clones:
            # whiteout: the SnapSet outlives the head so clones stay
            # readable/trimmable
            self.snapsets[(pool_id, name)] = info
        pg = self.object_pg(pool, name)
        up = self.pg_up(pool, pg)
        for osd in self.osds:
            if osd.alive:
                for shard in range(pool.size):
                    osd.delete((pool_id, pg, name, shard))
        self.extent_cache.invalidate_object((pool_id, name))
        log = self._log(pool_id, pg)
        prev_head = log.head
        e = log.append(self.osdmap.epoch, name, op=OP_DELETE)
        self._advance_lc(pool_id, pg,
                         (o for o in up
                          if o != ITEM_NONE and self.osds[o].alive),
                         prev_head, e.version)

    # ----------------------------------------------------------- failure --
    def _lose_memory(self, osd: int) -> None:
        """Process death drops in-memory state: the PG heat table
        dies with the process, so the synthesized per-OSD counters
        restart from zero — the mon's history layer must see that as
        a counted RESET, never a negative rate."""
        services = getattr(self, "services", None) or []
        svc = services[osd] if osd < len(services) else None
        heat = getattr(svc, "heat", None)
        if heat is not None:
            heat.reset()

    def kill_osd(self, osd: int) -> None:
        """Thrasher-style kill (qa/tasks/ceph_manager.py kill_osd): process
        death — store contents are lost to the cluster."""
        self.osds[osd].crash()
        self.osds[osd].alive = False
        self._lose_memory(osd)
        self.osdmap.mark_down(osd)

    def fail_osd(self, osd: int) -> None:
        """Process death WITHOUT the map knowing yet: the state the
        heartbeat/failure-report pipeline exists to detect."""
        self.osds[osd].crash()
        self.osds[osd].alive = False
        self._lose_memory(osd)

    def out_osd(self, osd: int) -> None:
        self.osdmap.mark_out(osd)

    def revive_osd(self, osd: int) -> None:
        """Direct map mutation (standalone-sim flows).  Clusters with a
        Monitor should use restart_osd() + Monitor.osd_boot() so the
        epoch change reaches subscribed clients as an incremental."""
        self.osds[osd].alive = True
        self.osdmap.osd_up[osd] = True
        self.osdmap.osd_weight[osd] = 0x10000
        self.osdmap.bump_epoch()

    def restart_osd(self, osd: int) -> None:
        """Process back up, map untouched — pair with Monitor.osd_boot.
        An OSD that died to ``device.power_loss`` runs boot-time
        fsck(repair=True): torn objects are quarantined (recovery
        re-replicates them) and the count rides the next heartbeat
        tick to the mon's STORE_DAMAGED health check."""
        o = self.osds[osd]
        o.alive = True
        if o.power_lost:
            o.power_lost = False
            o.fsck_errors = len(o.objectstore.fsck(repair=True))

    # ---------------------------------------------------------- recovery --
    def remap_diff(self, pool_id: int, old_up: np.ndarray
                   ) -> Dict[int, List[int]]:
        """Batched old-vs-new mapping diff: {pg: shards whose home moved}
        — vectorized, no per-PG Python loop."""
        new_up, _ = self.osdmap.map_pgs_batch(pool_id)
        n = min(len(old_up), len(new_up))
        diff = old_up[:n] != new_up[:n]
        pgs = np.flatnonzero(diff.any(axis=1))
        return {int(pg): [int(s) for s in np.flatnonzero(diff[pg])]
                for pg in pgs}

    def recover_all(self, pool_id: int) -> Dict[str, int]:
        """Rebuild every unreadable/misplaced shard onto the current up
        set: the batched analog of ECBackend::recover_object — damaged
        objects' stripes are grouped by erasure signature and each group
        decodes in one device call.
        """
        pool = self.osdmap.pools[pool_id]
        stats = {"objects_scanned": 0, "shards_rebuilt": 0,
                 "shards_copied": 0, "batches": 0}
        if pool.type == POOL_REPLICATED:
            for (pid, name), info in self.objects.items():
                if pid != pool_id:
                    continue
                stats["objects_scanned"] += 1
                pg = self.object_pg(pool, name)
                up = self.pg_up(pool, pg)
                payload = self._read_shard(pool_id, pg, name, 0, up)
                if payload is None:
                    continue
                for o in up:
                    if o != ITEM_NONE and self.osds[o].alive and \
                            self.osds[o].get((pool_id, pg, name, 0)) is None:
                        try:
                            self.services[o].put_recovery(
                                (pool_id, pg, name, 0), payload)
                        except IOError:
                            continue      # dropped push: next pass
                        stats["shards_copied"] += 1
            return stats

        codec = self.codec_for(pool)
        k, mm = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
        if self._device_staging(codec):
            return self._recover_all_dev(pool, pool_id, codec, k, mm,
                                         stats)
        return self._recover_all_host(pool, pool_id, codec, k, mm,
                                      stats)

    # ------------------------------------------ bulk recovery sub-ops --
    def _bulk_get_device(self, reads: Dict[Tuple, List[int]]
                         ) -> Dict[Tuple, object]:
        """Submit-all-then-gather device reads: ``reads`` maps each
        ShardKey to its ordered holder chain (presence-probed, the
        MissingLoc contract); ONE ``get_dev_many`` sub-op per holder
        OSD per round replaces the per-shard blocking round trips.  A
        holder that fails (drop injection, death mid-sweep) fails over
        to the next in the key's chain on the following round."""
        out: Dict[Tuple, object] = {rk: None for rk in reads}
        pending = {rk: list(chain) for rk, chain in reads.items()}
        while True:
            by_osd: Dict[int, List[Tuple]] = {}
            for rk, chain in pending.items():
                if out[rk] is not None or not chain:
                    continue
                by_osd.setdefault(chain.pop(0), []).append(rk)
            if not by_osd:
                return out
            fan = []
            for o, rkeys in sorted(by_osd.items()):
                try:
                    fan.append((o, rkeys, self.services[o]
                                .get_device_many_async(rkeys)))
                except IOError:
                    continue      # dropped sub-op: chains advance
            for o, rkeys, handle in fan:
                try:
                    res = self.services[o].wait_async(*handle)
                except IOError:
                    continue      # failed gather: chains advance
                for rk, r in zip(rkeys, res):
                    if r is not None:
                        out[rk] = r

    def _bulk_put_device(self, pushes: Dict[int, List[Tuple]]
                         ) -> Tuple[int, Set[int]]:
        """Submit-all-then-gather device pushes: ``pushes`` maps each
        target OSD to its (key, ref, durable_bytes) items; one
        ``put_dev_many`` sub-op per target under the
        background_recovery class.  Returns (landed count, targets
        whose batch landed) — a failed batch stays missing for the
        next pass (the dropped-push contract, batch-granular)."""
        fan = []
        for tgt, items in sorted(pushes.items()):
            if not items:
                continue
            try:
                fan.append((tgt, items, self.services[tgt]
                            .put_device_many_async(items)))
            except IOError:
                continue          # dropped push: next pass
        n = 0
        landed: Set[int] = set()
        for tgt, items, handle in fan:
            try:
                self.services[tgt].wait_async(*handle)
            except IOError:
                continue          # dropped push: next pass
            n += len(items)
            landed.add(tgt)
        return n, landed

    def _recover_all_dev(self, pool, pool_id: int, codec, k: int,
                         mm: int, stats: Dict[str, int]
                         ) -> Dict[str, int]:
        """Device-resident EC recovery sweep: host-side presence
        probes plan the fetch set, surviving shard refs gather through
        bulk async sub-ops, the grouped masked-XOR rebuild dispatches
        (collectively, when the data plane is up), and rebuilt/copied
        shards scatter back through bulk async pushes — no per-shard
        blocking round trip anywhere on the path."""
        n_shards = k + mm
        eager = self.staging_flush == "eager"
        objs, reads = [], {}
        for (pid, name), info in self.objects.items():
            if pid != pool_id:
                continue
            stats["objects_scanned"] += 1
            pg = self.object_pg(pool, name)
            up = self.pg_up(pool, pg)
            objs.append((name, info, pg, up))
            for shard in range(n_shards):
                key = (pool_id, pg, name, shard)
                chain = [o for o in self._shard_sources(up, shard)
                         if self.osds[o].has(key)]
                if chain:
                    reads[key] = chain
        refs = self._bulk_get_device(reads)
        groups: Dict[Tuple, List] = {}
        copies: Dict[int, List[Tuple]] = {}
        for name, info, pg, up in objs:
            U = info.chunk_size
            shard_files: Dict[int, object] = {}
            missing: List[int] = []
            for shard in range(n_shards):
                f = refs.get((pool_id, pg, name, shard))
                if f is None or f.size < info.n_stripes * U:
                    missing.append(shard)
                else:
                    shard_files[shard] = f
            # re-place surviving shards that are off their new home
            for shard, payload in shard_files.items():
                tgt = up[shard] if shard < len(up) else ITEM_NONE
                if tgt != ITEM_NONE and self.osds[tgt].alive and \
                        not self.osds[tgt].has(
                            (pool_id, pg, name, shard)):
                    copies.setdefault(tgt, []).append(
                        ((pool_id, pg, name, shard), payload,
                         np.asarray(payload).tobytes() if eager
                         else None))
            if not missing:
                continue
            avail = set(shard_files)
            try:
                plan = tuple(sorted(codec.minimum_to_decode(
                    set(missing), avail)))
            except ErasureCodeError:
                continue   # unrecoverable object
            key = (plan, tuple(missing), U)
            groups.setdefault(key, []).append(
                (name, up, shard_files, info.n_stripes, pg))
        stats["shards_copied"] += self._bulk_put_device(copies)[0]
        self._rebuild_groups_dev(pool_id, codec, k, mm, groups,
                                 eager, stats)
        return stats

    def _read_shard_ranges(self, pool_id: int, pg: int, name: str,
                           shard: int, up: List[int],
                           ranges) -> Optional[np.ndarray]:
        """Ranged shard read with the same holder failover as
        _read_shard; only the requested byte ranges move."""
        from .osd_service import CLASS_RECOVERY
        for o in self._shard_sources(up, shard):
            try:
                p = self.services[o].get((pool_id, pg, name, shard),
                                         klass=CLASS_RECOVERY,
                                         ranges=ranges)
            except IOError:
                continue
            if p is not None:
                return p
        return None

    def _repair_one_ranged(self, pool_id: int, pg: int, name: str,
                           up: List[int], codec, plan, lost: int,
                           U: int, S: int, sub_chunks: int,
                           stats: Dict[str, int]) -> bool:
        """Single-loss minimum-bandwidth repair: each helper in the
        codec's SubChunkPlan ships only its repair sub-chunk ranges
        (per stripe — a striped object's shard file is S independent
        U-byte codeword chunks back to back); ``codec.repair``
        regenerates the lost chunk stripe by stripe.  A failed helper
        aborts the object to the next pass (partial fetches must not
        decode)."""
        tgt = up[lost] if lost < len(up) else ITEM_NONE
        if tgt == ITEM_NONE or not self.osds[tgt].alive:
            return True        # homeless loss: nothing to land
        sc = U // sub_chunks
        helpers: Dict[int, np.ndarray] = {}
        fetched = 0
        for c, rg in sorted(plan.items()):
            r = self._read_shard_ranges(
                pool_id, pg, name, c, up,
                [(s * U + off * sc, cnt * sc)
                 for s in range(S) for off, cnt in rg])
            if r is None:
                return False   # helper lost mid-repair: next pass
            helpers[c] = r
            fetched += int(r.size)
        per_stripe = {c: h.size // S for c, h in helpers.items()}
        parts: List[np.ndarray] = []
        try:
            for s in range(S):
                parts.append(codec.repair(
                    lost,
                    {c: h[s * per_stripe[c]:(s + 1) * per_stripe[c]]
                     for c, h in helpers.items()}, U))
        except ErasureCodeError:
            return False
        rebuilt = np.concatenate(parts)
        try:
            self.services[tgt].put_recovery(
                (pool_id, pg, name, lost), rebuilt)
        except IOError:
            return False       # dropped push: next pass
        stats["shards_rebuilt"] += 1
        stats["repair_bytes_fetched"] = \
            stats.get("repair_bytes_fetched", 0) + fetched
        stats["ranged_repairs"] = stats.get("ranged_repairs", 0) + 1
        return True

    def _recover_all_host(self, pool, pool_id: int, codec, k: int,
                          mm: int, stats: Dict[str, int]
                          ) -> Dict[str, int]:
        """Host-tier EC recovery (layered codecs — clay/lrc/shec —
        and staging-off pools): presence+size probes plan the fetch,
        then ONLY the codec's minimal repair set moves — Clay single
        losses fetch d helpers' repair SUB-CHUNK ranges
        (``codec.repair``), LRC losses fetch the covering local
        group — instead of every surviving shard.
        ``repair_bytes_fetched`` counts the decode-fetch payload so
        callers can assert the repair-bandwidth saving against
        full-stripe k reads."""
        n_shards = k + mm
        groups: Dict[Tuple, List] = {}
        sub_chunks = codec.get_sub_chunk_count()
        for (pid, name), info in self.objects.items():
            if pid != pool_id:
                continue
            stats["objects_scanned"] += 1
            pg = self.object_pg(pool, name)
            up = self.pg_up(pool, pg)
            U = info.chunk_size
            want = info.n_stripes * U
            holders: Dict[int, List[int]] = {}
            for shard in range(n_shards):
                key = (pool_id, pg, name, shard)
                chain = [o for o in self._shard_sources(up, shard)
                         if self.osds[o].probe(key) >= want]
                if chain:
                    holders[shard] = chain
            missing = [s for s in range(n_shards) if s not in holders]
            # displaced survivors re-place regardless of decode fate
            fetch_copy = {}
            for shard in holders:
                tgt = up[shard] if shard < len(up) else ITEM_NONE
                if tgt != ITEM_NONE and self.osds[tgt].alive and \
                        not self.osds[tgt].has(
                            (pool_id, pg, name, shard)):
                    fetch_copy[shard] = tgt
            plan = None
            if missing:
                try:
                    plan = codec.minimum_to_decode(set(missing),
                                                   set(holders))
                except ErasureCodeError:
                    plan = None   # unrecoverable: copies still move
            partial = plan is not None and any(
                sum(cnt for _, cnt in rg) < sub_chunks
                for rg in plan.values())
            if partial and len(missing) == 1 and not fetch_copy:
                # regenerating-code single-loss repair (Clay): d
                # helpers each ship ONLY their repair sub-chunk
                # ranges, per stripe — the minimum-bandwidth
                # property, on the recovery path rather than just in
                # the codec registry
                self._repair_one_ranged(pool_id, pg, name, up, codec,
                                        plan, missing[0], U,
                                        info.n_stripes, sub_chunks,
                                        stats)
                continue
            files: Dict[int, np.ndarray] = {}
            for shard in sorted(set(fetch_copy) |
                                set(plan or {})):
                f = self._read_shard(pool_id, pg, name, shard, up)
                if f is not None and f.size >= want:
                    files[shard] = f
            for shard, tgt in fetch_copy.items():
                payload = files.get(shard)
                if payload is None:
                    continue      # probe raced a drop: next pass
                try:
                    self.services[tgt].put_recovery(
                        (pool_id, pg, name, shard), payload)
                except IOError:
                    continue      # dropped push: next pass
                stats["shards_copied"] += 1
            if not missing or plan is None:
                continue
            plan_files = {c: files[c] for c in plan if c in files}
            if len(plan_files) < len(plan):
                continue          # a fetch dropped: next pass
            stats["repair_bytes_fetched"] = \
                stats.get("repair_bytes_fetched", 0) + \
                sum(f.size for f in plan_files.values())
            key = (tuple(sorted(plan)), tuple(missing), U)
            groups.setdefault(key, []).append(
                (name, up, plan_files, info.n_stripes, pg))
        for (plan, missing, U), members in groups.items():
            stats["batches"] += 1
            batch = np.concatenate([
                np.stack([np.stack([files[c][s * U:(s + 1) * U]
                                    for c in plan])
                          for s in range(n_str)])
                for name, up, files, n_str, pg in members])
            rebuilt = np.asarray(codec.decode_chunks_batch(
                list(plan), batch, list(missing)))
            pos = 0
            for name, up, files, n_str, pg in members:
                part = rebuilt[pos:pos + n_str]
                pos += n_str
                for i, shard in enumerate(missing):
                    tgt = up[shard] if shard < len(up) else ITEM_NONE
                    if tgt == ITEM_NONE or not self.osds[tgt].alive:
                        continue
                    try:
                        self.services[tgt].put_recovery(
                            (pool_id, pg, name, shard),
                            part[:, i].reshape(-1))
                    except IOError:
                        continue          # dropped push: next pass
                    stats["shards_rebuilt"] += 1
        return stats

    def _rebuild_groups_dev(self, pool_id, codec, k, mm, groups,
                            eager, stats) -> None:
        """Device rebuild with ONE gather + ONE masked-XOR dispatch
        per (geometry, buffer-composition) subgroup — the erasure
        SIGNATURE travels as a dynamic full-width mask operand (the
        bench_recovery design on the cluster path): per-signature
        static shapes would pay one XLA compile per signature, seconds
        each through a remote-compile tunnel.

        The gather reads ALL k+m canonical columns per object (missing
        columns read whatever the canonical buffer holds — the decode
        masks are zero at non-available columns, so the values never
        contribute); the full-width bit-matrix for each object's
        signature positions the recovery matrix at its available
        chunks' plane columns, zero-padded to m erased rows."""
        n = k + mm
        # flatten the signature groups, then regroup by (stripe count,
        # canonical buffer composition, W); members whose refs do not
        # form uniform same-start windows (re-uploaded axis-0 refs,
        # mixed recovery buffers) fall back to the per-member path —
        # dropping them would be silent non-repair
        subs: Dict[Tuple, List] = {}
        irregular: List[Tuple] = []
        for (plan, missing, U), members in groups.items():
            for name, up, files, n_str, pg in members:
                comp, uniform = [], True
                by_col = {}
                s0_seen = None
                for c, r in files.items():
                    if getattr(r, "axis", 0) != 1:
                        uniform = False
                        break
                    if s0_seen is None:
                        s0_seen = r.s0
                    elif r.s0 != s0_seen:
                        # per-column starts differ (e.g., one column
                        # is a prior recovery's rebuilt buffer): the
                        # single-starts gather would read the WRONG
                        # rows for that column
                        uniform = False
                        break
                    by_col[c] = (id(r.buf), r.buf, r.idx, r.s0)
                if not uniform or not by_col:
                    irregular.append((plan, missing, U, name, up,
                                      files, n_str, pg))
                    continue
                # canonical column inference: a put batch stages data
                # shard c as column c of one shared buffer and parity
                # c as column c-k of the encode output, so a MISSING
                # column's canonical source is derivable from any
                # present same-class sibling — the composition key
                # must not encode the missing set, or every erasure
                # signature becomes its own compile
                d_src = next(((bid, buf) for c, (bid, buf, idx, _)
                              in by_col.items()
                              if c < k and idx == c), None)
                p_src = next(((bid, buf) for c, (bid, buf, idx, _)
                              in by_col.items()
                              if c >= k and idx == c - k), None)
                anchor = next(iter(by_col.values()))
                for c in range(n):
                    if c in by_col:
                        bid, buf, idx, _ = by_col[c]
                        comp.append((bid, idx))
                    elif c < k and d_src is not None:
                        comp.append((d_src[0], c))
                    elif c >= k and p_src is not None:
                        comp.append((p_src[0], c - k))
                    else:
                        comp.append((anchor[0], anchor[2]))
                if d_src is not None:
                    by_col.setdefault(-1, (d_src[0], d_src[1], 0, 0))
                if p_src is not None:
                    by_col.setdefault(-2, (p_src[0], p_src[1], 0, 0))
                key = (n_str, U, tuple(comp))
                subs.setdefault(key, []).append(
                    (name, up, files, n_str, pg, tuple(missing),
                     tuple(sorted(files)), by_col, anchor))
        for (n_str, U, comp), all_mems in subs.items():
            W = U // 4
            # resolve composition ids back to buffers via any member
            bufmap = {}
            for mem in all_mems:
                for c, (bid, buf, idx, _) in mem[7].items():
                    bufmap[bid] = buf
            col_bufs = [(bufmap[bid], idx) for bid, idx in comp]
            # bound PEAK HBM per chunk: the window stack (G*S*n*U) is
            # joined by its pow2-pad copy (≤2x) and the rebuilt output
            # while both are live, so the per-member price is ~3x the
            # stack bytes — chunk members to fit the budget (chunk
            # sizes repeat, so the executables still amortize)
            per_mem = max(1, 3 * n_str * n * U)
            g_cap = max(1, REBUILD_GATHER_BUDGET // per_mem)
            g_cap = 1 << (g_cap.bit_length() - 1)     # pow2 bucket
            chunks = [all_mems[i:i + g_cap]
                      for i in range(0, len(all_mems), g_cap)]
            for mems in chunks:
                self._rebuild_chunk_dev(pool_id, codec, k, mm, n,
                                        comp, col_bufs, mems, n_str,
                                        U, W, eager, stats)

        # per-member fallback for irregular refs: pays a static-spec
        # assemble (possible compile) per shape, but the path is rare
        # and silence here would be non-repair
        from .device_store import ShardRef, assemble_refs
        for plan, missing, U, name, up, files, n_str, pg in irregular:
            stats["batches"] += 1
            sub = assemble_refs([files[c] for c in plan], n_str,
                                U // 4)
            rebuilt = codec.decode_words_device(list(plan), sub,
                                                list(missing))
            rebuilt_host = np.asarray(rebuilt) if eager else None
            for i, shard in enumerate(missing):
                tgt = up[shard] if shard < len(up) else ITEM_NONE
                if tgt == ITEM_NONE or not self.osds[tgt].alive:
                    continue
                b = np.ascontiguousarray(
                    rebuilt_host[:, i]).tobytes() if eager else None
                try:
                    self.services[tgt].put_device_recovery(
                        (pool_id, pg, name, shard),
                        ShardRef(rebuilt, i, axis=1), b)
                except IOError:
                    continue              # dropped push: next pass
                stats["shards_rebuilt"] += 1

    def _rebuild_chunk_dev(self, pool_id, codec, k, mm, n, comp,
                           col_bufs, mems, n_str, U, W, eager,
                           stats) -> None:
        import jax.numpy as jnp
        from ..ops import gf, gf2, xor_kernel
        from .device_store import ShardRef, assemble_windows
        stats["batches"] += 1
        starts = np.array([mem[8][3] for mem in mems],
                          dtype=np.int32)
        full = assemble_windows(col_bufs, starts, n_str)
        # per-object full-width signature tables, one per UNIQUE
        # signature (host-side; tiny), repeated per stripe
        sig_tab: Dict[Tuple, np.ndarray] = {}
        obj_masks = np.zeros((len(mems), 8 * mm, 8 * n),
                             dtype=np.int32)
        for j, mem in enumerate(mems):
            missing, avail = mem[5], mem[6]
            sig = (missing, avail)
            tab = sig_tab.get(sig)
            if tab is None:
                R, used = codec.decode_matrix(list(avail),
                                              list(missing))
                small = gf.gf8_bitmatrix(R)
                big = np.zeros((8 * mm, 8 * n), dtype=np.uint8)
                for jj, c in enumerate(used):
                    big[:8 * len(missing), 8 * c:8 * c + 8] = \
                        small[:, 8 * jj:8 * jj + 8]
                tab = gf2.bitmatrix_masks(big)
                sig_tab[sig] = tab
            obj_masks[j] = tab
        masks = np.repeat(obj_masks, n_str, axis=0)
        T = len(mems) * n_str
        Tp = 1
        while Tp < T:
            Tp <<= 1
        planes = full.reshape(T, 8 * n, W // 8)
        masks_d = jnp.asarray(masks)
        if Tp != T:        # pow2 bucket: bounded executable count
            planes = jnp.concatenate([planes, planes[:Tp - T]])
            masks_d = jnp.concatenate([masks_d, masks_d[:Tp - T]])
        from ..parallel.data_plane import plane as _data_plane
        dp = _data_plane()
        if dp is not None:
            # sharded COLLECTIVE recovery: the (stripe, signature)
            # batch splits across the mesh — each stripe carries its
            # own full-width signature mask — and the rebuilt rows
            # all-gather over the ICI ring inside the same dispatch,
            # so every target OSD's affine chip holds its rebuilt
            # shard chip-to-chip (no host staging hop; bit-identical
            # to the plain kernel, padding rows sliced off)
            rebuilt = dp.rebuild_collective(
                masks_d, planes, kind="recover")[:T].reshape(T, mm, W)
        else:
            rebuilt = xor_kernel.xor_matmul_w32(
                masks_d, planes)[:T].reshape(T, mm, W)
        rebuilt_host = np.asarray(rebuilt) if eager else None
        pushes: Dict[int, List[Tuple]] = {}
        for j, mem in enumerate(mems):
            name, up, files, n_str_m, pg, missing = mem[:6]
            pos = j * n_str
            for i, shard in enumerate(missing):
                tgt = up[shard] if shard < len(up) else ITEM_NONE
                if tgt == ITEM_NONE or not self.osds[tgt].alive:
                    continue
                b = np.ascontiguousarray(
                    rebuilt_host[pos:pos + n_str, i]
                ).tobytes() if eager else None
                pushes.setdefault(tgt, []).append(
                    ((pool_id, pg, name, shard),
                     ShardRef(rebuilt, i, axis=1, s0=pos,
                              s1=pos + n_str), b))
        n_landed, landed_tgts = self._bulk_put_device(pushes)
        stats["shards_rebuilt"] += n_landed
        if dp is not None:
            # chip-landing accounting for pushes that actually
            # LANDED (telemetry must agree with the recovery stats a
            # failed batch excludes)
            for tgt in landed_tgts:
                for _key, _ref, _b in pushes[tgt]:
                    dp.account_landed(tgt, n_str, U)

    def recover_delta(self, pool_id: int) -> Dict[str, int]:
        """Log-based delta recovery (the PGLog path the reference
        prefers over backfill, doc/dev/osd_internals/log_based_pg.rst):
        for every OSD in a PG's up set whose last_complete lags the
        authoritative log, recover ONLY the objects the log says
        changed; fall back to the full scan (`recover_all`-style
        backfill) only when the log was trimmed past the replica's
        version.
        """
        from ..common.tracer import tracer
        pool = self.osdmap.pools[pool_id]
        stats = {"pgs_checked": 0, "delta_objects": 0,
                 "backfill_pgs": 0, "shards_rebuilt": 0,
                 "shards_copied": 0}
        with tracer().start_span("recover_delta", pool=pool_id):
            return self._recover_delta_inner(pool, pool_id, stats)

    def _recover_delta_inner(self, pool, pool_id, stats):
        # objects per pg (host index; the real system reads the pg's
        # collection listing)
        pg_objects: Dict[int, List[str]] = {}
        for (pid, name) in self.objects:
            if pid == pool_id:
                pg_objects.setdefault(
                    self.object_pg(pool, name), []).append(name)
        for (pid, pg), log in list(self.pg_logs.items()):
            if pid != pool_id:
                continue
            stats["pgs_checked"] += 1
            up = self.pg_up(pool, pg)
            names: Set[str] = set()
            deleted: Set[str] = set()
            backfill = False
            for o in up:
                if o == ITEM_NONE:
                    continue
                lc = self.osds[o].last_complete.get((pool_id, pg), ZERO)
                if lc >= log.head:
                    continue
                ms = log.missing_since(lc)
                if ms.backfill:
                    backfill = True
                    break
                names.update(ms.need)
                deleted.update(ms.deleted)
            if backfill:
                stats["backfill_pgs"] += 1
                names = set(pg_objects.get(pg, []))
                deleted = set()
            # deletes the lagging replica missed: purge its shards so a
            # stale-map read can never resurrect the object
            for name in deleted:
                if (pool_id, name) in self.objects:
                    continue          # recreated after the delete
                for osd in self.osds:
                    if osd.alive:
                        for shard in range(pool.size):
                            osd.delete((pool_id, pg, name, shard))
                stats["deletes_applied"] = \
                    stats.get("deletes_applied", 0) + 1
            stats["delta_objects"] += len(names)
            all_ok = True
            for name in names:
                if not self._recover_object(pool, pg, name, up, stats):
                    all_ok = False
            if not all_ok:
                continue     # keep the gap visible for the next pass
            # everyone present (and alive) is now current
            for o in up:
                if o != ITEM_NONE and self.osds[o].alive:
                    self.osds[o].last_complete[(pool_id, pg)] = log.head
        return stats

    def _recover_object(self, pool: PGPool, pg: int, name: str,
                        up: List[int], stats: Dict[str, int]) -> bool:
        """Rebuild/copy one object's shards onto the up set; False when
        anything could not be recovered (the caller must NOT advance
        last_complete past it)."""
        info = self.objects.get((pool.id, name))
        if info is None:
            return True
        if pool.type == POOL_REPLICATED:
            payload = self._read_shard(pool.id, pg, name, 0, up)
            if payload is None:
                return False
            ok = True
            for o in up:
                if o == ITEM_NONE:
                    continue
                if not self.osds[o].alive:
                    ok = False       # undetected-dead member stays stale
                    continue
                if self.osds[o].get((pool.id, pg, name, 0)) is None:
                    try:
                        self.services[o].put_recovery(
                            (pool.id, pg, name, 0), payload)
                    except IOError:
                        ok = False        # dropped push: gap stays
                        continue
                    stats["shards_copied"] += 1
            return ok
        codec = self.codec_for(pool)
        k, mm = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
        U = info.chunk_size
        S = info.n_stripes
        dev = self._device_staging(codec)
        eager = self.staging_flush == "eager"
        missing = []
        files: Dict[int, np.ndarray] = {}
        ok = True
        for shard in range(k + mm):
            f = (self._read_shard_dev(pool.id, pg, name, shard, up)
                 if dev else
                 self._read_shard(pool.id, pg, name, shard, up))
            if f is None or f.size < S * U:
                missing.append(shard)
            else:
                files[shard] = f
                tgt = up[shard] if shard < len(up) else ITEM_NONE
                if tgt != ITEM_NONE and self.osds[tgt].alive and \
                        not self.osds[tgt].has(
                            (pool.id, pg, name, shard)):
                    try:
                        if dev:
                            self.services[tgt].put_device_recovery(
                                (pool.id, pg, name, shard), f,
                                np.asarray(f).tobytes() if eager
                                else None)
                        else:
                            self.services[tgt].put_recovery(
                                (pool.id, pg, name, shard), f)
                        stats["shards_copied"] += 1
                    except IOError:
                        ok = False        # dropped push: gap stays
        if not missing:
            return True
        try:
            plan = sorted(codec.minimum_to_decode(set(missing),
                                                  set(files)))
        except ErasureCodeError:
            return False     # unrecoverable NOW; retry when shards return
        if dev:
            from .device_store import ShardRef, assemble_refs
            sub = assemble_refs([files[c] for c in plan], S, U // 4)
            dec = codec.decode_words_device(plan, sub, missing)
            dec_host = np.asarray(dec) if eager else None
        else:
            sub = np.stack([
                np.stack([files[c][s * U:(s + 1) * U] for c in plan])
                for s in range(S)])
            dec = np.asarray(codec.decode_chunks_batch(plan, sub,
                                                       missing))
        for i, shard in enumerate(missing):
            tgt = up[shard] if shard < len(up) else ITEM_NONE
            if tgt == ITEM_NONE or not self.osds[tgt].alive:
                ok = False
                continue
            try:
                if dev:
                    b = np.ascontiguousarray(
                        dec_host[:, i]).tobytes() if eager else None
                    self.services[tgt].put_device_recovery(
                        (pool.id, pg, name, shard),
                        ShardRef(dec, i, axis=1), b)
                else:
                    self.services[tgt].put_recovery(
                        (pool.id, pg, name, shard),
                        dec[:, i].reshape(-1))
            except IOError:
                ok = False                # dropped push: gap stays
                continue
            stats["shards_rebuilt"] += 1
        return ok

    # -------------------------------------------------------------- scrub --
    def scrub(self, pool_id: int) -> List[Tuple[str, int]]:
        """Deep-scrub analog: re-encode data shards and compare parity
        (the checksum-compare role of src/osd/pg_scrubber.cc)."""
        pool = self.osdmap.pools[pool_id]
        if pool.type != POOL_ERASURE:
            return []
        codec = self.codec_for(pool)
        k, mm = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
        bad: List[Tuple[str, int]] = []
        for (pid, name), info in self.objects.items():
            if pid != pool_id:
                continue
            pg = self.object_pg(pool, name)
            up = self.pg_up(pool, pg)
            U = info.chunk_size
            files: Dict[int, np.ndarray] = {}
            for shard in range(k + mm):
                f = self._read_shard(pool_id, pg, name, shard, up)
                if f is not None and len(f) >= info.n_stripes * U:
                    files[shard] = f
            if not set(range(k)) <= set(files):
                continue
            dchunks = np.stack([
                files[c].reshape(info.n_stripes, U) for c in range(k)],
                axis=1)                              # [S, k, U]
            parity = np.asarray(codec.encode_chunks_batch(dchunks))
            for j in range(mm):
                if k + j in files:
                    want = files[k + j].reshape(info.n_stripes, U)
                    if not np.array_equal(parity[:, j], want):
                        bad.append((name, k + j))
        return bad
