"""Single-process cluster simulator — the end-to-end slice.

A memstore-backed fake cluster (the role of src/os/memstore/ + vstart.sh
in the reference's test strategy, SURVEY.md §4): N simulated OSDs hold
shard payloads in dicts; placement runs through the real OSDMap pipeline
(batched CRUSH on device); EC pools stripe/encode through the real codec
registry (batched bit-plane matmuls on device).

put(object) → ps hash → PG → up set → store shards on OSDs
get(object) → gather surviving shards → minimum_to_decode → decode
kill/out OSDs → remap diff (old vs new batched mapping) → recover_all
rebuilds lost shards via batched decode and re-places them — the
ECBackend recovery flow (src/osd/ECBackend.cc:757,433,462) collapsed
into array programs (BASELINE config #5).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..ec import instance as ec_registry
from ..ec.interface import ErasureCodeError
from ..ops import hashing
from ..placement.crush_map import ITEM_NONE
from .osdmap import OSDMap, PGPool, POOL_ERASURE, POOL_REPLICATED

ShardKey = Tuple[int, int, str, int]   # (pool, pg, object, shard)


class SimOSD:
    """A fake OSD: a dict object store (memstore) plus liveness."""

    def __init__(self, osd_id: int):
        self.id = osd_id
        self.store: Dict[ShardKey, np.ndarray] = {}
        self.alive = True

    def put(self, key: ShardKey, data: np.ndarray) -> None:
        if not self.alive:
            raise IOError(f"osd.{self.id} is dead")
        self.store[key] = np.asarray(data, dtype=np.uint8).copy()

    def get(self, key: ShardKey) -> Optional[np.ndarray]:
        if not self.alive:
            return None
        return self.store.get(key)

    def delete(self, key: ShardKey) -> None:
        self.store.pop(key, None)


@dataclass
class ObjectInfo:
    """Client-side record of a written object (size for unpad)."""
    size: int
    chunk_size: int


class ClusterSim:
    """OSDMap + memstore OSDs + codec data path, in one process."""

    def __init__(self, osdmap: OSDMap):
        self.osdmap = osdmap
        self.osds = [SimOSD(i) for i in range(osdmap.max_osd)]
        self.codecs: Dict[int, object] = {}
        self.objects: Dict[Tuple[int, str], ObjectInfo] = {}
        self.ec_profiles: Dict[str, Dict[str, str]] = {}

    # ------------------------------------------------------------- pools --
    def create_ec_profile(self, name: str, profile: Dict[str, str]) -> None:
        """Validates by instantiating the plugin, like the mon
        (src/mon/OSDMonitor.cc:7349-7444)."""
        ec_registry().factory(profile.get("plugin", "jax"), profile)
        self.ec_profiles[name] = dict(profile)

    def codec_for(self, pool: PGPool):
        codec = self.codecs.get(pool.id)
        if codec is None:
            prof = self.ec_profiles[pool.erasure_code_profile]
            codec = ec_registry().factory(prof.get("plugin", "jax"), prof)
            self.codecs[pool.id] = codec
        return codec

    # ---------------------------------------------------------- placement --
    def object_pg(self, pool: PGPool, name: str) -> int:
        ps = hashing.str_hash_rjenkins(name.encode())
        return pool.raw_pg_to_pg(ps)

    def pg_up(self, pool: PGPool, pg: int) -> List[int]:
        up, _, acting, _ = self.osdmap.pg_to_up_acting_osds(pool.id, pg)
        return acting or up

    # --------------------------------------------------------------- I/O --
    def put(self, pool_id: int, name: str, data: bytes) -> List[int]:
        pool = self.osdmap.pools[pool_id]
        pg = self.object_pg(pool, name)
        up = self.pg_up(pool, pg)
        if pool.type == POOL_REPLICATED:
            payload = np.frombuffer(data, dtype=np.uint8)
            placed = []
            for o in up:
                if o == ITEM_NONE:
                    continue
                self.osds[o].put((pool_id, pg, name, 0), payload)
                placed.append(o)
            self.objects[(pool_id, name)] = ObjectInfo(len(data), len(data))
            return placed
        codec = self.codec_for(pool)
        k, mm = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
        chunks = codec.encode(set(range(k + mm)), data)
        placed = []
        for shard, payload in chunks.items():
            tgt = up[shard] if shard < len(up) else ITEM_NONE
            if tgt == ITEM_NONE:
                continue   # degraded write: shard currently homeless
            self.osds[tgt].put((pool_id, pg, name, shard), payload)
            placed.append(tgt)
        self.objects[(pool_id, name)] = ObjectInfo(
            len(data), codec.get_chunk_size(len(data)))
        return placed

    def get(self, pool_id: int, name: str) -> bytes:
        pool = self.osdmap.pools[pool_id]
        info = self.objects[(pool_id, name)]
        pg = self.object_pg(pool, name)
        up = self.pg_up(pool, pg)
        if pool.type == POOL_REPLICATED:
            # up set first, then any live OSD (stale-map / pre-recovery
            # reads, same as the EC branch below)
            sources = [o for o in up if o != ITEM_NONE] + \
                [o.id for o in self.osds]
            for o in sources:
                payload = self.osds[o].get((pool_id, pg, name, 0))
                if payload is not None:
                    return payload.tobytes()[:info.size]
            raise IOError(f"object {name}: no replica available")
        codec = self.codec_for(pool)
        k, mm = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
        avail: Dict[int, np.ndarray] = {}
        # shards may live on osds outside the current up set (stale map);
        # search up first, then everywhere (the real system would backfill)
        for shard in range(k + mm):
            tgt = up[shard] if shard < len(up) else ITEM_NONE
            sources = ([tgt] if tgt != ITEM_NONE else []) + \
                [o.id for o in self.osds]
            for o in sources:
                payload = self.osds[o].get((pool_id, pg, name, shard))
                if payload is not None:
                    avail[shard] = payload
                    break
        plan = codec.minimum_to_decode(set(range(k)), set(avail))
        out = codec.decode_concat({c: avail[c] for c in plan})
        return out.tobytes()[:info.size]

    # ----------------------------------------------------------- failure --
    def kill_osd(self, osd: int) -> None:
        """Thrasher-style kill (qa/tasks/ceph_manager.py kill_osd): process
        death — store contents are lost to the cluster."""
        self.osds[osd].alive = False
        self.osdmap.mark_down(osd)

    def out_osd(self, osd: int) -> None:
        self.osdmap.mark_out(osd)

    def revive_osd(self, osd: int) -> None:
        self.osds[osd].alive = True
        self.osdmap.osd_up[osd] = True
        self.osdmap.osd_weight[osd] = 0x10000
        self.osdmap.bump_epoch()

    # ---------------------------------------------------------- recovery --
    def remap_diff(self, pool_id: int, old_up: np.ndarray
                   ) -> Dict[int, List[int]]:
        """Batched old-vs-new mapping diff: {pg: shards whose home moved}."""
        new_up, _ = self.osdmap.map_pgs_batch(pool_id)
        diffs: Dict[int, List[int]] = {}
        n = min(len(old_up), len(new_up))
        for pg in range(n):
            moved = [s for s in range(new_up.shape[1])
                     if old_up[pg][s] != new_up[pg][s]]
            if moved:
                diffs[pg] = moved
        return diffs

    def recover_all(self, pool_id: int) -> Dict[str, int]:
        """Rebuild every unreadable/misplaced shard onto the current up set.

        The batched analog of ECBackend::recover_object: group damaged
        stripes by erasure signature, decode each group in one batched
        device call, write rebuilt shards to their new homes.
        """
        pool = self.osdmap.pools[pool_id]
        stats = {"objects_scanned": 0, "shards_rebuilt": 0,
                 "shards_copied": 0, "batches": 0}
        if pool.type == POOL_REPLICATED:
            for (pid, name), info in self.objects.items():
                if pid != pool_id:
                    continue
                stats["objects_scanned"] += 1
                pg = self.object_pg(pool, name)
                up = self.pg_up(pool, pg)
                payload = None
                for o in range(len(self.osds)):
                    p = self.osds[o].get((pool_id, pg, name, 0))
                    if p is not None:
                        payload = p
                        break
                if payload is None:
                    continue
                for o in up:
                    if o != ITEM_NONE and \
                            self.osds[o].get((pool_id, pg, name, 0)) is None:
                        self.osds[o].put((pool_id, pg, name, 0), payload)
                        stats["shards_copied"] += 1
            return stats

        codec = self.codec_for(pool)
        k, mm = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
        n_shards = k + mm
        # signature -> list of (pg, name, up, avail_chunks dict)
        groups: Dict[Tuple[Tuple[int, ...], Tuple[int, ...]], List] = {}
        for (pid, name), info in self.objects.items():
            if pid != pool_id:
                continue
            stats["objects_scanned"] += 1
            pg = self.object_pg(pool, name)
            up = self.pg_up(pool, pg)
            avail: Dict[int, np.ndarray] = {}
            missing: List[int] = []
            for shard in range(n_shards):
                found = None
                for o in range(len(self.osds)):
                    p = self.osds[o].get((pool_id, pg, name, shard))
                    if p is not None:
                        found = p
                        break
                if found is None:
                    missing.append(shard)
                else:
                    avail[shard] = found
            if missing:
                # chunk size is part of the key: stripes only batch with
                # shape-identical peers
                chunk_len = len(next(iter(avail.values()))) if avail else 0
                key = (tuple(sorted(avail)[:k]), tuple(missing), chunk_len)
                groups.setdefault(key, []).append((pg, name, up, avail))
            # re-place surviving shards that are off their new home
            for shard, payload in avail.items():
                tgt = up[shard] if shard < len(up) else ITEM_NONE
                if tgt != ITEM_NONE and \
                        self.osds[tgt].get((pool_id, pg, name, shard)) is None:
                    self.osds[tgt].put((pool_id, pg, name, shard), payload)
                    stats["shards_copied"] += 1
        for (use, missing, _chunk_len), members in groups.items():
            if len(use) < k:
                continue   # unrecoverable group
            stats["batches"] += 1
            batch = np.stack([
                np.stack([avail[c] for c in use]) for _, _, _, avail
                in members])
            rebuilt = codec.decode_chunks_batch(list(use), batch,
                                                list(missing))
            for i, (pg, name, up, _avail) in enumerate(members):
                for j, shard in enumerate(missing):
                    tgt = up[shard] if shard < len(up) else ITEM_NONE
                    if tgt == ITEM_NONE:
                        continue
                    self.osds[tgt].put((pool_id, pg, name, shard),
                                       rebuilt[i, j])
                    stats["shards_rebuilt"] += 1
        return stats

    # -------------------------------------------------------------- scrub --
    def scrub(self, pool_id: int) -> List[Tuple[str, int]]:
        """Deep-scrub analog: re-encode data shards and compare parity
        (the checksum-compare role of src/osd/pg_scrubber.cc)."""
        pool = self.osdmap.pools[pool_id]
        if pool.type != POOL_ERASURE:
            return []
        codec = self.codec_for(pool)
        k, mm = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
        bad: List[Tuple[str, int]] = []
        for (pid, name), info in self.objects.items():
            if pid != pool_id:
                continue
            pg = self.object_pg(pool, name)
            shards: Dict[int, np.ndarray] = {}
            for shard in range(k + mm):
                for o in range(len(self.osds)):
                    p = self.osds[o].get((pool_id, pg, name, shard))
                    if p is not None:
                        shards[shard] = p
                        break
            if set(range(k)) <= set(shards):
                parity = codec.encode_chunks(
                    np.stack([shards[i] for i in range(k)]))
                for j in range(mm):
                    if k + j in shards and \
                            not np.array_equal(parity[j], shards[k + j]):
                        bad.append((name, k + j))
        return bad
