"""BlueStore — block-device extent ObjectStore backend (L5).

The role of the reference's flagship store (src/os/bluestore/
BlueStore.cc — raw-device extents + RocksDB metadata + allocators +
per-block checksums + inline compression + deferred small writes),
re-designed around this repo's own seams rather than ported:

  * the "raw device" is one fixed-size ``block`` file carved into
    ``min_alloc``-sized blocks; free space is tracked by the native
    bitmap allocator (native/allocator_native.cpp — the
    BitmapAllocator role, src/os/bluestore/BitmapAllocator.h);
  * object metadata (onode: size + blob/extent map), xattrs and omap
    rows live in WalDB (the RocksDB role) and commit as ONE batch per
    transaction — the atomic commit point;
  * new data is written copy-on-write into freshly allocated blocks
    and fsynced BEFORE the KV commit, so a torn transaction can never
    clobber committed bytes; freed blocks are released only AFTER the
    commit (same reasoning, in-process);
  * every blob carries a crc32 per ``min_alloc`` stored block —
    partial reads verify only the blocks they touch and raise
    ChecksumError (EIO) on mismatch, BlueStore's csum-on-read stance;
  * blobs at/above ``compress_min`` are compressed through the
    compressor plugin registry (common/compressor.py) when it actually
    saves space — stored_len < raw_len is recorded in the blob header
    (the role of bluestore_compression_mode=aggressive);
  * small overwrites that land inside one existing uncompressed blob
    take the DEFERRED path (src/os/bluestore/BlueStore.cc deferred
    writes): the merged block bytes ride the KV commit batch and are
    applied to the device in place afterwards; mount() replays any
    deferred rows left by a crash (idempotent pwrites), so the KV
    batch remains the single durability point;
  * there is NO persisted freelist: mount() rebuilds the allocator
    bitmap from the committed onodes (the post-Pacific BlueStore "NCB"
    stance), and double-allocation across onodes is detected while
    marking — that is fsck's allocation check.

Crash model (kill -9 anywhere): a transaction is visible iff its KV
batch committed; COW data for uncommitted transactions sits in blocks
the rebuilt allocator still considers free.  See
tests/test_bluestore.py for the kill -9 storm.
"""
from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..common import crcutil
from ..common.compressor import compressors
from ..common.perf_counters import perf as _perf
from ..native_bridge import AllocatorError, BitmapAllocator
from .blockdev import BlockDevice
from .kv import WriteBatch, rm_object_rows
from .objectstore import (ChecksumError, Coll, ObjectStoreError,
                          OP_OMAP_RM, OP_OMAP_SET, OP_REMOVE, OP_SETATTR,
                          OP_TOUCH, OP_TRUNCATE, OP_WRITE, OP_WRITE_FULL,
                          Transaction)
from .wal_kv import WalDB

_BLOB_HDR = struct.Struct("<BBIIHI")     # flags, comp_id, raw_len,
                                         #   stored_len, n_runs, n_csums
_RUN = struct.Struct("<QI")              # start_block, n_blocks
_EXT = struct.Struct("<QIII")            # obj_off, length, blob_idx,
                                         #   blob_off (into RAW stream)
_DEF = struct.Struct("<QI")              # dev_byte_off, payload_len

FLAG_COMPRESSED = 1
ONDISK_FORMAT = 2               # blob headers carry a compressor id

# per-blob compressor ids (persisted in the blob header, so a remount
# never has to GUESS which algorithm wrote a blob — the reference
# records the compressor per blob too, bluestore_blob_t::COMP types)
_COMP_IDS = {"": 0, "zlib": 1, "lzma": 2, "bz2": 3, "zstd": 4}
_COMP_NAMES = {v: k for k, v in _COMP_IDS.items()}


@dataclass
class Blob:
    """A stored region: stored_len bytes across `runs` device blocks,
    raw_len logical bytes after decompression, one crc32 per stored
    min_alloc block (the bluestore_blob_t + csum array role)."""
    flags: int = 0
    raw_len: int = 0
    stored_len: int = 0
    runs: List[Tuple[int, int]] = field(default_factory=list)
    csums: List[int] = field(default_factory=list)
    comp: str = ""                  # compressor that wrote this blob

    @property
    def compressed(self) -> bool:
        return bool(self.flags & FLAG_COMPRESSED)

    def n_blocks(self) -> int:
        return sum(n for _, n in self.runs)


@dataclass
class Onode:
    """Per-object metadata: logical size + extent map over blobs (the
    bluestore onode_t/extent_map role).  Extents are sorted by
    obj_off and never overlap (writes punch before inserting)."""
    size: int = 0
    blobs: List[Blob] = field(default_factory=list)
    # (obj_off, length, blob_idx, blob_off)
    extents: List[Tuple[int, int, int, int]] = field(default_factory=list)

    def encode(self) -> bytes:
        out = [struct.pack("<QI", self.size, len(self.blobs))]
        for b in self.blobs:
            out.append(_BLOB_HDR.pack(b.flags, _COMP_IDS[b.comp],
                                      b.raw_len, b.stored_len,
                                      len(b.runs), len(b.csums)))
            out += [_RUN.pack(*r) for r in b.runs]
            out.append(struct.pack(f"<{len(b.csums)}I", *b.csums))
        out.append(struct.pack("<I", len(self.extents)))
        out += [_EXT.pack(*e) for e in self.extents]
        return b"".join(out)

    @classmethod
    def decode(cls, blob: bytes) -> "Onode":
        size, n_blobs = struct.unpack_from("<QI", blob, 0)
        off = 12
        blobs = []
        for _ in range(n_blobs):
            flags, comp_id, raw_len, stored_len, n_runs, n_csums = \
                _BLOB_HDR.unpack_from(blob, off)
            off += _BLOB_HDR.size
            runs = []
            for _ in range(n_runs):
                runs.append(_RUN.unpack_from(blob, off))
                off += _RUN.size
            csums = list(struct.unpack_from(f"<{n_csums}I", blob, off))
            off += 4 * n_csums
            comp = _COMP_NAMES.get(comp_id)
            if comp is None:
                # fsck catches ObjectStoreError and reports the object
                # as bad; a bare KeyError would escape it
                raise ObjectStoreError(
                    f"unknown compressor id {comp_id}")
            blobs.append(Blob(flags, raw_len, stored_len, runs, csums,
                              comp))
        (n_ext,) = struct.unpack_from("<I", blob, off)
        off += 4
        extents = []
        for _ in range(n_ext):
            extents.append(_EXT.unpack_from(blob, off))
            off += _EXT.size
        return cls(size=size, blobs=blobs, extents=extents)


def _collkey(coll: Coll) -> str:
    return f"{coll[0]}.{coll[1]}"


def _objkey(coll: Coll, oid: str) -> str:
    return f"{_collkey(coll)}/{oid}"


def _split_objkey(key: str) -> Tuple[Coll, str]:
    ck, oid = key.split("/", 1)
    p, g = ck.split(".", 1)
    return (int(p), int(g)), oid


class BlueStore:
    """Durable block-device ObjectStore (block file + WalDB metadata)."""

    def __init__(self, path: str, *, device_bytes: int = 1 << 28,
                 min_alloc: int = 4096, fsync: bool = True,
                 compression: Optional[str] = None,
                 compress_min: int = 4096,
                 deferred_max: Optional[int] = None,
                 compact_extents: int = 64,
                 fsck_on_mount: bool = True):
        self.path = path
        self.fsync = fsync
        os.makedirs(path, exist_ok=True)
        self.kv = WalDB(os.path.join(path, "kv"), fsync=fsync)
        # superblock: geometry is fixed at mkfs; remounts use the stored
        # values (passing different ones is a config error, not a
        # resize).  A format version gates incompatible onode layouts
        # (the ondisk_format/compat_ondisk_format role) — misdecoding
        # an old store must be a clear refusal, not garbage extents.
        sb = self.kv.get("meta", "superblock")
        if sb is None:
            self.device_bytes = int(device_bytes)
            self.min_alloc = int(min_alloc)
            self.kv.set("meta", "superblock", struct.pack(
                "<QII", self.device_bytes, self.min_alloc,
                ONDISK_FORMAT))
        elif len(sb) == 12:          # v1: no version field, old blobs
            raise ObjectStoreError(
                "incompatible on-disk format v1 (pre-versioned blob "
                f"headers); this build reads format {ONDISK_FORMAT}")
        else:
            self.device_bytes, self.min_alloc, fmt = \
                struct.unpack("<QII", sb)
            if fmt != ONDISK_FORMAT:
                raise ObjectStoreError(
                    f"incompatible on-disk format {fmt} "
                    f"(this build reads {ONDISK_FORMAT})")
        if self.device_bytes % self.min_alloc:
            raise ObjectStoreError("device size not block-aligned")
        self.n_blocks = self.device_bytes // self.min_alloc
        self.compress_min = compress_min
        self.compact_extents = compact_extents
        self.deferred_max = (self.min_alloc if deferred_max is None
                             else deferred_max)
        if compression and compression not in _COMP_IDS:
            # fail at mkfs/mount, not mid-commit in Onode.encode (a
            # KeyError there would strike after blocks were allocated)
            raise ValueError(
                f"unsupported BlueStore compressor {compression!r}; "
                f"choose from {sorted(k for k in _COMP_IDS if k)}")
        self._comp = (compressors().factory(compression)
                      if compression else None)
        self._comp_name = compression
        dev_path = os.path.join(path, "block")
        # the block device behind the barrier API: every data byte
        # this store persists is visible to the crash-state recorder
        # (cluster/blockdev.py), and the device.* power-loss
        # faultpoints fire inside it
        self._dev = BlockDevice(dev_path, size=self.device_bytes)
        self._lock = threading.RLock()
        self._pc = _perf("bluestore")
        self.txns_applied = 0
        self.deferred_applied = 0
        # cold-restart observability: the KV mount already replayed
        # its WAL — surface records/bytes/duration as perf counters
        # (the recovery-trajectory datapoint bench_crash_recovery reads)
        rs = self.kv.replay_stats
        self._pc.inc("wal_replay_entries", int(rs["records"]))
        self._pc.inc("wal_replay_bytes", int(rs["bytes"]))
        self._pc.set("wal_replay_last_s", round(rs["seconds"], 6))
        self.alloc = BitmapAllocator(self.n_blocks)
        try:
            self._rebuild_allocations()
            self._replay_deferred()
            bad = self.fsck() if fsck_on_mount else []
        except Exception:
            self.close()        # no fd leak on a failed mount
            raise
        if bad:
            self.close()
            raise ObjectStoreError(f"fsck on mount: bad objects {bad}")

    # ------------------------------------------------------------- mount --
    def _rebuild_allocations(self) -> None:
        """NCB freelist rebuild: mark every committed blob's runs; an
        overlap here is on-disk corruption."""
        for key, blob in self.kv.iterate("onode"):
            onode = Onode.decode(blob)
            for b in onode.blobs:
                for start, n in b.runs:
                    try:
                        self.alloc.mark(start, n)
                    except AllocatorError as e:
                        raise ObjectStoreError(
                            f"mount: {key}: double-allocated blocks "
                            f"[{start},+{n}): {e}") from e

    def _replay_deferred(self) -> None:
        """Re-apply deferred writes whose in-place pwrite may not have
        happened before a crash (idempotent), then drop the rows."""
        t0 = time.perf_counter()
        rows = list(self.kv.iterate("deferred"))
        self.deferred_replayed = len(rows)
        self.deferred_replay_bytes = 0
        self.deferred_replay_s = 0.0
        if not rows:
            return
        batch = WriteBatch()
        for key, payload in rows:
            dev_off, ln = _DEF.unpack_from(payload, 0)
            data = payload[_DEF.size:_DEF.size + ln]
            self._dev.pwrite(data, dev_off)
            self.deferred_replay_bytes += ln
            batch.rm("deferred", key)
        if self.fsync:
            self._dev.fsync()
        self.kv.submit(batch)
        self.deferred_replay_s = time.perf_counter() - t0
        self._pc.inc("deferred_replay_entries", len(rows))
        self._pc.inc("deferred_replay_bytes",
                     self.deferred_replay_bytes)
        self._pc.set("deferred_replay_last_s",
                     round(self.deferred_replay_s, 6))

    # ------------------------------------------------------------ helpers --
    def _onode(self, coll: Coll, oid: str) -> Optional[Onode]:
        blob = self.kv.get("onode", _objkey(coll, oid))
        return Onode.decode(blob) if blob is not None else None

    def _blob_block_list(self, blob: Blob) -> List[int]:
        blocks: List[int] = []
        for start, n in blob.runs:
            blocks.extend(range(start, start + n))
        return blocks

    def _read_stored(self, blob: Blob, s0: int, s1: int,
                     check: bool = True) -> bytes:
        """Read stored bytes [s0, s1) of a blob, verifying the crc of
        every touched stored block."""
        if s1 > blob.stored_len:
            raise ObjectStoreError("stored read past blob end")
        c0 = s0 // self.min_alloc
        c1 = (s1 + self.min_alloc - 1) // self.min_alloc
        blocks = self._blob_block_list(blob)
        parts = []
        # ONE device read per contiguous device run (crc verification
        # stays per-block on the slices) — the read-side twin of
        # _make_blob's batched writes
        ci = c0
        while ci < c1:
            cj = ci + 1
            while cj < c1 and blocks[cj] == blocks[cj - 1] + 1:
                cj += 1
            want = min((cj - ci) * self.min_alloc,
                       blob.stored_len - ci * self.min_alloc)
            buf = self._dev.pread(want, blocks[ci] * self.min_alloc)
            if len(buf) != want:
                raise ChecksumError(
                    f"blob blocks {ci}..{cj} @dev {blocks[ci]}: "
                    f"short device read (EIO)")
            mv = memoryview(buf)
            for k in range(ci, cj):
                lo = (k - ci) * self.min_alloc
                chunk = mv[lo:lo + self.min_alloc]
                if check and zlib.crc32(chunk) != blob.csums[k]:
                    raise ChecksumError(
                        f"blob block {k} @dev {blocks[k]}: data "
                        f"fails checksum (EIO)")
            parts.append(buf)
            ci = cj
        joined = b"".join(parts)
        lo = s0 - c0 * self.min_alloc
        return joined[lo:lo + (s1 - s0)]

    def _read_raw(self, blob: Blob, r0: int, r1: int) -> bytes:
        """Read RAW (decompressed) bytes [r0, r1) of a blob."""
        if blob.compressed:
            stored = self._read_stored(blob, 0, blob.stored_len)
            # the blob header names its own compressor — remount args
            # never matter for readback
            raw = compressors().factory(blob.comp or "zlib") \
                .decompress(stored)
            if len(raw) != blob.raw_len:
                raise ChecksumError("decompressed length mismatch (EIO)")
            return raw[r0:r1]
        return self._read_stored(blob, r0, r1)

    @staticmethod
    def _punch(onode: Onode, off: int, length: int) -> None:
        """Remove [off, off+length) from the extent map, splitting
        extents that straddle the boundary.  Blobs stay (possibly
        partially referenced); _reap_blobs drops unreferenced ones."""
        end = off + length
        out: List[Tuple[int, int, int, int]] = []
        for e_off, e_len, bi, b_off in onode.extents:
            e_end = e_off + e_len
            if e_end <= off or e_off >= end:
                out.append((e_off, e_len, bi, b_off))
                continue
            if e_off < off:                    # keep head
                out.append((e_off, off - e_off, bi, b_off))
            if e_end > end:                    # keep tail
                cut = end - e_off
                out.append((end, e_end - end, bi, b_off + cut))
        out.sort()
        onode.extents = out

    @staticmethod
    def _reap_blobs(onode: Onode) -> List[Tuple[int, int]]:
        """Drop blobs no extent references; returns their runs (to be
        released AFTER commit) and renumbers extent blob indices."""
        referenced = {bi for _, _, bi, _ in onode.extents}
        freed: List[Tuple[int, int]] = []
        remap: Dict[int, int] = {}
        kept: List[Blob] = []
        for i, b in enumerate(onode.blobs):
            if i in referenced:
                remap[i] = len(kept)
                kept.append(b)
            else:
                freed.extend(b.runs)
        onode.blobs = kept
        onode.extents = [(o, ln, remap[bi], bo)
                         for o, ln, bi, bo in onode.extents]
        return freed

    def _make_blob(self, data, trusted=None
                   ) -> Tuple[Blob, List[Tuple[int, bytes]]]:
        """Build a blob for `data`: maybe compress, allocate blocks,
        return (blob, [(dev_byte_off, payload)]) pending device writes.
        Allocator state IS mutated — the caller must release on txn
        failure.

        ``trusted`` (common/crcutil.Csums over exactly these bytes)
        is the one-pass integrity handoff: the wire's verify scan
        already computed per-min_alloc sub-crcs for this payload, so
        the store ADOPTS them as blob csums instead of running its
        own third pass.  Only applies when the bytes are stored
        verbatim (no compression win) and the block geometries match;
        any mismatch falls back to the local scan."""
        raw_len = len(data)
        stored = data
        flags = 0
        comp_name = ""
        if (self._comp is not None and raw_len >= self.compress_min):
            c = self._comp.compress(data)
            # only keep a win that saves at least one block
            if (len(c) + self.min_alloc - 1) // self.min_alloc < \
                    (raw_len + self.min_alloc - 1) // self.min_alloc:
                stored = c
                flags = FLAG_COMPRESSED
                comp_name = self._comp_name or ""
        n_blocks = (len(stored) + self.min_alloc - 1) // self.min_alloc
        runs = [(int(s), int(n))
                for s, n in self.alloc.allocate(n_blocks)]
        mv = crcutil.as_u8(stored)
        if trusted is not None and not flags and \
                trusted.block == self.min_alloc and \
                trusted.length == len(stored):
            csums = list(trusted.subs)
            crcutil.note_trusted(len(stored))
        else:
            csums = []
            for b in range(n_blocks):
                csums.append(zlib.crc32(
                    mv[b * self.min_alloc:
                       min((b + 1) * self.min_alloc, len(stored))]))
            crcutil.note_scan(len(stored), "store")
        writes: List[Tuple[int, bytes]] = []
        ci = 0
        zero_copy = crcutil.flag("wire_zero_copy")
        # ONE device write per contiguous run (not per block): the
        # checksum granularity stays min_alloc, the syscall count
        # drops from stored_len/min_alloc to len(runs) — this is the
        # difference between ~256 pwrites and ~1 for a 1 MiB shard.
        # The run payloads are VIEWS over the caller's buffer (the
        # wire frame), so the bytes go receive buffer -> page cache
        # with no intermediate materialization.
        for start, n in runs:
            lo = ci * self.min_alloc
            hi = min(lo + n * self.min_alloc, len(stored))
            if zero_copy:
                writes.append((start * self.min_alloc, mv[lo:hi]))
            else:
                crcutil.note_copy(hi - lo, "make_blob")
                writes.append((start * self.min_alloc,
                               bytes(mv[lo:hi])))  # noqa: CTL130 —
                # the counted legacy path the bench prices
            ci += n
        return Blob(flags, raw_len, len(stored), runs, csums,
                    comp_name), writes

    # ------------------------------------------------------------- write --
    def apply_transaction(self, txn: Transaction) -> None:
        with self._lock:
            self._apply_locked(txn)

    def _apply_locked(self, txn: Transaction) -> None:
        txn_csums = getattr(txn, "csums", None) or {}
        staged: Dict[Tuple[Coll, str], Optional[Onode]] = {}
        xattrs: Dict[Tuple[Coll, str, str], Optional[bytes]] = {}
        omaps: Dict[Tuple[Coll, str, str], Optional[bytes]] = {}
        pending: List[Tuple[int, bytes]] = []     # COW device writes
        # deferred in-place updates, keyed per staged object so a
        # same-txn remove drops them: (dev_byte_off, payload)
        deferred: Dict[Tuple[Coll, str], List[Tuple[int, bytes]]] = {}
        newly_allocated: List[Tuple[int, int]] = []
        to_release: List[Tuple[int, int]] = []

        def stage(coll: Coll, oid: str, create: bool) -> Optional[Onode]:
            key = (coll, oid)
            if key not in staged:
                cur = self._onode(coll, oid)
                if cur is None:
                    staged[key] = Onode() if create else None
                else:
                    staged[key] = Onode(cur.size,
                                        [Blob(b.flags, b.raw_len,
                                              b.stored_len, list(b.runs),
                                              list(b.csums), b.comp)
                                         for b in cur.blobs],
                                        list(cur.extents))
            elif staged[key] is None and create:
                staged[key] = Onode()
            return staged[key]

        def rm_obj_rows(coll: Coll, oid: str) -> None:
            ok = _objkey(coll, oid) + "\x00"
            for prefix, sink in (("xattr", xattrs), ("omap", omaps)):
                for k, _ in self.kv.iterate(prefix, start=ok):
                    if not k.startswith(ok):
                        break
                    sink[(coll, oid, k[len(ok):])] = None
            for sink in (xattrs, omaps):
                for (c2, o2, k2) in list(sink):
                    if (c2, o2) == (coll, oid):
                        sink[(c2, o2, k2)] = None

        fresh_blobs: set = set()              # id(blob) created this txn

        def maybe_compact(o: Onode, key) -> None:
            """Extent-map defragmentation (the BlueStore blob-gc role):
            once an object's map outgrows ``compact_extents``, rewrite
            it as one blob.  Only safe when every referenced byte is
            committed on the device (no fresh blobs, no pending
            deferred merges for this object)."""
            if len(o.extents) < self.compact_extents or \
                    key in deferred or \
                    any(id(o.blobs[bi]) in fresh_blobs
                        for _, _, bi, _ in o.extents):
                return
            content = self._read_onode(o, 0, o.size)
            for b in o.blobs:
                to_release.extend(b.runs)
            o.blobs = []
            o.extents = []
            if content:
                new_blob(o, content, 0)

        def new_blob(o: Onode, data, obj_off: int,
                     trusted=None) -> None:
            blob, writes = self._make_blob(data, trusted=trusted)
            fresh_blobs.add(id(blob))
            newly_allocated.extend(blob.runs)
            pending.extend(writes)
            self._punch(o, obj_off, len(data))
            o.blobs.append(blob)
            o.extents.append((obj_off, len(data), len(o.blobs) - 1, 0))
            o.extents.sort()
            to_release.extend(self._reap_blobs(o))

        def try_deferred(o: Onode, key, obj_off: int,
                         data: bytes) -> bool:
            """Small overwrite fully inside ONE uncompressed extent →
            merge into the affected stored blocks in place; payload
            rides the KV batch (the BlueStore deferred-write path)."""
            if len(data) > self.deferred_max:
                return False
            for e_off, e_len, bi, b_off in o.extents:
                if not (e_off <= obj_off and
                        obj_off + len(data) <= e_off + e_len):
                    continue
                blob = o.blobs[bi]
                if blob.compressed or id(blob) in fresh_blobs:
                    # fresh blobs' COW bytes are not on the device yet
                    # — read-merge would see garbage; take the COW path
                    return False
                s0 = b_off + (obj_off - e_off)      # stored offset
                s1 = s0 + len(data)
                c0 = s0 // self.min_alloc
                c1 = (s1 + self.min_alloc - 1) // self.min_alloc
                lo = c0 * self.min_alloc
                blocks = self._blob_block_list(blob)
                prior = deferred.get(key, [])
                # read-merge per touched stored block: a prior same-txn
                # deferred payload for the block IS its current content
                # (the device is stale until post-commit apply);
                # otherwise read the device and verify its crc.  A
                # block the write FULLY covers is never read at all —
                # the old double-verify re-crc'd device bytes that the
                # merge was about to overwrite wholesale (the
                # read-back-re-scan class ISSUE 15 retires): its
                # content below is placeholder zeros the overwrite
                # replaces byte-for-byte.
                cur = bytearray()
                for ci in range(c0, c1):
                    bs = blocks[ci] * self.min_alloc
                    blk_end = min((ci + 1) * self.min_alloc,
                                  blob.stored_len)
                    hit = next((p for off2, p in reversed(prior)
                                if off2 == bs), None)
                    if hit is not None:
                        chunk = hit
                    elif s0 <= ci * self.min_alloc and s1 >= blk_end:
                        chunk = bytes(blk_end - ci * self.min_alloc)
                    else:
                        chunk = self._read_stored(
                            blob, ci * self.min_alloc, blk_end)
                    cur.extend(chunk)
                cur[s0 - lo:s1 - lo] = data
                # per-block csum refresh + device payloads
                dq = deferred.setdefault(key, [])
                for ci in range(c0, c1):
                    blo = (ci - c0) * self.min_alloc
                    chunk = bytes(cur[blo:blo + self.min_alloc])
                    blob.csums[ci] = zlib.crc32(chunk)
                    dq.append((blocks[ci] * self.min_alloc, chunk))
                return True
            return False

        try:
            for op in txn.ops:
                kind = op[0]
                if kind == OP_TOUCH:
                    _, coll, oid = op
                    stage(coll, oid, create=True)
                elif kind == OP_WRITE_FULL:
                    _, coll, oid, data = op
                    o = stage(coll, oid, create=True)
                    # drop the whole extent map, then write one blob
                    for b in o.blobs:
                        to_release.extend(b.runs)
                    o.blobs = []
                    o.extents = []
                    o.size = len(data)
                    if len(data):
                        new_blob(o, data, 0,
                                 trusted=txn_csums.get((coll, oid)))
                    deferred.pop((coll, oid), None)
                elif kind == OP_WRITE:
                    _, coll, oid, offset, data = op
                    o = stage(coll, oid, create=True)
                    o.size = max(o.size, offset + len(data))
                    if not data:
                        continue
                    if not try_deferred(o, (coll, oid), offset,
                                        bytes(data)):
                        maybe_compact(o, (coll, oid))
                        new_blob(o, bytes(data), offset)
                elif kind == OP_TRUNCATE:
                    _, coll, oid, size = op
                    o = stage(coll, oid, create=False)
                    if o is None:
                        raise ObjectStoreError(
                            f"truncate: no object {oid}")
                    if size < o.size:
                        self._punch(o, size, o.size - size)
                        to_release.extend(self._reap_blobs(o))
                    o.size = size
                elif kind == OP_REMOVE:
                    _, coll, oid = op
                    o = stage(coll, oid, create=False)
                    if o is None:
                        raise ObjectStoreError(f"remove: no object {oid}")
                    for b in o.blobs:
                        to_release.extend(b.runs)
                    staged[(coll, oid)] = None
                    deferred.pop((coll, oid), None)
                    rm_obj_rows(coll, oid)
                elif kind == OP_SETATTR:
                    _, coll, oid, key, value = op
                    if stage(coll, oid, create=False) is None:
                        raise ObjectStoreError(f"setattr: no object {oid}")
                    xattrs[(coll, oid, key)] = value
                elif kind == OP_OMAP_SET:
                    _, coll, oid, key, value = op
                    if stage(coll, oid, create=False) is None:
                        raise ObjectStoreError(
                            f"omap_set: no object {oid}")
                    omaps[(coll, oid, key)] = value
                elif kind == OP_OMAP_RM:
                    _, coll, oid, key = op
                    if stage(coll, oid, create=False) is None:
                        raise ObjectStoreError(f"omap_rm: no object {oid}")
                    if omaps.get((coll, oid, key), b"") is None or (
                            (coll, oid, key) not in omaps and
                            self.kv.get(
                                "omap",
                                _objkey(coll, oid) + "\x00" + key)
                            is None):
                        raise ObjectStoreError(f"omap_rm: no key {key}")
                    omaps[(coll, oid, key)] = None
                else:
                    raise ObjectStoreError(f"unknown txn op {kind!r}")
        except Exception:
            # roll back this txn's allocations; nothing hit the KV
            for start, n in newly_allocated:
                self.alloc.release(start, n)
            raise

        # ---- COW data to the device FIRST (commit point is the KV) ----
        for dev_off, payload in pending:
            self._dev.pwrite(payload, dev_off)
        if pending and self.fsync:
            self._dev.fsync()

        batch = WriteBatch()
        def_rows: List[Tuple[str, int, bytes]] = []
        seq = self.txns_applied
        for (coll, oid), onode in staged.items():
            key = _objkey(coll, oid)
            if onode is None:
                batch.rm("onode", key)
            else:
                batch.set("onode", key, onode.encode())
        for (coll, oid, key), val in xattrs.items():
            row = _objkey(coll, oid) + "\x00" + key
            if val is None:
                batch.rm("xattr", row)
            else:
                batch.set("xattr", row, val)
        for (coll, oid, key), val in omaps.items():
            row = _objkey(coll, oid) + "\x00" + key
            if val is None:
                batch.rm("omap", row)
            else:
                batch.set("omap", row, val)
        for key, writes in deferred.items():
            if staged.get(key) is None:
                continue                      # object died this txn
            for i, (dev_off, payload) in enumerate(writes):
                row = f"{seq:016d}.{len(def_rows):04d}"
                batch.set("deferred", row,
                          _DEF.pack(dev_off, len(payload)) + payload)
                def_rows.append((row, dev_off, payload))
        self.kv.submit(batch)                 # ← the atomic commit point
        self.txns_applied += 1

        # ---- post-commit: deferred in-place applies, then cleanup ----
        if def_rows:
            clear = WriteBatch()
            for row, dev_off, payload in def_rows:
                self._dev.pwrite(payload, dev_off)
                clear.rm("deferred", row)
            # the rows may only be durably dropped once the in-place
            # bytes are ON the device — same order as _replay_deferred
            # (clearing first would lose the write on power cut)
            if self.fsync:
                self._dev.fsync()
            self.deferred_applied += len(def_rows)
            self.kv.submit(clear)
        for start, n in to_release:
            self.alloc.release(start, n)

    # -------------------------------------------------------------- read --
    # Reads hold the store lock: the post-commit deferred apply (and
    # allocator release) must not interleave with a reader that already
    # fetched the NEW onode but would see the OLD device bytes — that
    # window would surface as a spurious EIO on committed data.
    def _get(self, coll: Coll, oid: str) -> Onode:
        o = self._onode(coll, oid)
        if o is None:
            raise ObjectStoreError(f"no object {oid} in {coll}")
        return o

    def exists(self, coll: Coll, oid: str) -> bool:
        return self.kv.get("onode", _objkey(coll, oid)) is not None

    def _read_onode(self, o: Onode, offset: int, end: int) -> bytes:
        if end <= offset:
            return b""
        out = bytearray(end - offset)         # holes read as zeros
        for e_off, e_len, bi, b_off in o.extents:
            lo = max(e_off, offset)
            hi = min(e_off + e_len, end)
            if hi <= lo:
                continue
            raw = self._read_raw(o.blobs[bi], b_off + (lo - e_off),
                                 b_off + (hi - e_off))
            out[lo - offset:hi - offset] = raw
        return bytes(out)

    def read(self, coll: Coll, oid: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        with self._lock:
            o = self._get(coll, oid)
            end = (o.size if length is None
                   else min(offset + length, o.size))
            return self._read_onode(o, offset, end)

    def read_with_csums(self, coll: Coll, oid: str):
        """Full-object read PLUS the store-trusted sub-crcs:
        -> (data, crcutil.Csums | None).

        The reply-direction half of the one-pass handoff (RingReply):
        csum-on-read just verified every stored block against the
        blob csum array, so those csums are TRUSTED for the bytes
        being returned — the daemon's reply path folds them into the
        frame crc / ring doorbell via crc32_combine and sends with
        ZERO additional scans.  Only the simple write_full shape
        qualifies (one uncompressed blob storing the logical bytes
        verbatim, one extent covering [0, size)): overwrite histories
        and compressed blobs return csums None, and the sender runs
        its one counted scan exactly as before."""
        with self._lock:
            o = self._get(coll, oid)
            data = self._read_onode(o, 0, o.size)
            cs = None
            if len(o.blobs) == 1 and len(o.extents) == 1 and \
                    not o.blobs[0].compressed:
                b = o.blobs[0]
                e_off, e_len, _bi, b_off = o.extents[0]
                if (e_off == 0 and b_off == 0 and e_len == o.size
                        and b.raw_len == o.size
                        and b.stored_len == o.size
                        and len(b.csums) ==
                        (o.size + self.min_alloc - 1)
                        // self.min_alloc):
                    cs = crcutil.Csums(self.min_alloc,
                                       list(b.csums), o.size)
            return data, cs

    def stat(self, coll: Coll, oid: str) -> Dict[str, int]:
        with self._lock:
            o = self._get(coll, oid)
            # 'csum' is a CONTENT digest (crc over the logical bytes),
            # not a layout digest — replicas with different extent
            # histories must agree, that is what scrub compares
            return {"size": o.size,
                    "csum": zlib.crc32(self._read_onode(o, 0, o.size)),
                    "allocated": sum(b.n_blocks() for b in o.blobs)
                    * self.min_alloc,
                    "stored": sum(b.stored_len for b in o.blobs),
                    "extents": len(o.extents)}

    def getattr(self, coll: Coll, oid: str, key: str) -> bytes:
        with self._lock:
            v = self.kv.get("xattr", _objkey(coll, oid) + "\x00" + key)
            if v is None:
                self._get(coll, oid)   # object-missing error first
                raise KeyError(key)
            return v

    def omap_get(self, coll: Coll, oid: str, key: str) -> bytes:
        with self._lock:
            v = self.kv.get("omap", _objkey(coll, oid) + "\x00" + key)
            if v is None:
                self._get(coll, oid)
                raise KeyError(key)
            return v

    def omap_list(self, coll: Coll, oid: str,
                  start: str = "") -> List[Tuple[str, bytes]]:
        """All omap rows of an object from ``start`` (sorted) — the
        ObjectMap::get_iterator role (PG logs live here)."""
        with self._lock:
            ok = _objkey(coll, oid) + "\x00"
            out = []
            for k, v in self.kv.iterate("omap", start=ok + start):
                if not k.startswith(ok):
                    break
                out.append((k[len(ok):], v))
            return out

    def list_objects(self, coll: Coll) -> List[str]:
        ck = _collkey(coll) + "/"
        out = []
        for k, _ in self.kv.iterate("onode", start=ck):
            if not k.startswith(ck):
                break
            out.append(k[len(ck):])
        return sorted(out)

    def list_collections(self) -> List[Coll]:
        seen = set()
        for k, _ in self.kv.iterate("onode"):
            seen.add(_split_objkey(k)[0])
        return sorted(seen)

    def verify(self, coll: Coll, oid: str) -> bool:
        with self._lock:
            try:
                o = self._onode(coll, oid)
                if o is None:
                    return False
                for b in o.blobs:
                    self._read_stored(b, 0, b.stored_len)
                return True
            except (ChecksumError, ObjectStoreError):
                return False

    # ------------------------------------------------------------- fsck --
    def fsck(self, repair: bool = False) -> List[Tuple[Coll, str]]:
        """Walk every onode: csum-verify all stored bytes, bounds-check
        extents, and rebuild the allocation bitmap to detect
        double-allocated blocks (the BlueStore fsck roles).

        ``repair=True`` QUARANTINES each inconsistent object instead
        of just listing it: its onode + xattr/omap rows are dropped in
        one KV batch, so the object reads as missing and scrub /
        peering recovery re-replicate it from healthy copies (the
        fsck --repair stance: a locally-damaged replica must not keep
        serving EIO when the cluster holds good bytes).  Device blocks
        stay allocated until the next mount's NCB rebuild — leaking
        space is safe, releasing blocks a double-allocated twin still
        references is not.  Counted on perf counters
        ``bluestore.fsck_errors`` / ``bluestore.fsck_repaired``."""
        with self._lock:
            return self._fsck_locked(repair)

    def _fsck_locked(self, repair: bool = False
                     ) -> List[Tuple[Coll, str]]:
        bad = []
        shadow = BitmapAllocator(self.n_blocks)
        for key, raw in self.kv.iterate("onode"):
            coll, oid = _split_objkey(key)
            ok = True
            try:
                o = Onode.decode(raw)
                for b in o.blobs:
                    for start, n in b.runs:
                        shadow.mark(start, n)
                    want = ((b.stored_len + self.min_alloc - 1)
                            // self.min_alloc)
                    if b.n_blocks() < want or len(b.csums) != want:
                        raise ObjectStoreError("blob geometry")
                    self._read_stored(b, 0, b.stored_len)
                for e_off, e_len, bi, b_off in o.extents:
                    blob = o.blobs[bi]
                    if b_off + e_len > blob.raw_len or \
                            e_off + e_len > o.size:
                        raise ObjectStoreError("extent bounds")
            except (ChecksumError, ObjectStoreError, AllocatorError,
                    struct.error, IndexError):
                ok = False
            if not ok:
                bad.append((coll, oid))
        if bad:
            self._pc.inc("fsck_errors", len(bad))
        if repair and bad:
            batch = WriteBatch()
            for coll, oid in bad:
                rm_object_rows(self.kv, batch, "onode",
                               _objkey(coll, oid))
            self.kv.submit(batch)
            self._pc.inc("fsck_repaired", len(bad))
        return bad

    def close(self) -> None:
        with self._lock:
            self.kv.close()
            self._dev.close()

    # --------------------------------------------------------- test hook --
    def corrupt(self, coll: Coll, oid: str, offset: int = 0) -> None:
        """Flip a stored device byte under `offset` WITHOUT updating
        the blob csum (EIO injection)."""
        with self._lock:
            self._corrupt_locked(coll, oid, offset)

    def _corrupt_locked(self, coll: Coll, oid: str, offset: int) -> None:
        o = self._get(coll, oid)
        for e_off, e_len, bi, b_off in o.extents:
            if not (e_off <= offset < e_off + e_len):
                continue
            blob = o.blobs[bi]
            s = b_off + (offset - e_off) if not blob.compressed else 0
            blocks = self._blob_block_list(blob)
            dev_off = blocks[s // self.min_alloc] * self.min_alloc + \
                (s % self.min_alloc)
            cur = self._dev.pread(1, dev_off)
            self._dev.pwrite(bytes([cur[0] ^ 0xFF]), dev_off)
            return
        raise ObjectStoreError(f"corrupt: no extent at {offset}")
