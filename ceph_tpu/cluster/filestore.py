"""FileStore — durable log-structured ObjectStore backend (L5).

The persistence tier VERDICT r2 named as the biggest gap: same
Transaction contract as cluster/objectstore.py's MemStore, but nothing
lives only in RAM:

  * object DATA is appended to ``data.log`` as CRC32-framed extents
    (never overwritten in place — log-structured, the BlueStore
    deferred/extent role, src/os/bluestore/BlueStore.cc);
  * object METADATA (logical size + extent list), xattrs and omap rows
    are one WalDB write batch per transaction (cluster/wal_kv.py — the
    RocksDBStore role), committed AFTER the data log is flushed, so the
    KV batch is the atomic commit point;
  * mount() rebuilds from disk alone; ``fsck()`` verifies every
    extent's bounds and checksum (fsck-on-mount is the constructor
    default); orphan data-log space (crashes, overwrites, removes) is
    reclaimed by generation GC — ``gc_data_log`` rewrites live objects
    into a fresh log and flips extents + generation pointer in one
    atomic KV batch (auto-triggered when the log outgrows live data by
    ``gc_factor``).

Crash model (kill -9 anywhere):
  - crash before data fsync  -> txn absent, store = pre-txn state
  - crash after data, before KV commit -> txn absent, orphan extents
    (space only, invisible to reads; fsck counts them)
  - crash after KV commit    -> txn fully present
A transaction is never partially visible (single-batch commit).

Reads overlay an object's extents in log order (latest wins per byte),
verifying each extent CRC — BlueStore's csum-on-read EIO stance.
Objects whose extent chains grow past ``compact_extents`` are rewritten
as a single extent during the next apply (object-level compaction).
"""
from __future__ import annotations

import os
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import blockdev
from .kv import WriteBatch, rm_object_rows
from .objectstore import (ChecksumError, Coll, ObjectStoreError,
                          OP_OMAP_RM, OP_OMAP_SET, OP_REMOVE, OP_SETATTR,
                          OP_TOUCH, OP_TRUNCATE, OP_WRITE, OP_WRITE_FULL,
                          Transaction)
from .wal_kv import WalDB

# obj_off, vlen (valid overlay bytes), log_off, crc, plen (payload
# bytes in the log, what the crc covers; vlen <= plen after truncation)
_EXT = struct.Struct("<QIQII")


@dataclass
class _Meta:
    size: int = 0
    extents: List[Tuple[int, int, int, int, int]] = field(
        default_factory=list)

    def encode(self) -> bytes:
        out = [struct.pack("<QI", self.size, len(self.extents))]
        out += [_EXT.pack(*e) for e in self.extents]
        return b"".join(out)

    @classmethod
    def decode(cls, blob: bytes) -> "_Meta":
        size, n = struct.unpack_from("<QI", blob, 0)
        off = 12
        ext = []
        for _ in range(n):
            ext.append(_EXT.unpack_from(blob, off))
            off += _EXT.size
        return cls(size=size, extents=ext)


def _collkey(coll: Coll) -> str:
    return f"{coll[0]}.{coll[1]}"


def _objkey(coll: Coll, oid: str) -> str:
    return f"{_collkey(coll)}/{oid}"


class FileStore:
    """Durable ObjectStore on a directory (data.log + WalDB metadata)."""

    def __init__(self, path: str, *, fsync: bool = True,
                 compact_extents: int = 16, fsck_on_mount: bool = True,
                 gc_factor: int = 4, gc_min_bytes: int = 1 << 22):
        self.path = path
        self.fsync = fsync
        self.compact_extents = compact_extents
        self.gc_factor = gc_factor
        self.gc_min_bytes = gc_min_bytes
        os.makedirs(path, exist_ok=True)
        self.kv = WalDB(os.path.join(path, "kv"), fsync=fsync)
        # the live data log is generation-named; the current generation
        # lives in the KV so a GC flips extents AND generation in one
        # atomic batch (see gc_data_log)
        gen_blob = self.kv.get("meta", "data_gen")
        self._gen = int(gen_blob) if gen_blob else 0
        # migrate pre-generation stores: their extents reference the
        # bytes now living in data.0.log
        legacy = os.path.join(path, "data.log")
        if self._gen == 0 and os.path.exists(legacy) and \
                not os.path.exists(self._gen_path(0)):
            blockdev.replace(legacy, self._gen_path(0))
        self._data_path = self._gen_path(self._gen)
        # append log behind the BlockDevice barrier API (reads share
        # the same device handle — no separate read fd)
        self._data = blockdev.BlockDevice(self._data_path)
        self._lock = threading.RLock()
        self.txns_applied = 0
        self._drop_stale_generations()
        if fsck_on_mount:
            try:
                bad = self.fsck()
            except Exception:
                self.close()
                raise
            if bad:
                self.close()
                raise ObjectStoreError(
                    f"fsck on mount: bad objects {bad}")

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.path, f"data.{gen}.log")

    def _drop_stale_generations(self) -> None:
        """Crash leftovers: a half-written next-gen log whose KV flip
        never committed, or a previous-gen log already superseded."""
        for name in os.listdir(self.path):
            if name == f"data.{self._gen}.log" or \
                    not (name.startswith("data.") and
                         name.endswith(".log")):
                continue
            gen_part = name[len("data."):-len(".log")]
            if not gen_part.isdigit():
                continue               # never touch non-generation files
            blockdev.unlink(os.path.join(self.path, name))

    # ---------------------------------------------------------- data log --
    def _append_data(self, payloads: List[bytes]) -> List[Tuple[int, int]]:
        """Append payloads; returns (log_off, crc) per payload.  The
        caller holds the lock; fsync happens once per transaction."""
        spans = []
        for p in payloads:
            off = self._data.append(p)
            spans.append((off, zlib.crc32(p)))
        if self.fsync:
            self._data.fsync()
        return spans

    def _read_extent(self, log_off: int, ln: int, crc: int) -> bytes:
        buf = self._data.pread(ln, log_off)
        if len(buf) != ln or zlib.crc32(buf) != crc:
            raise ChecksumError(
                f"extent @{log_off}+{ln}: data fails checksum (EIO)")
        return buf

    # -------------------------------------------------------------- meta --
    def _meta(self, coll: Coll, oid: str) -> Optional[_Meta]:
        blob = self.kv.get("obj", _objkey(coll, oid))
        return _Meta.decode(blob) if blob is not None else None

    # ------------------------------------------------------------- write --
    def apply_transaction(self, txn: Transaction) -> None:
        """Stage all ops, then: data append + fsync, then ONE KV batch."""
        with self._lock:
            staged: Dict[Tuple[Coll, str], Optional[_Meta]] = {}
            xattrs: Dict[Tuple[Coll, str, str], Optional[bytes]] = {}
            omaps: Dict[Tuple[Coll, str, str], Optional[bytes]] = {}
            touched_colls: List[Coll] = []
            payloads: List[bytes] = []          # pending data-log appends

            def stage(coll: Coll, oid: str, create: bool) -> Optional[_Meta]:
                key = (coll, oid)
                if key not in staged:
                    cur = self._meta(coll, oid)
                    if cur is None:
                        staged[key] = _Meta() if create else None
                        if create:
                            touched_colls.append(coll)
                    else:
                        staged[key] = _Meta(cur.size, list(cur.extents))
                elif staged[key] is None and create:
                    staged[key] = _Meta()
                    touched_colls.append(coll)
                return staged[key]

            def rm_obj_rows(coll: Coll, oid: str) -> None:
                ok = _objkey(coll, oid) + "\x00"
                for prefix in ("xattr", "omap"):
                    for k, _ in self.kv.iterate(prefix, start=ok):
                        if not k.startswith(ok):
                            break
                        (xattrs if prefix == "xattr" else omaps)[
                            (coll, oid, k[len(ok):])] = None
                # rows staged EARLIER IN THIS TXN die with the object too
                for staged_rows in (xattrs, omaps):
                    for (c2, o2, key2) in list(staged_rows):
                        if (c2, o2) == (coll, oid):
                            staged_rows[(c2, o2, key2)] = None

            for op in txn.ops:
                kind = op[0]
                if kind == OP_TOUCH:
                    _, coll, oid = op
                    stage(coll, oid, create=True)
                elif kind in (OP_WRITE, OP_WRITE_FULL):
                    if kind == OP_WRITE:
                        _, coll, oid, offset, data = op
                    else:
                        _, coll, oid, data = op
                        offset = 0
                    o = stage(coll, oid, create=True)
                    if kind == OP_WRITE_FULL:
                        o.extents = []
                        o.size = len(data)
                    else:
                        o.size = max(o.size, offset + len(data))
                    if len(data):
                        # placeholder extent (log_off = -1-payload_idx)
                        # so later same-txn ops (truncate clips,
                        # write_full resets) see this write; patched to
                        # the real log offset after the append below
                        o.extents.append((offset, len(data),
                                          -1 - len(payloads), 0,
                                          len(data)))
                        payloads.append(bytes(data))
                elif kind == OP_TRUNCATE:
                    _, coll, oid, size = op
                    o = stage(coll, oid, create=False)
                    if o is None:
                        raise ObjectStoreError(f"truncate: no object {oid}")
                    if size < o.size:
                        # shrink: clip overlay lengths so a later regrow
                        # reads zeros, not resurrected bytes
                        clipped = []
                        for obj_off, vlen, log_off, crc, plen in o.extents:
                            if obj_off >= size:
                                continue
                            vlen = min(vlen, size - obj_off)
                            clipped.append((obj_off, vlen, log_off, crc,
                                            plen))
                        o.extents = clipped
                    o.size = size
                elif kind == OP_REMOVE:
                    _, coll, oid = op
                    if stage(coll, oid, create=False) is None:
                        raise ObjectStoreError(f"remove: no object {oid}")
                    staged[(coll, oid)] = None
                    rm_obj_rows(coll, oid)
                elif kind == OP_SETATTR:
                    _, coll, oid, key, value = op
                    if stage(coll, oid, create=False) is None:
                        raise ObjectStoreError(f"setattr: no object {oid}")
                    xattrs[(coll, oid, key)] = bytes(value)
                elif kind == OP_OMAP_SET:
                    _, coll, oid, key, value = op
                    if stage(coll, oid, create=False) is None:
                        raise ObjectStoreError(f"omap_set: no object {oid}")
                    omaps[(coll, oid, key)] = bytes(value)
                elif kind == OP_OMAP_RM:
                    _, coll, oid, key = op
                    if stage(coll, oid, create=False) is None:
                        raise ObjectStoreError(f"omap_rm: no object {oid}")
                    skey = (coll, oid, key)
                    if skey in omaps:
                        present = omaps[skey] is not None
                    else:
                        present = self.kv.get(
                            "omap",
                            _objkey(coll, oid) + "\x00" + key) is not None
                    if not present:
                        raise ObjectStoreError(f"omap_rm: no key {key}")
                    omaps[skey] = None
                else:
                    raise ObjectStoreError(f"unknown txn op {kind!r}")

            # append all payloads, then patch surviving placeholders
            # (placeholders dropped by remove/write_full/truncate simply
            # leave orphan log bytes, reclaimed by gc)
            spans = self._append_data(payloads) if payloads else []
            for o in staged.values():
                if o is None:
                    continue
                o.extents = [
                    (obj_off, vlen, *spans[-1 - log_off], plen)
                    if log_off < 0 else
                    (obj_off, vlen, log_off, crc, plen)
                    for (obj_off, vlen, log_off, crc, plen) in o.extents]
            batch = WriteBatch()
            for (coll, oid), o in staged.items():
                if o is None:
                    batch.rm("obj", _objkey(coll, oid))
                    continue
                if len(o.extents) > self.compact_extents:
                    data = self._materialize(o)
                    (off, crc), = self._append_data([bytes(data)])
                    o.extents = [(0, o.size, off, crc, o.size)] \
                        if o.size else []
                batch.set("obj", _objkey(coll, oid), o.encode())
            for coll in touched_colls:
                batch.set("coll", _collkey(coll), b"")
            for (coll, oid, key), v in xattrs.items():
                kk = _objkey(coll, oid) + "\x00" + key
                batch.set("xattr", kk, v) if v is not None \
                    else batch.rm("xattr", kk)
            for (coll, oid, key), v in omaps.items():
                kk = _objkey(coll, oid) + "\x00" + key
                batch.set("omap", kk, v) if v is not None \
                    else batch.rm("omap", kk)
            self.kv.submit(batch)               # atomic commit point
            self.txns_applied += 1
            self._maybe_gc()

    # ---------------------------------------------------------------- gc --
    _GC_CHECK_EVERY = 64

    def _maybe_gc(self) -> None:
        """Reclaim orphaned log space when the log outgrows the live
        data by gc_factor.  The live-bytes scan is O(objects), so it
        runs every _GC_CHECK_EVERY transactions, not per commit."""
        size = self._data.tell()
        if size < self.gc_min_bytes or \
                self.txns_applied % self._GC_CHECK_EVERY:
            return
        live = 0
        for _k, blob in self.kv.iterate("obj"):
            live += _Meta.decode(blob).size
        if size > self.gc_factor * max(live, 1):
            self.gc_data_log()

    def gc_data_log(self) -> int:
        """Rewrite every live object contiguously into a NEW generation
        data log; extents and the generation pointer flip in ONE KV
        batch, so a crash at any instruction leaves a consistent store
        (old gen + old extents, or new gen + new extents; stray files
        are dropped on mount).  Returns bytes reclaimed."""
        with self._lock:
            old_size = self._data.tell()
            new_gen = self._gen + 1
            new_path = self._gen_path(new_gen)
            batch = WriteBatch()
            newdev = blockdev.BlockDevice(new_path, fresh=True)
            for k, blob in self.kv.iterate("obj"):
                m = _Meta.decode(blob)
                data = bytes(self._materialize(m))
                off = newdev.append(data)
                m.extents = [(0, m.size, off, zlib.crc32(data),
                              m.size)] if m.size else []
                batch.set("obj", k, m.encode())
            if self.fsync:
                newdev.fsync()
            new_size = newdev.tell()
            batch.set("meta", "data_gen", str(new_gen).encode())
            self.kv.submit(batch)               # the atomic flip
            self._data.close()
            old_path = self._data_path
            self._gen = new_gen
            self._data_path = new_path
            self._data = newdev
            blockdev.unlink(old_path)
            return max(0, old_size - new_size)

    def _materialize(self, meta: _Meta) -> bytearray:
        data = bytearray(meta.size)
        for obj_off, vlen, log_off, crc, plen in meta.extents:
            buf = self._read_extent(log_off, plen, crc)
            end = min(obj_off + vlen, meta.size)
            if end > obj_off:
                data[obj_off:end] = buf[:end - obj_off]
        return data

    # -------------------------------------------------------------- read --
    def _get_meta(self, coll: Coll, oid: str) -> _Meta:
        m = self._meta(coll, oid)
        if m is None:
            raise ObjectStoreError(f"no object {oid} in {coll}")
        return m

    def exists(self, coll: Coll, oid: str) -> bool:
        return self.kv.get("obj", _objkey(coll, oid)) is not None

    def read(self, coll: Coll, oid: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        with self._lock:
            m = self._get_meta(coll, oid)
            data = self._materialize(m)
        end = m.size if length is None else offset + length
        return bytes(data[offset:end])

    def stat(self, coll: Coll, oid: str) -> Dict[str, int]:
        with self._lock:
            m = self._get_meta(coll, oid)
            return {"size": m.size,
                    "csum": zlib.crc32(bytes(self._materialize(m)))}

    def getattr(self, coll: Coll, oid: str, key: str) -> bytes:
        v = self.kv.get("xattr", _objkey(coll, oid) + "\x00" + key)
        if v is None:
            self._get_meta(coll, oid)          # object-missing error first
            raise KeyError(key)
        return v

    def omap_get(self, coll: Coll, oid: str, key: str) -> bytes:
        v = self.kv.get("omap", _objkey(coll, oid) + "\x00" + key)
        if v is None:
            self._get_meta(coll, oid)
            raise KeyError(key)
        return v

    def omap_list(self, coll: Coll, oid: str,
                  start: str = "") -> List[Tuple[str, bytes]]:
        """All omap rows of an object from ``start`` (sorted) — the
        ObjectMap::get_iterator role (PG logs live here)."""
        prefix = _objkey(coll, oid) + "\x00"
        out = []
        for k, v in self.kv.iterate("omap", start=prefix + start):
            if not k.startswith(prefix):
                break
            out.append((k[len(prefix):], v))
        return out

    def list_objects(self, coll: Coll) -> List[str]:
        ck = _collkey(coll) + "/"
        out = []
        for k, _ in self.kv.iterate("obj", start=ck):
            if not k.startswith(ck):
                break
            out.append(k[len(ck):])
        return sorted(out)

    def list_collections(self) -> List[Coll]:
        out = []
        for k, _ in self.kv.iterate("coll"):
            pool, pg = k.split(".")
            out.append((int(pool), int(pg)))
        return sorted(out)

    # ------------------------------------------------------------- fsck --
    def fsck(self, repair: bool = False) -> List[Tuple[Coll, str]]:
        """Verify every object's extents (bounds + CRC); also computes
        the orphaned data-log fraction into ``last_fsck_orphan_bytes``.
        ``repair=True`` quarantines inconsistent objects (drops their
        meta + xattr/omap rows in one batch) so recovery re-replicates
        them — same contract as BlueStore.fsck(repair=True)."""
        bad = []
        live = 0
        size = self._data.tell()
        for k, blob in self.kv.iterate("obj"):
            ck, oid = k.split("/", 1)
            pool, pg = ck.split(".")
            coll = (int(pool), int(pg))
            try:
                m = _Meta.decode(blob)
                for obj_off, vlen, log_off, crc, plen in m.extents:
                    if log_off + plen > size:
                        raise ObjectStoreError("extent past data log end")
                    self._read_extent(log_off, plen, crc)
                    live += plen
            except (ObjectStoreError, ChecksumError, struct.error):
                bad.append((coll, oid))
        self.last_fsck_orphan_bytes = max(0, size - live)
        if repair and bad:
            batch = WriteBatch()
            for coll, oid in bad:
                rm_object_rows(self.kv, batch, "obj",
                               _objkey(coll, oid))
            self.kv.submit(batch)
        return bad

    # --------------------------------------------------------- test hook --
    def corrupt(self, coll: Coll, oid: str, offset: int = 0) -> None:
        """Flip a stored byte WITHOUT updating checksums (EIO path)."""
        with self._lock:
            m = self._get_meta(coll, oid)
            if not m.extents:
                raise ObjectStoreError(f"{oid} has no stored extents")
            for obj_off, vlen, log_off, crc, plen in reversed(m.extents):
                if obj_off <= offset < obj_off + vlen:
                    pos = log_off + (offset - obj_off)
                    break
            else:
                pos = m.extents[-1][2]
            b = self._data.pread(1, pos)
            self._data.pwrite(bytes([b[0] ^ 0xFF]), pos)

    def close(self) -> None:
        with self._lock:
            if self.fsync:
                self._data.fsync()
            self._data.close()
            self.kv.close()
