"""ObjectStore — the local persistence interface (L5).

Role of src/os/ObjectStore.h + Transaction.h (the transaction-based
store contract every backend implements) with the memstore backend
(src/os/memstore/) and BlueStore's data-integrity stance (per-object
checksums verified on read, the role of BlueStore's per-block crc32c;
fsck() walks everything).

Semantics kept from the reference contract:
  * all mutations travel in a Transaction (an op list), applied
    atomically — on any op failure the whole txn rolls back;
  * objects live in collections (one per PG: the `coll_t` role);
  * touch/write/truncate/remove/setattr/omap ops;
  * reads verify the stored checksum and raise on mismatch (BlueStore
    returns EIO on csum failure rather than serving bad bytes).
"""
from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

Coll = Tuple[int, int]           # (pool, pg) — coll_t


class ObjectStoreError(IOError):
    pass


class ChecksumError(ObjectStoreError):
    pass


# transaction op codes (Transaction.h OP_* subset)
OP_TOUCH = "touch"
OP_WRITE = "write"
OP_WRITE_FULL = "write_full"
OP_TRUNCATE = "truncate"
OP_REMOVE = "remove"
OP_SETATTR = "setattr"
OP_OMAP_SET = "omap_set"
OP_OMAP_RM = "omap_rm"


class Transaction:
    """Recorded op list (ObjectStore::Transaction): build host-side,
    apply atomically."""

    def __init__(self):
        self.ops: List[Tuple] = []
        # trusted per-block csums riding ALONGSIDE the op list (a
        # side table keyed (coll, oid), so stores that know nothing
        # about checksums keep unpacking the same op tuples): the
        # wire's one-pass verify scan hands its sub-crcs here and
        # BlueStore._make_blob adopts them instead of re-scanning
        self.csums: dict = {}

    def touch(self, coll: Coll, oid: str) -> "Transaction":
        self.ops.append((OP_TOUCH, coll, oid))
        return self

    def write(self, coll: Coll, oid: str, offset: int,
              data: bytes) -> "Transaction":
        self.ops.append((OP_WRITE, coll, oid, offset, bytes(data)))
        return self

    def write_full(self, coll: Coll, oid: str,
                   data: bytes, csums=None,
                   copy: bool = True) -> "Transaction":
        """``copy=False`` keeps ``data`` as the caller's buffer view
        (zero-copy wire path — the view must stay immutable until the
        transaction applies); the default snapshot stays for callers
        handing in mutable buffers.  ``csums`` (common/crcutil.Csums
        over exactly these bytes) marks them pre-verified."""
        if copy and not isinstance(data, bytes):
            data = bytes(data)
        self.ops.append((OP_WRITE_FULL, coll, oid, data))
        if csums is not None:
            self.csums[(coll, oid)] = csums
        else:
            # a later uncsummed rewrite of the same oid must not
            # adopt an earlier write's now-stale trusted csums (the
            # store would commit valid bytes under wrong checksums
            # and EIO every future read)
            self.csums.pop((coll, oid), None)
        return self

    def truncate(self, coll: Coll, oid: str, size: int) -> "Transaction":
        self.ops.append((OP_TRUNCATE, coll, oid, size))
        return self

    def remove(self, coll: Coll, oid: str) -> "Transaction":
        self.ops.append((OP_REMOVE, coll, oid))
        return self

    def setattr(self, coll: Coll, oid: str, key: str,
                value: bytes) -> "Transaction":
        self.ops.append((OP_SETATTR, coll, oid, key, bytes(value)))
        return self

    def omap_set(self, coll: Coll, oid: str, key: str,
                 value: bytes) -> "Transaction":
        self.ops.append((OP_OMAP_SET, coll, oid, key, bytes(value)))
        return self

    def omap_rm(self, coll: Coll, oid: str, key: str) -> "Transaction":
        self.ops.append((OP_OMAP_RM, coll, oid, key))
        return self

    def __len__(self) -> int:
        return len(self.ops)


@dataclass
class _Obj:
    data: bytearray = field(default_factory=bytearray)
    csum: int = 0
    xattrs: Dict[str, bytes] = field(default_factory=dict)
    omap: Dict[str, bytes] = field(default_factory=dict)
    # verified-since-last-external-mutation flag: writes recompute the
    # csum (trivially verified); only corrupt()/fsck force a re-check,
    # so the read hot path skips an O(size) crc per shard read
    verified: bool = True

    def recsum(self) -> None:
        self.csum = zlib.crc32(bytes(self.data))
        self.verified = True

    def check(self) -> bool:
        self.verified = zlib.crc32(bytes(self.data)) == self.csum
        return self.verified


class MemStore:
    """In-memory ObjectStore backend with verified checksums."""

    def __init__(self):
        self._colls: Dict[Coll, Dict[str, _Obj]] = {}
        self.txns_applied = 0

    # ------------------------------------------------------------- write --
    def apply_transaction(self, txn: Transaction) -> None:
        """Atomic: validate + stage against copies, then commit."""
        touched: Dict[Tuple[Coll, str], Optional[_Obj]] = {}

        def stage(coll: Coll, oid: str, create: bool,
                  keep_data: bool = True) -> Optional[_Obj]:
            """Copy-on-write staging; keep_data=False skips copying the
            payload bytes (write_full replaces them anyway — the
            simulator's hottest path would otherwise pay an O(size)
            deepcopy per overwrite)."""
            key = (coll, oid)
            if key not in touched:
                cur = self._colls.get(coll, {}).get(oid)
                if cur is None:
                    touched[key] = _Obj() if create else None
                else:
                    touched[key] = _Obj(
                        data=bytearray(cur.data) if keep_data
                        else bytearray(),
                        csum=cur.csum if keep_data else 0,
                        xattrs=dict(cur.xattrs),
                        omap=dict(cur.omap),
                        verified=cur.verified if keep_data else True)
            elif touched[key] is None and create:
                touched[key] = _Obj()
            return touched[key]

        for op in txn.ops:
            kind = op[0]
            if kind == OP_TOUCH:
                _, coll, oid = op
                stage(coll, oid, create=True)
            elif kind == OP_WRITE:
                _, coll, oid, offset, data = op
                o = stage(coll, oid, create=True)
                if len(o.data) < offset + len(data):
                    o.data.extend(b"\0" * (offset + len(data) -
                                           len(o.data)))
                o.data[offset:offset + len(data)] = data
                o.recsum()
            elif kind == OP_WRITE_FULL:
                _, coll, oid, data = op
                o = stage(coll, oid, create=True, keep_data=False)
                o.data = bytearray(data)
                o.recsum()
            elif kind == OP_TRUNCATE:
                _, coll, oid, size = op
                o = stage(coll, oid, create=False)
                if o is None:
                    raise ObjectStoreError(f"truncate: no object {oid}")
                if len(o.data) < size:
                    o.data.extend(b"\0" * (size - len(o.data)))
                else:
                    del o.data[size:]
                o.recsum()
            elif kind == OP_REMOVE:
                _, coll, oid = op
                if stage(coll, oid, create=False,
                         keep_data=False) is None:
                    raise ObjectStoreError(f"remove: no object {oid}")
                touched[(coll, oid)] = None
            elif kind == OP_SETATTR:
                _, coll, oid, key, value = op
                o = stage(coll, oid, create=False)
                if o is None:
                    raise ObjectStoreError(f"setattr: no object {oid}")
                o.xattrs[key] = value
            elif kind == OP_OMAP_SET:
                _, coll, oid, key, value = op
                o = stage(coll, oid, create=False)
                if o is None:
                    raise ObjectStoreError(f"omap_set: no object {oid}")
                o.omap[key] = value
            elif kind == OP_OMAP_RM:
                _, coll, oid, key = op
                o = stage(coll, oid, create=False)
                if o is None or key not in o.omap:
                    raise ObjectStoreError(f"omap_rm: no key {key}")
                del o.omap[key]
            else:
                raise ObjectStoreError(f"unknown txn op {kind!r}")
        # commit: only after every op validated
        for (coll, oid), obj in touched.items():
            c = self._colls.setdefault(coll, {})
            if obj is None:
                c.pop(oid, None)
            else:
                c[oid] = obj
        self.txns_applied += 1

    # -------------------------------------------------------------- read --
    def _get(self, coll: Coll, oid: str) -> _Obj:
        o = self._colls.get(coll, {}).get(oid)
        if o is None:
            raise ObjectStoreError(f"no object {oid} in {coll}")
        return o

    def exists(self, coll: Coll, oid: str) -> bool:
        return oid in self._colls.get(coll, {})

    def verify(self, coll: Coll, oid: str) -> bool:
        """Presence + integrity without copying payload bytes: True iff
        the object exists and its (lazily re-checked) checksum holds."""
        o = self._colls.get(coll, {}).get(oid)
        return o is not None and (o.verified or o.check())

    def read(self, coll: Coll, oid: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        o = self._get(coll, oid)
        if not o.verified and not o.check():
            raise ChecksumError(
                f"{oid}: stored data fails checksum (EIO)")
        end = len(o.data) if length is None else offset + length
        return bytes(o.data[offset:end])

    def stat(self, coll: Coll, oid: str) -> Dict[str, int]:
        o = self._get(coll, oid)
        return {"size": len(o.data), "csum": o.csum}

    def getattr(self, coll: Coll, oid: str, key: str) -> bytes:
        return self._get(coll, oid).xattrs[key]

    def omap_get(self, coll: Coll, oid: str, key: str) -> bytes:
        return self._get(coll, oid).omap[key]

    def omap_list(self, coll: Coll, oid: str,
                  start: str = "") -> List[Tuple[str, bytes]]:
        """All omap rows of an object from ``start`` (sorted) — the
        ObjectMap::get_iterator role (PG logs live here)."""
        o = self._get(coll, oid)
        return [(k, o.omap[k]) for k in sorted(o.omap) if k >= start]

    def list_objects(self, coll: Coll) -> List[str]:
        return sorted(self._colls.get(coll, {}))

    def list_collections(self) -> List[Coll]:
        return sorted(self._colls)

    # ------------------------------------------------------------- fsck --
    def fsck(self, repair: bool = False) -> List[Tuple[Coll, str]]:
        """Verify every object's checksum (BlueStore fsck role).
        ``repair=True`` quarantines failing objects (drops them) so
        recovery re-replicates from healthy copies — the same
        contract the durable backends implement."""
        bad = []
        for coll, objs in self._colls.items():
            for oid, o in objs.items():
                if not o.check():
                    bad.append((coll, oid))
        if repair:
            for coll, oid in bad:
                self._colls.get(coll, {}).pop(oid, None)
        return bad

    # --------------------------------------------------------- test hook --
    def corrupt(self, coll: Coll, oid: str, offset: int = 0) -> None:
        """Flip a byte WITHOUT updating the checksum (EIO injection)."""
        o = self._get(coll, oid)
        if not o.data:
            o.data.extend(b"\0")
        o.data[offset] ^= 0xFF
        o.verified = False        # force the next read to re-check
