"""OSDService — the simulator OSD behind the real messenger stack.

VERDICT r2 weak #4: the native queues, mClock scheduler and dispatcher
existed but the data path never used them.  This module is the wiring:
every shard op now enters an OSD through its bounded native
MessageQueue, drains into the dmClock scheduler, and executes in QoS
order on the OSD's dispatch thread — the reference shape
``OSD::ms_fast_dispatch -> enqueue_op -> sharded OpScheduler ->
dequeue_op`` (src/osd/OSD.cc:7114,9745,9807), with client IO and
recovery pushes in different QoS classes (mClockScheduler,
src/osd/scheduler/mClockScheduler.cc).

Callers get synchronous helpers (put/get/delete) that block on the op's
completion event, so ClusterSim semantics — and the chaos test — are
unchanged while every byte flows queue -> scheduler -> dispatch.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common import faults
from ..common import tracer as _trace
from ..common.lockdep import LockdepLock
from ..common.op_tracker import tracker as _op_tracker
from ..common.perf_counters import perf as _perf
from ..msg import encoding

faults.declare("msg.drop_op",
               "drop an op at the in-process messenger boundary "
               "(queue admission raises IOError, no dispatch) — the "
               "sim tier's frame-drop axis: sub-writes degrade and "
               "recovery must repair, reads fail over")
from ..msg.dispatcher import BatchingDispatcher
from ..msg.queue import Envelope, MessageQueue, QueueClosed, QueueFull
from ..msg.scheduler import CLASS_CLIENT, CLASS_RECOVERY, MClockScheduler
from .pg_heat import PGHeatTracker


def _heat_half_life() -> float:
    try:
        from ..common.options import config
        return float(config().get("pg_heat_half_life"))
    except Exception:
        return 60.0

MSG_OSD_OP = 0x10

ShardKey = Tuple[int, int, str, int]


class OSDService:
    """Per-OSD op front end: queue -> mClock -> execute."""

    def __init__(self, osd, *, capacity_items: int = 4096,
                 capacity_bytes: int = 1 << 28):
        self.osd = osd
        self.in_q = MessageQueue(capacity_items=capacity_items,
                                 capacity_bytes=capacity_bytes)
        self.sched = MClockScheduler()
        self._ids = itertools.count(1)
        self._lock = LockdepLock("osd.service", recursive=False)
        self._events: Dict[int, threading.Event] = {}
        self._results: Dict[int, Any] = {}
        # device-array side table: the control frame rides the native
        # queue, the HBM buffer handle rides here (the zero-copy "data
        # segment" of a real messenger frame — device payloads never
        # serialize through the wire path in-process)
        self._op_objs: Dict[int, Any] = {}
        # dispatch-latency histogram + slow-op test hook: one shared
        # "osd.service" group (per-OSD families would explode the
        # exporter); per-OSD attribution rides the tracked-op events
        self._pc = _perf("osd.service")
        # test hook: seconds to sleep inside _execute (models a stalled
        # device dispatch; drives the SLOW_OPS acceptance path)
        self.inject_execute_delay = 0.0
        # per-PG client-io heat (pool HitSet role).  Manual clock: the
        # heartbeat advances it to its tick count, so decay is
        # seed-deterministic on the sim tick clock
        self.heat = PGHeatTracker(half_life=_heat_half_life())
        self.dispatcher = BatchingDispatcher(
            self.in_q, self._handle, linger=0.0,
            name=f"osd.{osd.id}").start()

    # ------------------------------------------------------- server side --
    def _handle(self, batch: List[Envelope]) -> None:
        # fast dispatch: envelopes land in the QoS scheduler first.
        # batch occupancy is THE feed-the-MXU knob, so it lands on every
        # tracked op in the batch (dispatcher thread -> mark by id)
        trk = _op_tracker()
        depth = self.in_q.stats()["depth"]
        for env in batch:
            op = encoding.loads(env.payload)
            with self._lock:
                obj = self._op_objs.pop(env.id, None)
            if obj is not None:
                op["_obj"] = obj
            trk.mark(op.get("track_id"), "reached_osd",
                     osd=self.osd.id, batch_occupancy=len(batch),
                     queue_depth=depth)
            self.sched.enqueue((env.id, op),
                               klass=op.get("klass", CLASS_CLIENT))
        # dequeue_op in scheduler order
        while True:
            item = self.sched.dequeue()
            if item is None:
                break
            _klass, (op_id, op) = item
            try:
                result = self._execute(op)
            except Exception as e:         # surfaced to the waiter
                result = e
            with self._lock:
                ev = self._events.get(op_id)
                if ev is not None:         # waiter gone (timeout): drop
                    self._results[op_id] = result
            if ev is not None:
                ev.set()

    def _execute(self, op: Dict[str, Any]):
        _op_tracker().mark(op.get("track_id"), "dispatched_device",
                           osd=self.osd.id, kind=op["kind"])
        t0 = time.perf_counter()
        try:
            if self.inject_execute_delay > 0:
                time.sleep(self.inject_execute_delay)
            # daemon-side dispatch stage span, linked under the
            # submitting op's trace context (carried on the op dict —
            # the in-process half of trace propagation); the nested
            # device.dispatch child covers the store/device access.
            # service = the EXECUTING entity (this OSD), not the
            # process-wide default "client" the sim tier used to stamp
            with _trace.linked_span(
                    "osd.dispatch", op.get("tctx"),
                    service=f"osd.{self.osd.id}",
                    osd=self.osd.id, kind=op["kind"]):
                with _trace.child_span("device.dispatch",
                                       service=f"osd.{self.osd.id}",
                                       osd=self.osd.id):
                    out = self._execute_inner(op)
            self._record_heat(op, out)
            return out
        finally:
            # device-dispatch latency distribution (the encode/store
            # stage averages hide; acceptance histogram family)
            self._pc.hinc("dispatch_s", time.perf_counter() - t0)

    def _record_heat(self, op: Dict[str, Any], result: Any) -> None:
        """Count a completed CLIENT op against its PG's heat ledger —
        recovery traffic is placement churn, not client load, so it
        stays out (matching what ``osd.io`` counts on the daemon
        tier)."""
        if op.get("klass", CLASS_CLIENT) != CLASS_CLIENT:
            return
        key = op.get("key")
        if key is None:                    # bulk *_many ride recovery
            return
        kind = op["kind"]
        pool, pg = int(key[0]), int(key[1])
        if kind in ("put", "put_dev"):
            data = op.get("data")
            nbytes = (len(data) if data is not None
                      else int(getattr(op.get("_obj"), "nbytes", 0)
                               or 0))
            self.heat.record(pool, pg, "wr", nbytes=nbytes)
        elif kind in ("get", "get_dev"):
            self.heat.record(pool, pg, "rd",
                             nbytes=int(getattr(result, "nbytes", 0)
                                        or 0))
        elif kind == "delete":
            self.heat.record(pool, pg, "wr")

    def _execute_inner(self, op: Dict[str, Any]):
        kind = op["kind"]
        if kind == "get_dev_many":
            # bulk device read: ONE queue->scheduler->dispatch round
            # for a whole recovery gather (None per absent/EIO key —
            # the caller's per-key failover decides what that means)
            return [self.osd.get_device(tuple(k))
                    for k in op["keys"]]
        if kind == "put_dev_many":
            # bulk device push (the recovery-push scatter half): the
            # HBM refs ride the _obj side table as one list; optional
            # per-key durable bytes ride ``datas`` (eager mode)
            arrs = op["_obj"]
            datas = op.get("datas") or [None] * len(op["keys"])
            for k, a, d in zip(op["keys"], arrs, datas):
                self.osd.put_device(tuple(k), a, d)
            return len(op["keys"])
        key: ShardKey = tuple(op["key"])   # typed encoding lists it
        if kind == "put":
            self.osd.put(key, np.frombuffer(op["data"], dtype=np.uint8))
            return True
        if kind == "get":
            if op.get("ranges"):
                # sub-shard ranged read (Clay repair helpers): only
                # the requested byte ranges cross the messenger
                return self.osd.get_ranges(key, op["ranges"])
            return self.osd.get(key)
        if kind == "put_dev":
            self.osd.put_device(key, op["_obj"], op.get("data"))
            return True
        if kind == "get_dev":
            return self.osd.get_device(key)
        if kind == "delete":
            self.osd.delete(key)
            return True
        raise ValueError(f"unknown osd op kind {kind!r}")

    # ------------------------------------------------------- client side --
    def call_async(self, op: Dict[str, Any], timeout: float = 30.0,
                   obj: Any = None) -> Tuple[int, threading.Event]:
        """Enqueue an op without waiting (the MOSDECSubOp fan-out
        shape: a primary keeps k+m sub-ops in flight concurrently,
        src/osd/ECBackend.cc:1976).  Pair with wait_async()."""
        if faults.fire("msg.drop_op", osd=self.osd.id,
                       kind=op.get("kind")) is not None:
            # fires on the SUBMITTING thread (deterministic order for
            # seeded thrash runs), before any state is registered
            raise IOError(f"osd.{self.osd.id}: op dropped "
                          f"(fault injected)")
        src = op.get("src", "client")
        if faults.partitioned(src, f"osd.{self.osd.id}"):
            # in-process netsplit: the op never reaches this OSD's
            # queue.  Sim-tier traffic all originates at the client/
            # primary entity "client" (recovery pushes included — the
            # sim's orchestrator IS the primary), so a partition that
            # cuts "client" from a group of OSDs severs their whole
            # data path while the daemons stay alive
            raise IOError(f"osd.{self.osd.id}: unreachable from "
                          f"{src} (netsplit)")
        op_id = next(self._ids)
        ev = threading.Event()
        with self._lock:
            self._events[op_id] = ev
            if obj is not None:
                self._op_objs[op_id] = obj
        top = _op_tracker().current()
        if top is not None:
            # ride the tracked-op id on the control frame so the
            # dispatcher thread can mark events on the same record
            op = dict(op, track_id=top.op_id)
            top.mark_event("queued", osd=self.osd.id,
                           queue_depth=self.in_q.stats()["depth"])
        # trace propagation (in-process dispatch half): the active
        # span's (trace_id, span_id) rides the op dict so the
        # dispatcher thread's stage spans link under it; the queue
        # admission itself is the "osd.queue" stage
        op = _trace.stamp(dict(op)) if _trace.enabled() else op
        with _trace.child_span("osd.queue", osd=self.osd.id):
            payload = encoding.dumps(op)
            try:
                self.in_q.push(Envelope(MSG_OSD_OP, op_id, -1,
                                        payload), timeout=timeout)
            except (QueueFull, QueueClosed):
                with self._lock:
                    self._events.pop(op_id, None)
                    self._op_objs.pop(op_id, None)
                raise IOError(f"osd.{self.osd.id}: op queue "
                              f"unavailable")
        return op_id, ev

    def wait_async(self, op_id: int, ev: threading.Event,
                   timeout: float = 30.0):
        if not ev.wait(timeout):
            with self._lock:
                self._events.pop(op_id, None)
                self._results.pop(op_id, None)
                self._op_objs.pop(op_id, None)
            raise IOError(f"osd.{self.osd.id}: op {op_id} timed out")
        with self._lock:
            self._events.pop(op_id, None)
            result = self._results.pop(op_id)
        if isinstance(result, Exception):
            raise result
        return result

    def _call(self, op: Dict[str, Any], timeout: float = 30.0,
              obj: Any = None):
        op_id, ev = self.call_async(op, timeout, obj)
        return self.wait_async(op_id, ev, timeout)

    def put(self, key: ShardKey, data: np.ndarray,
            klass: str = CLASS_CLIENT) -> None:
        self._call({"kind": "put", "key": key, "klass": klass,
                    "data": np.asarray(data, dtype=np.uint8).tobytes()})

    def get(self, key: ShardKey, klass: str = CLASS_CLIENT,
            ranges=None) -> Optional[np.ndarray]:
        op = {"kind": "get", "key": key, "klass": klass}
        if ranges:
            op["ranges"] = [list(r) for r in ranges]
        return self._call(op)

    def delete(self, key: ShardKey, klass: str = CLASS_CLIENT) -> None:
        self._call({"kind": "delete", "key": key, "klass": klass})

    def put_recovery(self, key: ShardKey, data: np.ndarray) -> None:
        """Recovery pushes ride the background-recovery QoS class."""
        self.put(key, data, klass=CLASS_RECOVERY)

    # --------------------------------------------- device-staged shards --
    def put_device(self, key: ShardKey, arr,
                   data_bytes: Optional[bytes] = None,
                   klass: str = CLASS_CLIENT) -> None:
        """Stage a device shard array on the OSD.  ``data_bytes`` is the
        eager durable write-through (same bytes); None defers flushing
        (staged/WAL mode)."""
        self._call({"kind": "put_dev", "key": key, "klass": klass,
                    "data": data_bytes}, obj=arr)

    def get_device(self, key: ShardKey, klass: str = CLASS_CLIENT):
        """Fetch a shard as a device array (HBM-resident if staged)."""
        return self._call({"kind": "get_dev", "key": key,
                           "klass": klass})

    def put_device_recovery(self, key: ShardKey, arr,
                            data_bytes: Optional[bytes] = None) -> None:
        self.put_device(key, arr, data_bytes, klass=CLASS_RECOVERY)

    # --------------------------------------------- bulk recovery sub-ops --
    def get_device_many_async(self, keys: List[ShardKey],
                              klass: str = CLASS_RECOVERY
                              ) -> Tuple[int, threading.Event]:
        """Submit ONE bulk device read for ``keys`` (pair with
        wait_async; result is a per-key list, None per miss).  The
        recovery sweep's gather half: submit-all-then-gather across
        OSDs instead of one blocking round trip per shard."""
        return self.call_async({"kind": "get_dev_many",
                                "keys": [list(k) for k in keys],
                                "klass": klass})

    def put_device_many_async(self, items: List[Tuple[ShardKey, Any,
                                                      Optional[bytes]]],
                              klass: str = CLASS_RECOVERY
                              ) -> Tuple[int, threading.Event]:
        """Submit ONE bulk device push of (key, ref, durable_bytes)
        triples — the recovery-push scatter half."""
        return self.call_async(
            {"kind": "put_dev_many",
             "keys": [list(k) for k, _, _ in items],
             "datas": [d for _, _, d in items],
             "klass": klass},
            obj=[a for _, a, _ in items])

    def stats(self) -> Dict[str, int]:
        return self.in_q.stats()

    def stop(self) -> None:
        self.dispatcher.stop()
        self.in_q.close()
