"""BlockDevice — the store tier's one door to persistent media.

Role of the reference's block-device abstraction under BlueStore
(src/os/bluestore/KernelDevice.cc: aio writes, flush() barriers) plus
the crash-state *recorder* the CrashDev harness (cluster/crashdev.py)
needs: every byte the storage tier persists — BlueStore data pwrites,
WalDB WAL appends, KV snapshots, MANIFEST renames, FileStore log
appends — crosses this API, so the recorder sees the complete
(offset, bytes, barrier-epoch) stream and can enumerate what a power
cut at any instruction could have left on media.

Model (the ALICE/CrashMonkey block-order model, restricted to what
these stores actually rely on):

  * ``pwrite``/``append`` are asynchronous: until the file's next
    ``fsync`` they are *pending* — a crash may persist each of them
    fully, partially (torn), or not at all, in any order;
  * ``fsync`` is a **barrier**: everything written to that file
    before it is durable once it returns;
  * ``replace`` (atomic rename) and ``unlink``/``truncate`` are
    treated as ordering points for the file(s) they touch — the
    stores only rename files whose bytes were fsynced first (the
    write-tmp/fsync/rename idiom), so modelling metadata ops as
    ordered is sound for this tree and keeps generated images states
    a real ext4-ordered-mode cut could produce.

Faultpoints (declared in common/faults.py, armable over every
daemon's ``fault_injection`` asok grammar):

  * ``device.torn_write``  — a pwrite persists only a prefix and the
    process browns out mid-write (params: ``keep`` bytes, ``exit``);
  * ``device.lost_write``  — the device acks a write that never
    reaches media (firmware write loss); the process continues, the
    per-block checksums / fsck are the detectors;
  * ``device.power_loss``  — the process dies AT a barrier, before
    the fsync completes (params: ``exit``).

A dying fire drops a ``POWER_LOSS`` marker next to the device file so
the next daemon boot knows to run a full ``fsck(repair=True)`` and
report quarantined objects up the heartbeat (the STORE_DAMAGED
health-check pipeline).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..common import faults
from ..common import tracer as _trace

POWER_LOSS_MARKER = "POWER_LOSS"

# record ops (op, relpath, a, b):
#   ("write",   rel, offset, bytes)    data landing on the file
#   ("trunc",   rel, size,   None)     ftruncate (also file creation)
#   ("barrier", rel, None,   None)     fsync — seals prior writes
#   ("rename",  rel_src, rel_dst, None)
#   ("unlink",  rel, None,   None)
#   ("mark",    label, a,    None)     harness annotation (acked txn)
OP_WRITE = "write"
OP_TRUNC = "trunc"
OP_BARRIER = "barrier"
OP_RENAME = "rename"
OP_UNLINK = "unlink"
OP_MARK = "mark"


class PowerLoss(IOError):
    """An injected power cut surfaced in-process (``exit=False``
    arming; daemons arm with ``exit=True`` and simply die)."""


class Recorder:
    """Ordered write-stream recorder for one store tree.  Paths are
    stored RELATIVE to ``root`` so crash images materialize into any
    directory.  Thread-safe: stores submit from many threads."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._lock = threading.Lock()
        self.log: List[Tuple[str, str, Any, Any]] = []

    def _rel(self, path: str) -> str:
        return os.path.relpath(os.path.abspath(path), self.root)

    def record(self, op: str, path: str, a: Any = None,
               b: Any = None) -> None:
        with self._lock:
            self.log.append((op, self._rel(path), a, b))

    def record_rename(self, src: str, dst: str) -> None:
        with self._lock:
            self.log.append((OP_RENAME, self._rel(src),
                             self._rel(dst), None))

    def mark(self, label: Any, extra: Any = None) -> None:
        """Harness annotation: 'the transaction identified by
        ``label`` was ACKED here' — the crash-state checker's oracle
        boundary."""
        with self._lock:
            self.log.append((OP_MARK, label, extra, None))

    def snapshot(self) -> List[Tuple[str, str, Any, Any]]:
        with self._lock:
            return list(self.log)

    def __len__(self) -> int:
        with self._lock:
            return len(self.log)


_REG_LOCK = threading.Lock()
_RECORDERS: List[Recorder] = []


def attach(root: str) -> Recorder:
    """Start recording every BlockDevice op under ``root`` (a store
    directory).  Returns the recorder; pair with detach()."""
    r = Recorder(root)
    with _REG_LOCK:
        _RECORDERS.append(r)
    return r


def detach(rec: Recorder) -> None:
    with _REG_LOCK:
        try:
            _RECORDERS.remove(rec)
        except ValueError:
            pass


def recorder_for(path: str) -> Optional[Recorder]:
    p = os.path.abspath(path)
    with _REG_LOCK:
        for r in reversed(_RECORDERS):
            if p == r.root or p.startswith(r.root + os.sep):
                return r
    return None


def _wants_exit(params: Dict[str, Any]) -> bool:
    v = params.get("exit", True)
    return str(v).lower() not in ("false", "0", "no")


class BlockDevice:
    """One persistent file behind the barrier API.

    Covers both shapes the stores use: random-access block files
    (BlueStore's ``block``: ``pwrite``/``pread`` at offsets) and
    append-only logs (WAL / data logs: ``append`` returns the offset
    written).  ``fresh=True`` truncates on open (a restarted WAL);
    ``size=`` pins a fixed-size device (recorded so crash images
    recreate the geometry)."""

    def __init__(self, path: str, *, fresh: bool = False,
                 size: Optional[int] = None):
        self.path = path
        self.rec = recorder_for(path)
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        self._closed = False
        if fresh:
            os.ftruncate(self._fd, 0)
            self._size = 0
            if self.rec is not None:
                self.rec.record(OP_TRUNC, path, 0)
        else:
            self._size = os.fstat(self._fd).st_size
        if size is not None and self._size != size:
            os.ftruncate(self._fd, size)
            self._size = size
            if self.rec is not None:
                self.rec.record(OP_TRUNC, path, size)

    # ------------------------------------------------------------ write --
    def pwrite(self, data: bytes, offset: int) -> int:
        # no bytes() snapshot: os.pwrite takes any buffer, and the
        # zero-copy wire path hands views straight off the receive
        # buffer — materializing here re-copied EVERY stored byte.
        # The recorder path (crash harness) still snapshots its own
        # stable copy below.
        p = faults.fire("device.torn_write", path=self.path)
        if p is not None:
            keep = int(p.get("keep", max(1, len(data) // 2)))
            os.pwrite(self._fd, data[:keep], offset)
            self._power_cut(p, f"torn write ({keep}/{len(data)} "
                               f"bytes) at {offset}")
        if faults.fire("device.lost_write", path=self.path) is not None:
            # firmware-lost write: the OS acks it, the media never
            # sees it.  The logical size still advances (subsequent
            # appends land past it); the hole reads back as zeros and
            # the checksum tier is the detector.
            self._size = max(self._size, offset + len(data))
            return len(data)
        os.pwrite(self._fd, data, offset)
        self._size = max(self._size, offset + len(data))
        if self.rec is not None:
            # the recorder replays writes long after the caller's
            # buffer view is reused: snapshot (harness-only cost)
            self.rec.record(OP_WRITE, self.path, offset, bytes(data))
        return len(data)

    def append(self, data: bytes) -> int:
        off = self._size
        self.pwrite(data, off)
        return off

    def truncate(self, n: int) -> None:
        os.ftruncate(self._fd, n)
        self._size = n
        if self.rec is not None:
            self.rec.record(OP_TRUNC, self.path, n)

    def fsync(self) -> None:
        p = faults.fire("device.power_loss", path=self.path)
        if p is not None:
            self._power_cut(p, "power loss at barrier")
        # store-barrier trace stage: null unless the op above this
        # barrier carries an active span (the ClusterTelemetry
        # queue/dispatch/store-barrier/device stage set)
        with _trace.child_span("store.barrier"):
            os.fsync(self._fd)
        if self.rec is not None:
            self.rec.record(OP_BARRIER, self.path)

    def flush(self) -> None:
        """Compat no-op (writes are unbuffered; fsync is the barrier)."""

    # ------------------------------------------------------------- read --
    def pread(self, n: int, offset: int) -> bytes:
        return os.pread(self._fd, n, offset)

    def tell(self) -> int:
        """Logical size / next append offset."""
        return self._size

    # ---------------------------------------------------------- lifetime --
    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            os.close(self._fd)
        except OSError:
            pass

    def _power_cut(self, params: Dict[str, Any], why: str) -> None:
        # marker first: the next boot of this store must know a power
        # cut happened and run fsck(repair) (best-effort — a marker
        # that fails to land just skips the automatic fsck)
        try:
            mfd = os.open(
                os.path.join(os.path.dirname(self.path) or ".",
                             POWER_LOSS_MARKER),
                os.O_WRONLY | os.O_CREAT, 0o644)
            os.close(mfd)
        except OSError:
            pass
        if _wants_exit(params):
            os._exit(9)
        raise PowerLoss(f"fault injected: {why} on {self.path}")


# ------------------------------------------------------- metadata ops ---

def replace(src: str, dst: str) -> None:
    """Atomic rename through the recorder (the snapshot/MANIFEST
    pointer-flip idiom)."""
    rec = recorder_for(dst)
    os.replace(src, dst)
    if rec is not None:
        rec.record_rename(src, dst)


def unlink(path: str, missing_ok: bool = True) -> None:
    rec = recorder_for(path)
    try:
        os.unlink(path)
    except FileNotFoundError:
        if not missing_ok:
            raise
        return
    if rec is not None:
        rec.record(OP_UNLINK, path)


def power_loss_markers(store_root: str) -> List[str]:
    """POWER_LOSS markers under a store directory (root + immediate
    subdirs — the block file and the KV live one level apart)."""
    out = []
    root = os.path.abspath(store_root)
    cand = [root]
    try:
        cand += [os.path.join(root, d) for d in os.listdir(root)
                 if os.path.isdir(os.path.join(root, d))]
    except OSError:
        return []
    for d in cand:
        m = os.path.join(d, POWER_LOSS_MARKER)
        if os.path.exists(m):
            out.append(m)
    return out


def clear_power_loss_markers(store_root: str) -> int:
    n = 0
    for m in power_loss_markers(store_root):
        try:
            os.unlink(m)
            n += 1
        except OSError:
            pass
    return n
