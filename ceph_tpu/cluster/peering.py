"""PG peering state machine.

Role of the reference's PeeringState (src/osd/PeeringState.h:561 — a
boost::statechart driving every PG through
Reset → Started/Primary/Peering{GetInfo, GetLog, GetMissing} →
Activating → Recovering/Backfilling → Clean after EVERY map change,
re-establishing consensus on the PG's authoritative history before
serving I/O).

Compact event-driven re-creation over the simulator's state: the
machine consumes AdvMap (a new epoch touched this PG), queries member
OSDs' last_complete (the GetInfo/GetLog exchange against pg_logs),
computes missing members (GetMissing), activates, recovers via the
log-based delta path, and settles Clean.  Transitions are explicit and
recorded so tests can assert the exact path taken.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..placement.crush_map import ITEM_NONE
from .pglog import ZERO

# states (subset of PeeringState.h:653ff)
RESET = "Reset"
GET_INFO = "Peering/GetInfo"
GET_LOG = "Peering/GetLog"
GET_MISSING = "Peering/GetMissing"
ACTIVATING = "Activating"
RECOVERING = "Recovering"
BACKFILLING = "Backfilling"
CLEAN = "Clean"
INCOMPLETE = "Incomplete"


@dataclass
class PeeringResult:
    state: str
    history: List[str]
    up: List[int]
    missing_osds: List[int]
    recovered: Dict[str, int] = field(default_factory=dict)


class PGStateMachine:
    """One PG's peering driver."""

    def __init__(self, sim, pool_id: int, pg: int):
        self.sim = sim
        self.pool_id = pool_id
        self.pg = pg
        self.state = RESET
        self.history: List[str] = [RESET]
        self.epoch = sim.osdmap.epoch
        self.up: List[int] = []
        self.missing_osds: List[int] = []

    def _to(self, state: str) -> None:
        self.state = state
        self.history.append(state)

    # -------------------------------------------------------------- events --
    def on_adv_map(self) -> None:
        """AdvMap: the map moved — restart interval (PeeringState.h:441)."""
        self.epoch = self.sim.osdmap.epoch
        self.state = RESET
        self.history.append(RESET)

    def peer(self) -> PeeringResult:
        """Run the full peering sequence to quiescence."""
        sim = self.sim
        pool = sim.osdmap.pools[self.pool_id]
        log = sim.pg_logs.get((self.pool_id, self.pg))

        # GetInfo: who is in the interval, what do they have
        self._to(GET_INFO)
        self.up = sim.pg_up(pool, self.pg)
        live = [o for o in self.up
                if o != ITEM_NONE and sim.osds[o].alive]
        if not live:
            self._to(INCOMPLETE)
            return self._result()

        # GetLog: the authoritative log (sim.pg_logs is the primary's)
        self._to(GET_LOG)
        head = log.head if log else ZERO

        # GetMissing: members whose last_complete lags the log head
        self._to(GET_MISSING)
        self.missing_osds = [
            o for o in live
            if sim.osds[o].last_complete.get((self.pool_id, self.pg),
                                             ZERO) < head]
        holes = [o for o in self.up if o == ITEM_NONE or
                 not sim.osds[o].alive]

        self._to(ACTIVATING)
        recovered: Dict[str, int] = {}
        if self.missing_osds or holes:
            needs_backfill = any(
                log is not None and not log.covers(
                    sim.osds[o].last_complete.get(
                        (self.pool_id, self.pg), ZERO))
                for o in self.missing_osds)
            self._to(BACKFILLING if needs_backfill else RECOVERING)
            recovered = sim.recover_delta(self.pool_id)
        self._to(CLEAN)
        return self._result(recovered)

    def _result(self, recovered: Optional[Dict[str, int]] = None
                ) -> PeeringResult:
        return PeeringResult(
            state=self.state, history=list(self.history),
            up=list(self.up), missing_osds=list(self.missing_osds),
            recovered=recovered or {})


class PeeringCoordinator:
    """All PGs of a pool: re-peer everything after a map change (the
    role OSD::consume_map plays fanning AdvMap to its PGs)."""

    def __init__(self, sim, pool_id: int):
        self.sim = sim
        self.pool_id = pool_id
        pool = sim.osdmap.pools[pool_id]
        self.machines = {pg: PGStateMachine(sim, pool_id, pg)
                         for pg in range(pool.pg_num)}

    def handle_map_change(self) -> Dict[int, PeeringResult]:
        out: Dict[int, PeeringResult] = {}
        for pg, m in self.machines.items():
            m.on_adv_map()
            out[pg] = m.peer()
        return out

    def states(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for m in self.machines.values():
            counts[m.state] = counts.get(m.state, 0) + 1
        return counts
