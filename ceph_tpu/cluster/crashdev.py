"""CrashDev — power-loss crash-state enumeration for the storage tier.

The stores assert their durability contract in comments ("WAL fsynced
before KV commit", "deferred replay is idempotent"); this module turns
those comments into a *proof harness*.  Every byte BlueStore / WalDB /
FileStore persist crosses the BlockDevice barrier API
(cluster/blockdev.py), so a Recorder attached to a store directory
captures the complete ordered write stream with ``fsync`` barriers.
From that stream the generator materializes simulated power-loss
images:

  * **clean prefix cuts** — the crash happens exactly at an op
    boundary; everything before it landed, nothing after;
  * **torn tails** — the last in-flight write persists only a seeded
    prefix of its bytes;
  * **dropped writes** — a seeded subset of the *pending* set (writes
    after their file's last barrier) never reaches media;
  * **reordering within a barrier epoch** — pending writes land in a
    seeded permutation; writes sealed by a barrier are never reordered
    across it (fsync means what it says).

Each image is reopened and the contract asserted
(:func:`check_bluestore_image`):

  1. the store mounts and ``fsck()`` is clean,
  2. every transaction ACKED before the crash point is fully
     readable (bytes match the oracle),
  3. the at-most-one unacked in-flight transaction is either absent
     or complete — never a Frankenstein mix of old and new,
  4. reopening is convergent: a SECOND crash during the mount's
     deferred/WAL replay, reopened again, still satisfies 1–3
     (:func:`double_crash_check`).

The harness is falsifiable: break the ordering (ack a transaction
whose WAL record was never fsynced — ``kv_fsync=False``) and the
dropped-tail images lose acked writes, which the checker reports
(tests prove the harness catches exactly that bug class).

``tear_wal_tail`` is the process-tier sibling used by
``ceph thrash --powercycle``: after a SIGKILL it mutates the dead
OSD's store the way a power cut could have — tearing bytes off the
WAL's trailing *partial* record (a fragment that never completed its
commit, so no acked write may depend on it).
"""
from __future__ import annotations

import os
import random
import shutil
import struct
import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import blockdev
from .blockdev import (OP_BARRIER, OP_MARK, OP_RENAME, OP_TRUNC,
                       OP_UNLINK, OP_WRITE)

Rec = Tuple[str, str, Any, Any]


# ----------------------------------------------------------- analysis ---

def crash_points(log: List[Rec]) -> List[int]:
    """Prefix lengths ending right after each barrier — the 'clean
    cut at every barrier' image set."""
    return [i + 1 for i, r in enumerate(log) if r[0] == OP_BARRIER]


def pending_writes(log: List[Rec], upto: int) -> List[int]:
    """Indices of write records in ``log[:upto]`` that are NOT sealed:
    after their file's last barrier (or metadata ordering point).
    These are the writes a power cut at ``upto`` may tear, drop or
    reorder; everything else is durable."""
    sealed_at: Dict[str, int] = {}
    for i, (op, path, a, _b) in enumerate(log[:upto]):
        if op in (OP_BARRIER, OP_TRUNC, OP_UNLINK):
            sealed_at[path] = i
        elif op == OP_RENAME:
            sealed_at[path] = i          # src
            sealed_at[a] = i             # dst
    return [i for i, (op, path, _a, _b) in enumerate(log[:upto])
            if op == OP_WRITE and i > sealed_at.get(path, -1)]


def marks_before(log: List[Rec], upto: int) -> List[Any]:
    """Labels of transactions ACKED before the crash point."""
    return [r[1] for r in log[:upto] if r[0] == OP_MARK]


# ------------------------------------------------------ materialization ---

def materialize(log: List[Rec], upto: int, outdir: str, *,
                drop: Iterable[int] = (),
                tear: Optional[Tuple[int, int]] = None,
                order: Optional[List[int]] = None) -> None:
    """Replay ``log[:upto]`` into ``outdir`` (which may already hold a
    base image — the double-crash path replays a mount's writes onto a
    copy of the crashed image).

    ``drop``: pending-write indices that never reach media.
    ``tear``: ``(index, keep_bytes)`` — that pending write persists
    only its first ``keep_bytes``.
    ``order``: permutation of the pending-write indices (defaults to
    log order).  Only PENDING writes (see :func:`pending_writes`) may
    be mutated — sealed writes always land verbatim, in order.
    """
    os.makedirs(outdir, exist_ok=True)
    pend = set(pending_writes(log, upto))
    dropset = set(drop) & pend
    fds: Dict[str, int] = {}

    def fd(rel: str) -> int:
        f = fds.get(rel)
        if f is None:
            p = os.path.join(outdir, rel)
            d = os.path.dirname(p)
            if d:
                os.makedirs(d, exist_ok=True)
            fds[rel] = f = os.open(p, os.O_RDWR | os.O_CREAT, 0o644)
        return f

    def drop_fd(rel: str) -> None:
        f = fds.pop(rel, None)
        if f is not None:
            os.close(f)

    try:
        for i, (op, path, a, b) in enumerate(log[:upto]):
            if op == OP_WRITE:
                if i in pend:
                    continue             # pending tail: applied below
                os.pwrite(fd(path), b, a)
            elif op == OP_TRUNC:
                os.ftruncate(fd(path), a)
            elif op == OP_RENAME:
                drop_fd(path)
                drop_fd(a)
                src = os.path.join(outdir, path)
                dst = os.path.join(outdir, a)
                d = os.path.dirname(dst)
                if d:
                    os.makedirs(d, exist_ok=True)
                if os.path.exists(src):
                    os.replace(src, dst)
            elif op == OP_UNLINK:
                drop_fd(path)
                try:
                    os.unlink(os.path.join(outdir, path))
                except FileNotFoundError:
                    pass
            # OP_BARRIER / OP_MARK: no file effect
        # the pending tail, in the chosen order, with drops and tears
        # (deferring it is order-equivalent: by definition no later
        # ordering op touches these files inside the prefix)
        seq = [i for i in (order if order is not None
                           else sorted(pend)) if i in pend]
        for i in sorted(pend):
            if i not in seq:
                seq.append(i)            # a permutation must cover all
        for i in seq:
            if i in dropset:
                continue
            op, path, off, data = log[i]
            if tear is not None and i == tear[0]:
                data = data[:tear[1]]
            os.pwrite(fd(path), data, off)
    finally:
        for f in fds.values():
            os.close(f)


def seeded_images(log: List[Rec], seed: int, n_images: int,
                  out_base: str, prefix: str = "img"
                  ) -> Iterable[Dict[str, Any]]:
    """Seeded torn/dropped/reordered crash images: every draw comes
    from one ``random.Random(seed)``, so the image set is
    bit-reproducible per seed."""
    rng = random.Random(seed)
    for j in range(n_images):
        upto = rng.randrange(1, len(log) + 1)
        pend = pending_writes(log, upto)
        drop = [i for i in pend if rng.random() < 0.35]
        tear = None
        tearable = [i for i in pend if i not in drop
                    and len(log[i][3]) > 1]
        if tearable and rng.random() < 0.5:
            t = max(tearable)            # the in-flight last write
            tear = (t, rng.randrange(1, len(log[t][3])))
        order = list(pend)
        rng.shuffle(order)
        outdir = os.path.join(out_base, f"{prefix}-{seed}-{j}")
        materialize(log, upto, outdir, drop=drop, tear=tear,
                    order=order)
        yield {"upto": upto, "drop": drop, "tear": tear,
               "order": order, "dir": outdir, "seed": seed, "n": j}


# ------------------------------------------------------------ harness ---

class CrashHarness:
    """Drive a seeded BlueStore workload under a Recorder, keeping a
    model oracle; then enumerate crash images and assert the acked-
    write durability contract on each.

    The workload exercises every durability path: COW writes
    (single- and multi-block), deferred small overwrites, truncates,
    removes, omap rows, and WAL compaction (``compact_bytes`` is tiny
    so snapshot + MANIFEST renames land mid-stream).

    ``kv_fsync=False`` is the DELIBERATELY BROKEN ordering: the KV
    commit (and therefore the ack) happens before the WAL record is
    fsynced — the exact bug class the harness exists to catch; tests
    assert that enumeration then FAILS.
    """

    STORE_SUBDIR = "store"

    def __init__(self, root: str, *, seed: int = 0,
                 n_txns: int = 30, kv_fsync: bool = True,
                 min_alloc: int = 512, device_bytes: int = 1 << 20,
                 compact_bytes: int = 1536):
        self.root = os.path.abspath(root)
        self.seed = seed
        self.n_txns = n_txns
        self.kv_fsync = kv_fsync
        self.min_alloc = min_alloc
        self.device_bytes = device_bytes
        self.compact_bytes = compact_bytes
        # states[t] = model {oid: bytes} AFTER txn t acked;
        # states[-1] = initial empty store
        self.states: Dict[int, Dict[str, bytes]] = {-1: {}}
        self.omaps: Dict[int, Dict[Tuple[str, str], bytes]] = {-1: {}}
        self.log: List[Rec] = []

    def _open_store(self):
        from .bluestore import BlueStore
        st = BlueStore(os.path.join(self.root, self.STORE_SUBDIR),
                       fsync=True, min_alloc=self.min_alloc,
                       device_bytes=self.device_bytes,
                       deferred_max=self.min_alloc,
                       fsck_on_mount=False)
        st.kv.compact_bytes = self.compact_bytes
        if not self.kv_fsync:
            # THE BUG: acks outrun the WAL barrier
            st.kv.fsync = False
        return st

    def run_workload(self) -> List[Rec]:
        from .objectstore import Transaction
        rec = blockdev.attach(self.root)
        st = self._open_store()
        rng = random.Random(self.seed)
        C = (1, 0)
        model: Dict[str, bytes] = {}
        omodel: Dict[Tuple[str, str], bytes] = {}
        try:
            for t in range(self.n_txns):
                oid = f"obj-{rng.randrange(6)}"
                txn = Transaction()
                roll = rng.random()
                cur = model.get(oid)
                if cur is None or roll < 0.45:
                    # COW write_full, 1..4 blocks
                    n = rng.randrange(self.min_alloc // 2,
                                      4 * self.min_alloc)
                    data = bytes(rng.getrandbits(8) for _ in range(n))
                    txn.write_full(C, oid, data)
                    model[oid] = data
                elif roll < 0.75 and len(cur) > 8:
                    # small in-place overwrite -> the deferred path
                    ln = rng.randrange(1, min(len(cur),
                                              self.min_alloc // 2))
                    off = rng.randrange(0, len(cur) - ln + 1)
                    patch = bytes(rng.getrandbits(8)
                                  for _ in range(ln))
                    txn.write(C, oid, off, patch)
                    model[oid] = cur[:off] + patch + cur[off + ln:]
                elif roll < 0.85 and cur:
                    size = rng.randrange(0, len(cur))
                    txn.truncate(C, oid, size)
                    model[oid] = cur[:size]
                elif roll < 0.93:
                    txn.omap_set(C, oid, f"k{rng.randrange(3)}",
                                 bytes(rng.getrandbits(8)
                                       for _ in range(16)))
                    key = txn.ops[-1][3]
                    omodel[(oid, key)] = txn.ops[-1][4]
                else:
                    txn.remove(C, oid)
                    del model[oid]
                    for k in [k for k in omodel if k[0] == oid]:
                        del omodel[k]
                st.apply_transaction(txn)
                # the ACK boundary: everything up to here must be
                # durable in any crash image cut after this mark
                rec.mark(t)
                self.states[t] = dict(model)
                self.omaps[t] = dict(omodel)
        finally:
            st.close()
            blockdev.detach(rec)
        self.log = rec.snapshot()
        return self.log

    # ------------------------------------------------------- checking --
    def _expect_at(self, upto: int):
        """(acked_state, acked_omaps, next_state, next_omaps) for a
        crash at ``upto``: acked is the model at the last mark before
        the cut; next_* is the (at most one) in-flight transaction's
        complete outcome — the only other state an object may show."""
        acked = marks_before(self.log, upto)
        last = acked[-1] if acked else -1
        nxt = last + 1 if last + 1 in self.states else None
        return (self.states[last], self.omaps[last],
                None if nxt is None else self.states[nxt],
                None if nxt is None else self.omaps[nxt])

    def check_image(self, imgdir: str, upto: int) -> List[str]:
        """Assert the contract on one materialized image; returns the
        violations (empty = image satisfies the contract)."""
        from .bluestore import BlueStore
        from .objectstore import ObjectStoreError
        C = (1, 0)
        state, ostate, nxt, onxt = self._expect_at(upto)
        problems: List[str] = []
        store_dir = os.path.join(imgdir, self.STORE_SUBDIR)
        try:
            st = BlueStore(store_dir, fsync=False,
                           min_alloc=self.min_alloc,
                           device_bytes=self.device_bytes,
                           deferred_max=self.min_alloc,
                           fsck_on_mount=False)
        except Exception as e:
            return [f"mount failed: {type(e).__name__}: {e}"]
        try:
            bad = st.fsck()
            if bad:
                problems.append(f"fsck found {bad}")
            # every acked object fully readable, bytes exact
            seen = set()
            for oid, want in state.items():
                seen.add(oid)
                try:
                    got = st.read(C, oid)
                except (IOError, ObjectStoreError) as e:
                    if nxt is not None and oid not in nxt:
                        continue     # in-flight REMOVE landed whole
                    problems.append(
                        f"acked {oid} unreadable: {e}")
                    continue
                if got != want:
                    if nxt is not None and got == nxt.get(oid):
                        continue     # the in-flight txn landed whole
                    problems.append(
                        f"acked {oid}: {len(got)}B != expected "
                        f"{len(want)}B (Frankenstein or lost write)")
            for (oid, key), want in ostate.items():
                try:
                    got = st.omap_get(C, oid, key)
                except (KeyError, IOError, ObjectStoreError):
                    if onxt is not None and (oid, key) not in onxt:
                        continue     # in-flight remove landed whole
                    problems.append(f"acked omap {oid}/{key} lost")
                    continue
                if got != want and not (
                        onxt is not None
                        and got == onxt.get((oid, key))):
                    problems.append(f"acked omap {oid}/{key} mutated")
            # no unacked txn partially visible: any extra object (or
            # content off the acked model) must match the ONE
            # in-flight txn's complete outcome
            for oid in st.list_objects(C):
                if oid in seen:
                    continue
                if nxt is None or oid not in nxt:
                    problems.append(f"phantom object {oid}")
                    continue
                try:
                    got = st.read(C, oid)
                except (IOError, ObjectStoreError) as e:
                    problems.append(
                        f"in-flight {oid} visible but unreadable: {e}")
                    continue
                if got != nxt[oid]:
                    problems.append(
                        f"in-flight {oid} PARTIALLY visible "
                        f"(Frankenstein)")
        finally:
            st.close()
        return problems

    def double_crash_check(self, imgdir: str, upto: int,
                           seed: int, scratch: str) -> List[str]:
        """Crash AGAIN during the image's recovery (mount = WAL +
        deferred replay), reopen, and re-assert the contract — the
        'deferred replay idempotent under double-crash' rule.  Also
        asserts replay convergence: however the second crash cuts the
        replay, the final KV state digests agree."""
        from .bluestore import BlueStore
        base = os.path.join(scratch, "base")
        if os.path.exists(base):
            shutil.rmtree(base)
        shutil.copytree(imgdir, base)
        # record the first recovery's writes (mutates imgdir)
        rec = blockdev.attach(imgdir)
        try:
            st = BlueStore(os.path.join(imgdir, self.STORE_SUBDIR),
                           fsync=True, min_alloc=self.min_alloc,
                           device_bytes=self.device_bytes,
                           deferred_max=self.min_alloc,
                           fsck_on_mount=False)
            st.close()
        finally:
            blockdev.detach(rec)
        rlog = rec.snapshot()
        problems: List[str] = []
        if not rlog:
            return problems              # nothing replayed: no window
        rng = random.Random(seed)
        cuts = sorted({rng.randrange(1, len(rlog) + 1)
                       for _ in range(3)} | {len(rlog)})
        digest = None
        for ci, cut in enumerate(cuts):
            t2 = os.path.join(scratch, f"dc-{ci}")
            if os.path.exists(t2):
                shutil.rmtree(t2)
            shutil.copytree(base, t2)
            pend = pending_writes(rlog, cut)
            drop = [i for i in pend if rng.random() < 0.5]
            materialize(rlog, cut, t2, drop=drop)
            for p in self.check_image(t2, upto):
                problems.append(f"double-crash cut {cut}: {p}")
            # convergence: reopen once more and compare KV digests
            st = self._reopen_quiet(t2)
            if st is not None:
                d = st.kv.state_digest()
                st.close()
                if digest is None:
                    digest = d
                elif d != digest:
                    problems.append(
                        f"double-crash cut {cut}: replay did not "
                        f"converge (kv digest differs)")
        return problems

    def _reopen_quiet(self, imgdir: str):
        from .bluestore import BlueStore
        try:
            return BlueStore(os.path.join(imgdir, self.STORE_SUBDIR),
                             fsync=False, min_alloc=self.min_alloc,
                             device_bytes=self.device_bytes,
                             deferred_max=self.min_alloc,
                             fsck_on_mount=False)
        except Exception:
            return None

    # ----------------------------------------------------- enumeration --
    def enumerate_and_check(self, out_base: str, *,
                            seeds: Iterable[int] = (0, 1, 2),
                            images_per_seed: int = 70,
                            barrier_stride: int = 1,
                            double_crash_every: int = 0
                            ) -> Dict[str, Any]:
        """The acceptance sweep: every ``barrier_stride``-th clean
        barrier cut plus ``images_per_seed`` seeded mutated images per
        seed; returns counts + violations (empty = contract proven
        over the set)."""
        if not self.log:
            raise RuntimeError("run_workload() first")
        report: Dict[str, Any] = {"barrier_cuts": 0, "seeded": 0,
                                  "double_crash": 0, "violations": []}
        cuts = crash_points(self.log)[::max(1, barrier_stride)]
        for ci, cut in enumerate(cuts):
            d = os.path.join(out_base, f"cut-{ci}")
            materialize(self.log, cut, d)
            report["barrier_cuts"] += 1
            for p in self.check_image(d, cut):
                report["violations"].append(f"barrier cut {cut}: {p}")
            if double_crash_every and ci % double_crash_every == 0:
                report["double_crash"] += 1
                report["violations"].extend(self.double_crash_check(
                    d, cut, seed=self.seed * 997 + ci,
                    scratch=os.path.join(out_base, f"dc-{ci}")))
            shutil.rmtree(d, ignore_errors=True)
        for seed in seeds:
            for img in seeded_images(self.log, seed, images_per_seed,
                                     out_base):
                report["seeded"] += 1
                for p in self.check_image(img["dir"], img["upto"]):
                    report["violations"].append(
                        f"seed {seed} img {img['n']} "
                        f"(upto={img['upto']}, drop={img['drop']}, "
                        f"tear={img['tear']}): {p}")
                shutil.rmtree(img["dir"], ignore_errors=True)
        return report

    def lost_tail_image(self, out_base: str) -> Tuple[str, int]:
        """The worst-case image for un-barriered commits: cut at the
        end of the stream with EVERY pending write dropped.  A correct
        store survives this trivially (pending = unacked); a store
        that acks before its WAL barrier loses acked writes here —
        the falsifiability probe."""
        upto = len(self.log)
        d = os.path.join(out_base, "lost-tail")
        materialize(self.log, upto, d,
                    drop=pending_writes(self.log, upto))
        return d, upto


# ------------------------------------------------- powercycle mutation ---

_WAL_MAGIC = 0x57414C31
_WAL_HDR = struct.Struct("<IQII")


def tear_wal_tail(store_dir: str, rng: random.Random) -> int:
    """Process-tier crash-state mutation for ``--powercycle``: walk
    the dead OSD's BlueStore WAL, find the trailing PARTIAL record (a
    fragment whose commit never completed — SIGKILL/power cut landed
    mid-append), and tear a seeded number of bytes off it.  Complete,
    crc-valid records are NEVER touched: they may carry acked writes.
    Returns bytes torn (0 when the tail was clean).

    The rng is always advanced exactly once so the thrasher's seeded
    schedule stays identical whether or not a partial tail existed.
    """
    draw = rng.randrange(1, 64)          # schedule-stable draw
    wal = os.path.join(store_dir, "kv", "wal.log")
    if not os.path.exists(wal):
        return 0
    with open(wal, "rb") as f:
        blob = f.read()
    off = 0
    good_end = 0
    while off + _WAL_HDR.size <= len(blob):
        magic, _seq, ln, crc = _WAL_HDR.unpack_from(blob, off)
        if magic != _WAL_MAGIC:
            break
        payload = blob[off + _WAL_HDR.size:off + _WAL_HDR.size + ln]
        if len(payload) != ln or zlib.crc32(payload) != crc:
            break
        off += _WAL_HDR.size + ln
        good_end = off
    partial = len(blob) - good_end
    if partial <= 0:
        return 0
    tear = min(partial, draw)
    with open(wal, "r+b") as f:          # noqa: store surgery on a
        f.truncate(len(blob) - tear)     # DEAD daemon's files
    return tear
