"""Durable per-PG op log for OSD daemons (the process-tier PGLog).

VERDICT r3 missing #3: daemons must run the repo's own PGLog/peering
machinery, not an ad-hoc list/pull/push.  This module binds
cluster/pglog.PGLog to a FileStore: entries and last_complete live in
the omap of a per-PG meta object, and every shard write appends its
log entry IN THE SAME TRANSACTION — an object version and its log
record cannot diverge across a SIGKILL (the reference writes the pg
log and the op in one ObjectStore transaction too,
src/osd/PrimaryLogPG.cc prepare_transaction + PGLog write).

Row layout (omap of object "meta:pglog" in the PG's collection):
    e:{epoch:010d}.{seq:010d} -> json {"obj":…, "op":…}
    last_complete             -> "epoch.seq"
Versions are (epoch, seq) eversion_t pairs, compared as tuples.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from .objectstore import Transaction
from .pglog import OP_DELETE, OP_MODIFY, LogEntry, PGLog, Version, ZERO

META_OID = "meta:pglog"


def _vkey(v: Version) -> str:
    return f"e:{v[0]:010d}.{v[1]:010d}"


def _venc(v: Version) -> bytes:
    return f"{v[0]}.{v[1]}".encode()


def _vdec(b: bytes) -> Version:
    e, s = bytes(b).decode().split(".")
    return (int(e), int(s))


class DurablePGLog:
    """One PG's log on one OSD daemon's FileStore."""

    def __init__(self, store, coll: Tuple[int, int],
                 max_entries: int = 3000):
        self.store = store
        self.coll = coll
        self.log = PGLog(max_entries=max_entries)
        self.last_complete: Version = ZERO
        self._load()

    # ----------------------------------------------------------- loading --
    def _load(self) -> None:
        if not self.store.exists(self.coll, META_OID):
            return
        for key, val in self.store.omap_list(self.coll, META_OID):
            if key.startswith("e:"):
                d = json.loads(bytes(val).decode())
                ep, seq = key[2:].split(".")
                v = (int(ep), int(seq))
                self.log.entries.append(LogEntry(v, d["obj"],
                                                 d.get("op",
                                                       OP_MODIFY)))
            elif key == "last_complete":
                self.last_complete = _vdec(val)
            elif key == "tail":
                self.log.tail = _vdec(val)
        self.log.entries.sort(key=lambda e: e.version)
        if self.log.entries:
            self.log.head = self.log.entries[-1].version
            self.log._seq = self.log.head[1]

    # ----------------------------------------------------------- writing --
    def _ensure_meta(self, txn: Transaction) -> None:
        if not self.store.exists(self.coll, META_OID):
            txn.touch(self.coll, META_OID)

    def append_txn(self, txn: Transaction, version: Version, obj: str,
                   op: int = OP_MODIFY,
                   advance_lc: bool = True) -> None:
        """Record one op into the caller's transaction and mirror it
        in memory once the caller applies the txn (callers MUST apply
        the txn; we update memory eagerly because apply_transaction
        either fully commits or raises, and on raise the daemon drops
        the connection/op anyway)."""
        self._ensure_meta(txn)
        txn.omap_set(self.coll, META_OID, _vkey(version),
                     json.dumps({"obj": obj, "op": op}).encode())
        e = LogEntry(version, obj, op)
        self.log.entries.append(e)
        self.log.head = version
        self.log._seq = max(self.log._seq, version[1])
        if advance_lc:
            self.last_complete = version
            txn.omap_set(self.coll, META_OID, "last_complete",
                         _venc(version))
        # bounded log: trim rows beyond the cap in the same txn
        while len(self.log.entries) > self.log.max_entries:
            dropped = self.log.entries.pop(0)
            self.log.tail = dropped.version
            txn.omap_rm(self.coll, META_OID, _vkey(dropped.version))
            txn.omap_set(self.coll, META_OID, "tail",
                         _venc(self.log.tail))

    def set_last_complete_txn(self, txn: Transaction,
                              version: Version) -> None:
        self._ensure_meta(txn)
        self.last_complete = version
        txn.omap_set(self.coll, META_OID, "last_complete",
                     _venc(version))

    def merge_tail_txn(self, txn: Transaction,
                       entries: List[Tuple[Version, str, int]],
                       head: Version) -> None:
        """Adopt the authority's log tail (PGLog::merge_log role):
        used by log_sync after delta/backfill recovery."""
        self._ensure_meta(txn)
        known = {e.version for e in self.log.entries}
        for v, obj, op in entries:
            v = (int(v[0]), int(v[1]))
            if v in known:
                continue
            txn.omap_set(self.coll, META_OID, _vkey(v),
                         json.dumps({"obj": obj, "op": op}).encode())
            self.log.entries.append(LogEntry(v, obj, op))
        self.log.entries.sort(key=lambda e: e.version)
        if self.log.entries:
            self.log.head = max(self.log.head,
                                self.log.entries[-1].version)
            self.log._seq = max(self.log._seq, self.log.head[1])
        self.set_last_complete_txn(txn, head)

    # ------------------------------------------------------------ queries --
    def next_version(self, epoch: int) -> Version:
        """Primary-side version assignment: strictly after head."""
        h = self.log.head
        if epoch > h[0]:
            return (epoch, 1)
        return (h[0], h[1] + 1)

    def info(self) -> Dict:
        return {"head": list(self.log.head),
                "last_complete": list(self.last_complete),
                "tail": list(self.log.tail),
                "n_entries": len(self.log.entries)}

    def entries_after(self, version: Version
                      ) -> List[Tuple[Version, str, int]]:
        return [(e.version, e.obj, e.op)
                for e in self.log.entries_after(version)]

    def covers(self, version: Version) -> bool:
        return self.log.covers(version)
