"""EC read-modify-write pipeline: partial-stripe overwrites, batched.

The reference's EC write path is a read-modify-write state machine —
ECBackend::start_rmw gathers the stripes an overwrite touches,
try_reads_to_commit reads the old boundary stripes (through an
ExtentCache so in-flight data is not re-read from shards), and
ECTransaction::generate_transactions emits per-shard writes
(src/osd/ECBackend.cc:1876,1976; src/osd/ECTransaction.h:185;
src/osd/ExtentCache.h).

TPU-native shape: the stripe is the batch element.  An overwrite of any
size resolves to (a) at most two partial boundary stripes whose OLD
bytes are fetched (extent cache first, then shard reads + batched
decode if degraded), (b) a pure-Python byte merge, (c) ONE batched
device encode over every affected stripe, (d) per-shard chunk writes.
The object's at-rest layout is the reference's stripewise shard format
(stripe_info_t, src/osd/ECUtil.h:28-60): shard j holds stripe i's chunk
j at byte range [i*U, (i+1)*U).
"""
from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class StripeInfo:
    """ECUtil::stripe_info_t analog: pure layout arithmetic."""
    k: int
    chunk_size: int                  # stripe_unit U

    @property
    def stripe_width(self) -> int:
        return self.k * self.chunk_size

    def stripe_count(self, size: int) -> int:
        """Stripes needed to hold `size` logical bytes."""
        if size <= 0:
            return 0
        return -(-size // self.stripe_width)

    def range_stripes(self, offset: int, length: int) -> Tuple[int, int]:
        """[first, last] stripe indices touched by the byte range."""
        if length <= 0:
            raise ValueError("length must be positive")
        return offset // self.stripe_width, \
            (offset + length - 1) // self.stripe_width

    def stripe_to_chunks(self, stripe: bytes) -> np.ndarray:
        """One stripe's bytes (padded to width) -> [k, U]."""
        buf = np.zeros(self.stripe_width, dtype=np.uint8)
        arr = np.frombuffer(stripe, dtype=np.uint8)[:self.stripe_width]
        buf[:len(arr)] = arr
        return buf.reshape(self.k, self.chunk_size)

    def chunks_to_stripe(self, chunks: np.ndarray) -> bytes:
        return chunks.reshape(-1).tobytes()


class ExtentCache:
    """Recently materialized stripes, keyed (object_key, stripe_index).

    Plays the role of the reference ExtentCache (src/osd/ExtentCache.h):
    back-to-back partial writes to the same stripes must not re-read
    their shards.  LRU-bounded by stripe count.
    """

    def __init__(self, capacity_stripes: int = 1024):
        self.capacity = capacity_stripes
        self._entries: "OrderedDict[Tuple, np.ndarray]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: Tuple) -> Optional[np.ndarray]:
        e = self._entries.get(key)
        if e is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return e

    def put(self, key: Tuple, chunks: np.ndarray) -> None:
        self._entries[key] = chunks
        self._entries.move_to_end(key)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)

    def invalidate_object(self, obj_key: Tuple) -> None:
        for k in [k for k in self._entries if k[:-1] == obj_key]:
            del self._entries[k]


class RmwPipeline:
    """One overwrite -> (old-read plan, merge, batched encode)."""

    def __init__(self, codec, stripe_unit: int,
                 cache: Optional[ExtentCache] = None):
        self.codec = codec
        self.k = codec.get_data_chunk_count()
        self.m = codec.get_coding_chunk_count()
        self.sinfo = StripeInfo(self.k, stripe_unit)
        self.cache = cache if cache is not None else ExtentCache()

    def write(self, obj_key: Tuple, old_size: int, offset: int,
              data: bytes,
              read_stripe: Callable[[int], Optional[np.ndarray]]
              ) -> Tuple[Dict[int, np.ndarray], int]:
        """Plan + execute an overwrite.

        ``read_stripe(i)`` returns the OLD data chunks [k, U] of stripe
        i (decoding if degraded) or None if the stripe was never
        written.  Returns ({stripe_index: [k+m, U] new chunks}, new
        object size); the caller persists the chunks per shard.
        """
        if not data:
            return {}, old_size
        si = self.sinfo
        first, last = si.range_stripes(offset, len(data))
        W = si.stripe_width
        n_str = last - first + 1
        # assemble the affected byte span, old bytes under new ones
        span = np.zeros(n_str * W, dtype=np.uint8)
        old_stripes = si.stripe_count(old_size)
        for idx in range(first, last + 1):
            s0 = idx * W
            partial_head = idx == first and offset > s0
            partial_tail = idx == last and (offset + len(data)) < \
                min(s0 + W, max(old_size, offset + len(data)))
            if (partial_head or partial_tail) and idx < old_stripes:
                old = self.cache.get(obj_key + (idx,))
                if old is None:
                    old = read_stripe(idx)
                if old is not None:
                    span[(idx - first) * W:(idx - first + 1) * W] = \
                        old.reshape(-1)
        new = np.frombuffer(data, dtype=np.uint8)
        a = offset - first * W
        span[a:a + len(new)] = new
        # ONE batched device encode over all affected stripes
        dchunks = span.reshape(n_str, self.k, si.chunk_size)
        parity = np.asarray(self.codec.encode_chunks_batch(dchunks))
        out: Dict[int, np.ndarray] = {}
        for j, idx in enumerate(range(first, last + 1)):
            chunks = np.concatenate([dchunks[j], parity[j]], axis=0)
            out[idx] = chunks
            self.cache.put(obj_key + (idx,), dchunks[j].copy())
        return out, max(old_size, offset + len(data))
