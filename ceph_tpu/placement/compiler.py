"""Crushmap text language — compile (text -> CrushMap) and decompile.

The reference ships a boost::spirit grammar + compiler/decompiler pair
(src/crush/grammar.h, src/crush/CrushCompiler.cc) behind
`crushtool -c/-d`.  This is a hand-written recursive-descent reader for
the same language — the wire format users actually edit:

    tunable <name> <value>
    device <num> <name> [class <class>]
    type <num> <name>
    <typename> <bucketname> {
        id <negid> [class <class>]     # shadow ids per device class
        alg uniform|list|tree|straw|straw2
        hash 0
        item <name> [weight <float>] [pos <int>]
    }
    rule <name> {
        id <num>
        type replicated|erasure
        step take <bucket> [class <class>]
        step set_chooseleaf_tries <n>
        step [choose|chooseleaf] [firstn|indep] <n> type <typename>
        step emit
    }
    choose_args <key> { { bucket_id <id> weight_set [ [ ... ] ] ids [..] } }

Weights are 16.16 fixed-point in the map, printed as 5-decimal floats
(the crushtool convention).  `step take <bucket> class <c>` compiles to
the class shadow bucket (CrushWrapper device-class trees,
src/crush/CrushWrapper.h:66) — built on demand by
`crush_map.build_class_shadow`.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from .crush_map import (
    ALG_BY_NAME, ALG_NAMES, HASH_RJENKINS1, RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP, RULE_CHOOSE_FIRSTN, RULE_CHOOSE_INDEP, RULE_EMIT,
    RULE_SET_CHOOSELEAF_STABLE, RULE_SET_CHOOSELEAF_TRIES,
    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES, RULE_SET_CHOOSE_LOCAL_TRIES,
    RULE_SET_CHOOSE_TRIES, RULE_SET_CHOOSELEAF_VARY_R, RULE_TAKE,
    Bucket, ChooseArg, CrushMap, Rule, Tunables,
)

_TUNABLES = ("choose_local_tries", "choose_local_fallback_tries",
             "choose_total_tries", "chooseleaf_descend_once",
             "chooseleaf_vary_r", "chooseleaf_stable",
             "straw_calc_version", "allowed_bucket_algs")

_RULE_TYPES = {1: "replicated", 3: "erasure"}
_RULE_TYPE_IDS = {v: k for k, v in _RULE_TYPES.items()}
# legacy spellings accepted by the reference compiler
_RULE_TYPE_IDS["msr_indep"] = 3


class CompileError(ValueError):
    def __init__(self, msg: str, line: Optional[int] = None):
        super().__init__(f"line {line}: {msg}" if line else msg)
        self.line = line


def _fmt_weight(w: int) -> str:
    return f"{w / 0x10000:.5f}"


def _parse_weight(tok: str, line: int) -> int:
    try:
        v = float(tok)
    except ValueError:
        raise CompileError(f"bad weight {tok!r}", line) from None
    if v < 0:
        raise CompileError(f"negative weight {tok!r}", line)
    return int(round(v * 0x10000))


class _Tokens:
    """Token stream with line tracking; comments stripped."""

    def __init__(self, text: str):
        self.toks: List[Tuple[str, int]] = []
        for ln, raw in enumerate(text.splitlines(), 1):
            body = raw.split("#", 1)[0]
            # brackets/braces are their own tokens
            body = re.sub(r"([{}\[\]])", r" \1 ", body)
            for tok in body.split():
                self.toks.append((tok, ln))
        self.pos = 0

    def peek(self) -> Optional[str]:
        return self.toks[self.pos][0] if self.pos < len(self.toks) else None

    def line(self) -> int:
        if self.pos < len(self.toks):
            return self.toks[self.pos][1]
        return self.toks[-1][1] if self.toks else 0

    def next(self, what: str = "token") -> str:
        if self.pos >= len(self.toks):
            raise CompileError(f"unexpected end of input, wanted {what}",
                               self.line())
        tok, _ = self.toks[self.pos]
        self.pos += 1
        return tok

    def expect(self, want: str) -> None:
        tok = self.next(repr(want))
        if tok != want:
            raise CompileError(f"expected {want!r}, got {tok!r}",
                               self.toks[self.pos - 1][1])

    def next_int(self, what: str = "integer") -> int:
        tok = self.next(what)
        try:
            return int(tok)
        except ValueError:
            raise CompileError(f"expected {what}, got {tok!r}",
                               self.toks[self.pos - 1][1]) from None


# ------------------------------------------------------------------ compile --

class CrushCompiler:
    """text -> CrushMap (one-shot; use compile_crushmap())."""

    def __init__(self, text: str):
        self.t = _Tokens(text)
        self.map = CrushMap(tunables=Tunables())
        self.tunables: Dict[str, int] = {}
        self.name_to_id: Dict[str, int] = {}
        self.type_by_name: Dict[str, int] = {}
        self.class_ids: Dict[Tuple[int, str], int] = {}  # (bucket, class)

    def compile(self) -> CrushMap:
        while (tok := self.t.peek()) is not None:
            if tok == "tunable":
                self._tunable()
            elif tok == "device":
                self._device()
            elif tok == "type":
                self._type()
            elif tok == "rule":
                self._rule()
            elif tok == "choose_args":
                self._choose_args()
            elif tok in self.type_by_name:
                self._bucket()
            else:
                raise CompileError(f"unknown directive {tok!r}",
                                   self.t.line())
        if self.tunables:
            known = {k: v for k, v in self.tunables.items()
                     if k in Tunables.__dataclass_fields__}
            self.map.tunables = Tunables(**known)
        # build shadows for every declared (bucket, class) pair that no
        # rule forced yet, so declared shadow ids survive a round-trip
        for (bid, cls) in list(self.class_ids):
            if (bid, cls) not in self.map.class_bucket_ids:
                self.map.build_class_shadow(bid, cls,
                                            preferred_ids=self.class_ids)
        self.map.finalize()
        return self.map

    def _tunable(self) -> None:
        self.t.expect("tunable")
        name = self.t.next("tunable name")
        val = self.t.next_int("tunable value")
        if name not in _TUNABLES:
            raise CompileError(f"unknown tunable {name!r}", self.t.line())
        self.tunables[name] = val

    def _device(self) -> None:
        self.t.expect("device")
        num = self.t.next_int("device number")
        name = self.t.next("device name")
        if num < 0:
            raise CompileError("device ids are non-negative", self.t.line())
        self.map.device_names[num] = name
        self.name_to_id[name] = num
        self.map.max_devices = max(self.map.max_devices, num + 1)
        if self.t.peek() == "class":
            self.t.next()
            self.map.device_classes[num] = self.t.next("class name")

    def _type(self) -> None:
        self.t.expect("type")
        num = self.t.next_int("type number")
        name = self.t.next("type name")
        self.map.type_names[num] = name
        self.type_by_name[name] = num

    def _bucket(self) -> None:
        type_name = self.t.next()
        btype = self.type_by_name[type_name]
        name = self.t.next("bucket name")
        if name in self.name_to_id:
            raise CompileError(f"duplicate name {name!r}", self.t.line())
        self.t.expect("{")
        bid: Optional[int] = None
        alg = None
        hash_ = HASH_RJENKINS1
        shadow: Dict[str, int] = {}
        items: List[int] = []
        weights: List[int] = []
        filled: set = set()
        while (tok := self.t.peek()) != "}":
            if tok is None:
                raise CompileError("unterminated bucket", self.t.line())
            if tok == "id":
                self.t.next()
                i = self.t.next_int("bucket id")
                if i >= 0:
                    raise CompileError("bucket ids are negative",
                                       self.t.line())
                if self.t.peek() == "class":
                    self.t.next()
                    shadow[self.t.next("class name")] = i
                else:
                    bid = i
            elif tok == "alg":
                self.t.next()
                alg_name = self.t.next("alg")
                if alg_name not in ALG_BY_NAME:
                    raise CompileError(f"unknown alg {alg_name!r}",
                                       self.t.line())
                alg = ALG_BY_NAME[alg_name]
            elif tok == "hash":
                self.t.next()
                h = self.t.next("hash")
                if h == "rjenkins1":
                    hash_ = 0
                else:
                    try:
                        hash_ = int(h)
                    except ValueError:
                        raise CompileError(f"unknown hash {h!r}",
                                           self.t.line()) from None
            elif tok == "item":
                self.t.next()
                iname = self.t.next("item name")
                if iname not in self.name_to_id:
                    raise CompileError(f"item {iname!r} not defined",
                                       self.t.line())
                iid = self.name_to_id[iname]
                w = 0
                pos = len(items)
                while self.t.peek() in ("weight", "pos"):
                    key = self.t.next()
                    if key == "weight":
                        w = _parse_weight(self.t.next("weight"),
                                          self.t.line())
                    else:
                        pos = self.t.next_int("pos")
                if iid < 0 and w == 0:
                    child = self.map.bucket(iid)
                    w = child.weight if child is not None else 0
                if pos in filled:
                    raise CompileError(f"item pos {pos} used twice",
                                       self.t.line())
                while len(items) <= pos:
                    items.append(0)
                    weights.append(0)
                items[pos] = iid
                weights[pos] = w
                filled.add(pos)
            else:
                raise CompileError(f"unknown bucket field {tok!r}",
                                   self.t.line())
        self.t.expect("}")
        if alg is None:
            raise CompileError(f"bucket {name!r} has no alg", self.t.line())
        if len(filled) != len(items):
            missing = [p for p in range(len(items)) if p not in filled]
            raise CompileError(
                f"bucket {name!r}: item pos {missing} never filled "
                "(phantom slots)", self.t.line())
        if bid is None:
            bid = self.map.next_bucket_id()
        b = Bucket(id=bid, alg=alg, type=btype, items=items,
                   weights=weights, hash=hash_)
        self.map.add_bucket(b)
        self.map.bucket_names[bid] = name
        self.name_to_id[name] = bid
        for cls, sid in shadow.items():
            self.class_ids[(bid, cls)] = sid

    def _rule(self) -> None:
        self.t.expect("rule")
        name = self.t.next("rule name")
        self.t.expect("{")
        ruleno = -1
        rtype = 1
        min_size, max_size = 1, 10
        steps: List[Tuple[int, int, int]] = []
        while (tok := self.t.peek()) != "}":
            if tok is None:
                raise CompileError("unterminated rule", self.t.line())
            if tok in ("id", "ruleset"):      # ruleset = legacy spelling
                self.t.next()
                ruleno = self.t.next_int("rule id")
            elif tok == "type":
                self.t.next()
                tname = self.t.next("rule type")
                if tname not in _RULE_TYPE_IDS:
                    raise CompileError(f"unknown rule type {tname!r}",
                                       self.t.line())
                rtype = _RULE_TYPE_IDS[tname]
            elif tok == "min_size":
                self.t.next()
                min_size = self.t.next_int()
            elif tok == "max_size":
                self.t.next()
                max_size = self.t.next_int()
            elif tok == "step":
                self.t.next()
                steps.append(self._step())
            else:
                raise CompileError(f"unknown rule field {tok!r}",
                                   self.t.line())
        self.t.expect("}")
        rule = Rule(steps=steps, name=name, type=rtype,
                    min_size=min_size, max_size=max_size)
        if ruleno < 0:
            ruleno = self.map.max_rules
        if ruleno < self.map.max_rules and \
                self.map.rules[ruleno] is not None:
            raise CompileError(f"duplicate rule id {ruleno}",
                               self.t.line())
        self.map.add_rule(rule, ruleno)

    def _step(self) -> Tuple[int, int, int]:
        op = self.t.next("step op")
        if op == "take":
            bname = self.t.next("bucket name")
            if bname not in self.name_to_id:
                raise CompileError(f"take: unknown bucket {bname!r}",
                                   self.t.line())
            bid = self.name_to_id[bname]
            if self.t.peek() == "class":
                self.t.next()
                cls = self.t.next("class name")
                bid = self.map.build_class_shadow(
                    bid, cls, preferred_ids=self.class_ids)
            return (RULE_TAKE, bid, 0)
        if op == "emit":
            return (RULE_EMIT, 0, 0)
        if op in ("set_choose_tries", "set_chooseleaf_tries",
                  "set_choose_local_tries",
                  "set_choose_local_fallback_tries",
                  "set_chooseleaf_vary_r", "set_chooseleaf_stable"):
            val = self.t.next_int()
            opcode = {
                "set_choose_tries": RULE_SET_CHOOSE_TRIES,
                "set_chooseleaf_tries": RULE_SET_CHOOSELEAF_TRIES,
                "set_choose_local_tries": RULE_SET_CHOOSE_LOCAL_TRIES,
                "set_choose_local_fallback_tries":
                    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
                "set_chooseleaf_vary_r": RULE_SET_CHOOSELEAF_VARY_R,
                "set_chooseleaf_stable": RULE_SET_CHOOSELEAF_STABLE,
            }[op]
            return (opcode, val, 0)
        if op in ("choose", "chooseleaf"):
            mode = self.t.next("firstn|indep")
            if mode not in ("firstn", "indep"):
                raise CompileError(f"expected firstn|indep, got {mode!r}",
                                   self.t.line())
            n = self.t.next_int("count")
            self.t.expect("type")
            tname = self.t.next("type name")
            if tname not in self.type_by_name:
                raise CompileError(f"unknown type {tname!r}", self.t.line())
            ttype = self.type_by_name[tname]
            opcode = {
                ("choose", "firstn"): RULE_CHOOSE_FIRSTN,
                ("choose", "indep"): RULE_CHOOSE_INDEP,
                ("chooseleaf", "firstn"): RULE_CHOOSELEAF_FIRSTN,
                ("chooseleaf", "indep"): RULE_CHOOSELEAF_INDEP,
            }[(op, mode)]
            return (opcode, n, ttype)
        raise CompileError(f"unknown step {op!r}", self.t.line())

    def _choose_args(self) -> None:
        self.t.expect("choose_args")
        key_tok = self.t.next("choose_args key")
        try:
            key: object = int(key_tok)
        except ValueError:
            key = key_tok
        self.t.expect("{")
        args: List[Optional[ChooseArg]] = \
            [None] * len(self.map.buckets)
        while self.t.peek() == "{":
            self.t.next()
            bucket_id = None
            weight_set = None
            ids = None
            while (tok := self.t.peek()) != "}":
                if tok is None:
                    raise CompileError("unterminated choose_args entry",
                                       self.t.line())
                if tok == "bucket_id":
                    self.t.next()
                    bucket_id = self.t.next_int("bucket id")
                elif tok == "weight_set":
                    self.t.next()
                    weight_set = self._weight_set()
                elif tok == "ids":
                    self.t.next()
                    ids = self._int_list()
                else:
                    raise CompileError(
                        f"unknown choose_args field {tok!r}", self.t.line())
            self.t.expect("}")
            if bucket_id is None or bucket_id >= 0:
                raise CompileError("choose_args entry needs bucket_id",
                                   self.t.line())
            idx = -1 - bucket_id
            while len(args) <= idx:
                args.append(None)
            args[idx] = ChooseArg(ids=ids, weight_set=weight_set)
        self.t.expect("}")
        self.map.choose_args[key] = args

    def _weight_set(self) -> List[List[int]]:
        self.t.expect("[")
        out: List[List[int]] = []
        while self.t.peek() == "[":
            self.t.next()
            row: List[int] = []
            while self.t.peek() != "]":
                row.append(_parse_weight(self.t.next("weight"),
                                         self.t.line()))
            self.t.expect("]")
            out.append(row)
        self.t.expect("]")
        return out

    def _int_list(self) -> List[int]:
        self.t.expect("[")
        out: List[int] = []
        while self.t.peek() != "]":
            out.append(self.t.next_int())
        self.t.expect("]")
        return out


def compile_crushmap(text: str) -> CrushMap:
    return CrushCompiler(text).compile()


# ---------------------------------------------------------------- decompile --

def _item_name(cmap: CrushMap, iid: int) -> str:
    if iid >= 0:
        return cmap.device_names.get(iid, f"osd.{iid}")
    return cmap.bucket_names.get(iid, f"bucket{-1 - iid}")


def decompile_crushmap(cmap: CrushMap) -> str:
    """CrushMap -> canonical text (crushtool -d shape); shadow buckets
    (negative ids created for device classes) are folded back into
    `id ... class ...` lines + `step take ... class ...` steps."""
    shadow_ids = getattr(cmap, "class_bucket_ids", {}) or {}
    shadow_rev: Dict[int, Tuple[int, str]] = {
        sid: (bid, cls) for (bid, cls), sid in shadow_ids.items()}
    out: List[str] = ["# begin crush map"]
    for name in _TUNABLES:
        val = getattr(cmap.tunables, name, None)
        if val is not None:
            out.append(f"tunable {name} {val}")
    out.append("")
    out.append("# devices")
    for d in range(cmap.max_devices):
        name = cmap.device_names.get(d, f"osd.{d}")
        cls = cmap.device_classes.get(d)
        out.append(f"device {d} {name}" + (f" class {cls}" if cls else ""))
    out.append("")
    out.append("# types")
    # declare every type referenced by a bucket or a choose step, even
    # when the map carries no names — `-d` output must always recompile
    referenced = {0}
    for b in cmap.buckets:
        if b is not None:
            referenced.add(b.type)
    for rule in cmap.rules:
        if rule is None:
            continue
        for op, a1, a2 in rule.steps:
            if op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSE_INDEP,
                      RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP):
                referenced.add(a2)
    for num in sorted(referenced | set(cmap.type_names)):
        out.append(f"type {num} "
                   f"{cmap.type_names.get(num, f'type{num}')}")
    out.append("")
    out.append("# buckets")
    # children before parents so the compiler can resolve item names
    emitted: set = set()

    def emit_bucket(b: Bucket) -> None:
        if b.id in emitted or b.id in shadow_rev:
            return
        emitted.add(b.id)
        for iid in b.items:
            if iid < 0:
                child = cmap.bucket(iid)
                if child is not None:
                    emit_bucket(child)
        tname = cmap.type_names.get(b.type, f"type{b.type}")
        out.append(f"{tname} {_item_name(cmap, b.id)} {{")
        out.append(f"\tid {b.id}\t\t# do not change unnecessarily")
        for (bid, cls), sid in sorted(shadow_ids.items()):
            if bid == b.id:
                out.append(f"\tid {sid} class {cls}\t\t"
                           "# do not change unnecessarily")
        out.append(f"\t# weight {_fmt_weight(b.weight)}")
        out.append(f"\talg {ALG_NAMES[b.alg]}")
        out.append(f"\thash {b.hash}" +
                   ("\t# rjenkins1" if b.hash == 0 else ""))
        for pos, (iid, w) in enumerate(zip(b.items, b.weights)):
            wv = b.item_weight(pos)
            out.append(f"\titem {_item_name(cmap, iid)} "
                       f"weight {_fmt_weight(wv)}")
        out.append("}")

    for b in cmap.buckets:
        if b is not None:
            emit_bucket(b)
    out.append("")
    out.append("# rules")
    for ruleno, rule in enumerate(cmap.rules):
        if rule is None:
            continue
        name = rule.name or f"rule-{ruleno}"
        out.append(f"rule {name} {{")
        out.append(f"\tid {ruleno}")
        out.append(f"\ttype {_RULE_TYPES.get(rule.type, 'replicated')}")
        out.append(f"\tmin_size {rule.min_size}")
        out.append(f"\tmax_size {rule.max_size}")
        for op, a1, a2 in rule.steps:
            if op == RULE_TAKE:
                if a1 in shadow_rev:
                    bid, cls = shadow_rev[a1]
                    out.append(f"\tstep take {_item_name(cmap, bid)} "
                               f"class {cls}")
                else:
                    out.append(f"\tstep take {_item_name(cmap, a1)}")
            elif op == RULE_EMIT:
                out.append("\tstep emit")
            elif op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSE_INDEP,
                        RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP):
                kind = "choose" if op in (RULE_CHOOSE_FIRSTN,
                                          RULE_CHOOSE_INDEP) else "chooseleaf"
                mode = "firstn" if op in (RULE_CHOOSE_FIRSTN,
                                          RULE_CHOOSELEAF_FIRSTN) else "indep"
                tname = cmap.type_names.get(a2, f"type{a2}")
                out.append(f"\tstep {kind} {mode} {a1} type {tname}")
            else:
                opname = {
                    RULE_SET_CHOOSE_TRIES: "set_choose_tries",
                    RULE_SET_CHOOSELEAF_TRIES: "set_chooseleaf_tries",
                    RULE_SET_CHOOSE_LOCAL_TRIES: "set_choose_local_tries",
                    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
                        "set_choose_local_fallback_tries",
                    RULE_SET_CHOOSELEAF_VARY_R: "set_chooseleaf_vary_r",
                    RULE_SET_CHOOSELEAF_STABLE: "set_chooseleaf_stable",
                }.get(op)
                if opname is None:
                    raise CompileError(f"cannot decompile op {op}")
                out.append(f"\tstep {opname} {a1}")
        out.append("}")
    if cmap.choose_args:
        out.append("")
        for key in sorted(cmap.choose_args, key=str):
            args = cmap.choose_args[key]
            out.append(f"choose_args {key} {{")
            for idx, arg in enumerate(args):
                if arg is None:
                    continue
                out.append("  {")
                out.append(f"    bucket_id {-1 - idx}")
                if arg.weight_set:
                    out.append("    weight_set [")
                    for row in arg.weight_set:
                        vals = " ".join(_fmt_weight(w) for w in row)
                        out.append(f"      [ {vals} ]")
                    out.append("    ]")
                if arg.ids:
                    vals = " ".join(str(i) for i in arg.ids)
                    out.append(f"    ids [ {vals} ]")
                out.append("  }")
            out.append("}")
    out.append("")
    out.append("# end crush map")
    return "\n".join(out) + "\n"
