"""CRUSH placement: map model, scalar reference mapper, batched TPU mapper."""
