"""The straw2 fixed-point log table.

The reference computes `crush_ln(u)` (2^44*log2(u+1) in fixed point,
src/crush/mapper.c:248-290) from two small tables whose published generating
formulas do NOT reproduce the shipped data (235/256 entries of __LL_tbl
deviate — a long-standing upstream quirk preserved for compatibility).  Since
straw2 only ever evaluates u in [0, 0xffff] (mapper.c:337-350), the entire
pipeline collapses to one 65536-entry LUT, extracted once from the reference
tables by scripts/gen_golden.py and stored as packaged data.

`STRAW2_LN[u] = crush_ln(u) - 0x1000000000000` is the (negative) numerator of
the straw2 draw; the draw itself is `trunc_div(STRAW2_LN[u], weight)`
(mapper.c:350-358).
"""
from __future__ import annotations

import functools
import os

import numpy as np

_DATA = os.path.join(os.path.dirname(__file__), "data", "crush_ln_u16.npy")

LN_SHIFT = 0x1000000000000  # 2^48; mapper.c:350
S64_MIN = -(2**63)


@functools.lru_cache(maxsize=None)
def crush_ln_lut() -> np.ndarray:
    """int64[65536]: crush_ln(u) for u in [0, 0xffff]."""
    lut = np.load(_DATA)
    lut.setflags(write=False)
    return lut


@functools.lru_cache(maxsize=None)
def straw2_ln_lut() -> np.ndarray:
    """int64[65536]: crush_ln(u) - 2^48 — the negative draw numerator."""
    lut = crush_ln_lut() - np.int64(LN_SHIFT)
    lut.setflags(write=False)
    return lut


def straw2_draw(u: int, weight: int) -> int:
    """Scalar straw2 draw: trunc_div(ln, weight); S64_MIN for weight==0.

    C's div64_s64 truncates toward zero; ln <= 0 and weight > 0, so
    trunc(ln/w) == -((-ln) // w).
    """
    if weight == 0:
        return S64_MIN
    ln = int(straw2_ln_lut()[u])
    return -((-ln) // weight)
