"""Batched CRUSH mapping as one jitted XLA program — north-star loop #1.

Replaces the reference's per-x interpreter stack (crush_do_rule,
src/crush/mapper.c:900-1105; CrushTester's triple loop,
src/crush/CrushTester.cc:612-623; the ParallelPGMapper thread-pool batcher,
src/osd/OSDMapMapping.h:18) with a single compiled call that maps millions
of PG ids at once:

  * The CrushMap compiles to dense padded arrays (items, weights, types,
    sizes, per-position weight-sets) — pure data, no pointers.
  * straw2 selection (mapper.c:361-384) is a vectorized hash → 64-bit
    fixed-point log LUT → truncating divide → argmax over the padded item
    axis.  argmax's first-max tie-break reproduces the scalar strict-'>'
    scan exactly.
  * The rule program is unrolled at trace time (steps are static); the
    data-dependent retry loops of crush_choose_firstn (mapper.c:460-648)
    and crush_choose_indep (mapper.c:655-843) become bounded
    lax.while_loops with masked state, vmapped over x.

Bit-exactness contract: for supported maps (straw2 buckets, modern
tunables with choose_local_tries == choose_local_fallback_tries == 0 —
the 'bobtail'+ profiles every real cluster runs) the batch output equals
scalar_mapper.do_rule element-for-element; tests/test_xla_mapper.py
enforces this on randomized hierarchies.  Unsupported maps raise
UnsupportedMapError so callers can fall back to the scalar path.

straw2 draws need 64-bit integers: importing this module enables
jax_enable_x64 (all other ceph_tpu kernels pin their dtypes explicitly).
"""
from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from ..common.op_tracker import mark_active as _mark_active  # noqa: E402
from ..common.options import config as _config  # noqa: E402
from ..common.perf_counters import perf as _perf  # noqa: E402
from ..ops import hashing  # noqa: E402
from . import lntable  # noqa: E402
from .crush_map import (  # noqa: E402
    BUCKET_LIST, BUCKET_STRAW, BUCKET_STRAW2, BUCKET_TREE, BUCKET_UNIFORM,
    ITEM_NONE, ITEM_UNDEF,
    RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP, RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP, RULE_EMIT, RULE_SET_CHOOSELEAF_STABLE,
    RULE_SET_CHOOSELEAF_TRIES, RULE_SET_CHOOSELEAF_VARY_R,
    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES, RULE_SET_CHOOSE_LOCAL_TRIES,
    RULE_SET_CHOOSE_TRIES, RULE_TAKE, CrushMap,
)

S64_MIN = lntable.S64_MIN


class UnsupportedMapError(Exception):
    """Map/rule uses features outside the vectorized subset."""


# ---------------------------------------------------------------- compile --

@dataclass(frozen=True)
class CompiledMap:
    """Dense, device-ready view of a CrushMap (all 5 bucket algs)."""
    items: np.ndarray        # i32 [B, S] child ids (pad 0)
    hash_ids: np.ndarray     # i32 [B, S] ids hashed by straw2 (choose_args)
    weight_sets: np.ndarray  # i32 [B, P, S] per-position weights
    sizes: np.ndarray        # i32 [B]
    types: np.ndarray        # i32 [B]
    algs: np.ndarray         # i32 [B] bucket algorithm
    bucket_ids: np.ndarray   # i32 [B] original (negative) bucket ids
    sum_weights: np.ndarray  # i64 [B, S]  LIST prefix sums (u32 values)
    straws: np.ndarray       # i64 [B, S]  STRAW v1 scalers (u32 values)
    node_weights: np.ndarray  # i64 [B, 2S] TREE interior-node weights
    num_nodes: np.ndarray    # i32 [B]
    n_buckets: int
    max_size: int
    n_positions: int
    max_devices: int
    max_depth: int
    all_straw2: bool

    def tables(self, strategy: str) -> "DeviceTables":
        return DeviceTables(self, strategy)


def compile_map(cmap: CrushMap, choose_args_key: object = None,
                n_positions: int = 1) -> CompiledMap:
    """Flatten the bucket hierarchy to padded arrays.

    Raises UnsupportedMapError for non-straw2 buckets or legacy local-retry
    tunables (the scalar mapper covers those).
    """
    t = cmap.tunables
    if t.choose_local_tries or t.choose_local_fallback_tries:
        raise UnsupportedMapError(
            "legacy local-retry tunables not vectorized (argonaut profile)")
    B = cmap.max_buckets
    if B == 0:
        raise UnsupportedMapError("map has no buckets")
    S = 1
    all_straw2 = True
    for b in cmap.buckets:
        if b is None:
            continue
        if b.alg not in (BUCKET_UNIFORM, BUCKET_LIST, BUCKET_TREE,
                         BUCKET_STRAW, BUCKET_STRAW2):
            raise UnsupportedMapError(
                f"bucket {b.id}: unknown algorithm {b.alg}")
        if b.alg != BUCKET_STRAW2:
            all_straw2 = False
        S = max(S, b.size)
        if b.alg == BUCKET_TREE and b.num_nodes:
            S = max(S, (b.num_nodes + 1) // 2)
    choose_args = cmap.choose_args.get(choose_args_key) \
        if choose_args_key is not None else None
    P = 1
    if choose_args is not None:
        for a in choose_args:
            if a is not None and a.weight_set is not None:
                P = max(P, len(a.weight_set))
    P = max(P, n_positions if choose_args is not None else 1)

    items = np.zeros((B, S), dtype=np.int32)
    hash_ids = np.zeros((B, S), dtype=np.int32)
    ws = np.zeros((B, P, S), dtype=np.int32)
    sizes = np.zeros(B, dtype=np.int32)
    types = np.zeros(B, dtype=np.int32)
    algs = np.full(B, BUCKET_STRAW2, dtype=np.int32)
    bucket_ids = np.zeros(B, dtype=np.int32)
    # u32 in the reference (crush_bucket_list::sum_weights,
    # crush_bucket_straw::straws); kept as int64 holding the mod-2^32
    # value so prefix sums >= 2^31 neither overflow the table dtype nor
    # lose the reference's u32 wrap semantics
    sum_weights = np.zeros((B, S), dtype=np.int64)
    straws = np.zeros((B, S), dtype=np.int64)
    node_weights = np.zeros((B, 2 * S), dtype=np.int64)
    num_nodes = np.zeros(B, dtype=np.int32)
    for idx, b in enumerate(cmap.buckets):
        if b is None:
            continue
        n = b.size
        sizes[idx] = n
        types[idx] = b.type
        algs[idx] = b.alg
        bucket_ids[idx] = b.id
        items[idx, :n] = b.items
        hash_ids[idx, :n] = b.items
        w_row = ([b.weights[0]] * n if b.alg == BUCKET_UNIFORM and
                 len(b.weights) == 1 and n > 1 else b.weights[:n])
        for p in range(P):
            ws[idx, p, :len(w_row)] = w_row
        if b.alg == BUCKET_LIST and b.sum_weights:
            sum_weights[idx, :n] = [w & 0xFFFFFFFF for w in b.sum_weights]
        if b.alg == BUCKET_STRAW and b.straws:
            straws[idx, :n] = [w & 0xFFFFFFFF for w in b.straws]
        if b.alg == BUCKET_TREE and b.node_weights:
            node_weights[idx, :len(b.node_weights)] = b.node_weights
            num_nodes[idx] = b.num_nodes
        if choose_args is not None and b.alg == BUCKET_STRAW2:
            # choose_args are consumed ONLY by straw2 selection
            # (mapper.c:309-326 via bucket_straw2_choose); legacy algs
            # keep their native weights, matching the scalar oracle
            arg = choose_args[idx] if idx < len(choose_args) else None
            if arg is not None:
                if arg.ids is not None:
                    hash_ids[idx, :n] = arg.ids
                if arg.weight_set is not None:
                    for p in range(P):
                        src = arg.weight_set[min(p, len(arg.weight_set) - 1)]
                        ws[idx, p, :n] = src

    # max descent depth: longest bucket→bucket chain + 1
    depth = np.ones(B, dtype=np.int64)
    # iterate to fixed point (hierarchies are DAG-ish and shallow)
    for _ in range(B):
        changed = False
        for idx, b in enumerate(cmap.buckets):
            if b is None:
                continue
            for it in b.items:
                if it < 0:
                    child = -1 - it
                    if child < B and depth[child] + 1 > depth[idx]:
                        depth[idx] = depth[child] + 1
                        changed = True
        if not changed:
            break
    return CompiledMap(
        items=items, hash_ids=hash_ids, weight_sets=ws, sizes=sizes,
        types=types, algs=algs, bucket_ids=bucket_ids,
        sum_weights=sum_weights, straws=straws,
        node_weights=node_weights, num_nodes=num_nodes,
        n_buckets=B, max_size=S, n_positions=P,
        max_devices=max(cmap.max_devices, 1), max_depth=int(depth.max()),
        all_straw2=all_straw2)


# ------------------------------------------------------------- primitives --

_LN_TABLES = os.path.join(os.path.dirname(__file__), "data",
                          "crush_ln_tables.npz")
LN_SHIFT_F = float(lntable.LN_SHIFT)            # 2^48
_2P24 = 16777216.0
_2P44 = 17592186044416.0


class DeviceTables:
    """Trace-time table-access layer for the vectorized mapper.

    Two bit-identical lookup strategies, chosen per backend:

      * 'gather' — direct row indexing.  Fast on CPU; on TPU XLA lowers
        these gathers to serial per-element loops (~0.1 G elem/s measured
        on v5e), which caps the whole mapper.
      * 'onehot' — every table row/LUT read becomes a one-hot matmul that
        rides the MXU.  crush_ln is re-derived EXACTLY from the two small
        reference tables (__RH_LH_tbl/__LL_tbl, src/crush/crush_ln_table.h)
        with 8-bit-limb integer arithmetic: one-hot(bf16) @ limb tables →
        int32 carry chains → f64 combine; verified equal to the 65536-entry
        LUT for every u.  Weights split into 16-bit halves so f32 one-hot
        products stay exact.
    """

    def __init__(self, cm: CompiledMap, strategy: str):
        self.cm = cm
        self.strategy = strategy
        self.B, self.S, self.P = cm.n_buckets, cm.max_size, cm.n_positions
        self.items = jnp.asarray(cm.items)
        self.sizes = jnp.asarray(cm.sizes)
        self.types = jnp.asarray(cm.types)
        if strategy == "gather":
            self.hash_ids = jnp.asarray(cm.hash_ids)
            self.weight_sets = jnp.asarray(cm.weight_sets)
            self.numer_lut = jnp.asarray(
                (-lntable.straw2_ln_lut()).astype(np.float64))
            if not cm.all_straw2:
                self.algs = jnp.asarray(cm.algs)
                self.bucket_ids = jnp.asarray(
                    cm.bucket_ids.astype(np.uint32))
                self.sum_weights = jnp.asarray(cm.sum_weights)
                self.straws = jnp.asarray(cm.straws)
                self.node_weights = jnp.asarray(cm.node_weights)
                self.num_nodes = jnp.asarray(cm.num_nodes)
            return
        if not cm.all_straw2:
            raise UnsupportedMapError(
                "onehot strategy vectorizes straw2 buckets only; "
                "legacy algs use the gather tables")
        if cm.max_devices >= (1 << 24):
            raise UnsupportedMapError(
                "onehot strategy requires device ids < 2^24 (f32-exact)")
        # every value that round-trips through an f32 one-hot matmul must
        # be f32-exact, including choose_args id overrides and child ids
        for name, arr in (("hash_ids", cm.hash_ids), ("items", cm.items)):
            if np.abs(arr.astype(np.int64)).max(initial=0) >= (1 << 24):
                raise UnsupportedMapError(
                    f"onehot strategy requires |{name}| < 2^24 (f32-exact)")
        self.items_f = jnp.asarray(cm.items.astype(np.float32))
        self.ids_f = jnp.asarray(cm.hash_ids.astype(np.float32))
        self.ws_hi = jnp.asarray(
            (cm.weight_sets >> 16).astype(np.float32))          # [B,P,S]
        self.ws_lo = jnp.asarray(
            (cm.weight_sets & 0xFFFF).astype(np.float32))
        self.sizes_f = jnp.asarray(cm.sizes.astype(np.float32))
        self.types_f = jnp.asarray(cm.types.astype(np.float32))
        d = np.load(_LN_TABLES)
        rh_lh = d["rh_lh"].astype(np.int64)
        ll = d["ll"].astype(np.int64)
        rh, lh = rh_lh[0:258:2], rh_lh[1:258:2]     # 129 entries each

        def limbs(v, n):
            return np.stack([(v >> (8 * j)) & 0xFF for j in range(n)], 1)

        # RH needs 7 limbs: RH[0] == 2^48 exactly
        self.t129 = jnp.asarray(np.concatenate(
            [limbs(rh, 7), limbs(lh, 6)], 1).astype(jnp.bfloat16))
        self.t256 = jnp.asarray(limbs(ll, 6).astype(jnp.bfloat16))

    # ---- per-lane accessors (called under vmap; bidx is a scalar) -------
    def bucket_onehot(self, bidx):
        return (jnp.arange(self.B, dtype=jnp.int32) == bidx) \
            .astype(jnp.float32)

    def bucket_row(self, bidx, pos):
        """(items [S] i32, hash_ids [S] u32, weights [S] f64, size i32)."""
        if self.strategy == "gather":
            pos_c = jnp.minimum(pos, self.P - 1)
            return (self.items[bidx],
                    self.hash_ids[bidx].astype(jnp.uint32),
                    self.weight_sets[bidx, pos_c].astype(jnp.float64),
                    self.sizes[bidx])
        ohb = self.bucket_onehot(bidx)                          # [B]
        items = (ohb @ self.items_f).astype(jnp.int32)          # [S]
        ids = (ohb @ self.ids_f).astype(jnp.int32).astype(jnp.uint32)
        w_hi = jnp.einsum("b,bps->ps", ohb, self.ws_hi)         # [P,S]
        w_lo = jnp.einsum("b,bps->ps", ohb, self.ws_lo)
        pos_c = jnp.minimum(pos, self.P - 1)
        psel = (jnp.arange(self.P, dtype=jnp.int32) == pos_c) \
            .astype(jnp.float64)
        w = psel @ (w_hi.astype(jnp.float64) * 65536.0 +
                    w_lo.astype(jnp.float64))                   # [S]
        size = (ohb @ self.sizes_f).astype(jnp.int32)
        return items, ids, w, size

    def bucket_type(self, bidx):
        if self.strategy == "gather":
            return self.types[jnp.clip(bidx, 0, self.B - 1)]
        ohb = self.bucket_onehot(jnp.clip(bidx, 0, self.B - 1))
        return (ohb @ self.types_f).astype(jnp.int32)

    def bucket_size(self, bidx):
        if self.strategy == "gather":
            return self.sizes[bidx]
        return (self.bucket_onehot(bidx) @ self.sizes_f).astype(jnp.int32)

    def item_at(self, items_row, idx):
        """items_row[idx] without a gather."""
        if self.strategy == "gather":
            return items_row[idx]
        sel = (jnp.arange(self.S, dtype=jnp.int32) == idx)
        return jnp.where(sel, items_row, 0).sum(dtype=jnp.int32)

    # ---- exact draw numerator: 2^48 - crush_ln(u) -----------------------
    def ln_numer(self, u):
        """u [S] u16 → positive f64 numerator, bit-exact vs the LUT."""
        if self.strategy == "gather":
            return self.numer_lut[u.astype(jnp.int32)]
        x = u.astype(jnp.int32) + 1
        e = (jax.lax.bitcast_convert_type(
            x.astype(jnp.float32), jnp.int32) >> 23) - 127
        bits = jnp.where((x & 0x18000) == 0, 15 - e, 0)
        xs = x << bits
        iexpon = 15 - bits
        k = (xs >> 8) - 128                                    # 0..128
        oh1 = (k[..., None] == jnp.arange(129, dtype=jnp.int32)
               ).astype(jnp.bfloat16)
        L1 = jnp.einsum("...k,kc->...c", oh1, self.t129,
                        preferred_element_type=jnp.float32).astype(jnp.int32)
        t = xs * L1[..., 0]
        for j in range(1, 7):                                  # carry chain
            t = xs * L1[..., j] + (t >> 8)
        idx2 = t & 0xFF
        oh2 = (idx2[..., None] == jnp.arange(256, dtype=jnp.int32)
               ).astype(jnp.bfloat16)
        L2 = jnp.einsum("...k,kc->...c", oh2, self.t256,
                        preferred_element_type=jnp.float32).astype(jnp.int32)
        c = 0
        out = []
        for j in range(6):                                     # LH + LL
            s = L1[..., 7 + j] + L2[..., j] + c
            out.append(s & 0xFF)
            c = s >> 8
        v_lo = out[0] | (out[1] << 8) | (out[2] << 16)
        v_hi = out[3] | (out[4] << 8) | (out[5] << 16)
        v = v_hi.astype(jnp.float64) * _2P24 + v_lo.astype(jnp.float64)
        result = iexpon.astype(jnp.float64) * _2P44 + jnp.floor(v / 16.0)
        return LN_SHIFT_F - result


def _u32(v):
    return jnp.asarray(v).astype(jnp.uint32)


def _straw2_choose(dt: DeviceTables, bidx, x, r, pos):
    """One straw2 selection (mapper.c:361-384): returns chosen child id.

    The reference draw is trunc_div(crush_ln(u) - 2^48, weight) maximized
    with first-index tie-break.  Negating, that is q = (-ln) // w
    MINIMIZED with first-index tie-break.  q is computed in float64:
    the dividend is < 2^48 (exact), the quotient is corrected by one ulp
    step each way, and products stay < 2^53, so q is the exact integer
    quotient — bit-identical to the reference's div64_s64 — without any
    TPU-emulated 64-bit integer ops.
    """
    S = dt.S
    items_row, ids, w, size = dt.bucket_row(bidx, pos)
    u = hashing.jx_hash3(
        jnp.broadcast_to(_u32(x), (S,)), ids,
        jnp.broadcast_to(_u32(r), (S,))) & jnp.uint32(0xFFFF)
    a = dt.ln_numer(u)                                 # [S] f64, 0..2^48
    q = jnp.floor(a / jnp.maximum(w, 1.0))
    q = q - (q * w > a)                                # exactness corrections
    q = q + ((q + 1.0) * w <= a)
    inf = jnp.float64(jnp.inf)
    q = jnp.where(w > 0, q, inf)
    q = jnp.where(jnp.arange(S, dtype=jnp.int32) < size, q, inf)
    return dt.item_at(items_row, jnp.argmin(q))


def _uniform_choose(dt: DeviceTables, bidx, x, r):
    """bucket_perm_choose (mapper.c:74-133): the r-th element of an
    incrementally built pseudo-random permutation.  The cross-call perm
    cache reconstructs as a pure function of (x, r): starting from the
    identity, step p swaps perm[p] with perm[p + hash(x,id,p) %% (n-p)]
    for p = 0..pr-1 (the pr==0 shortcut and its 0xFFFF expansion
    produce exactly this state, verified against the scalar oracle)."""
    S = dt.S
    n = jnp.maximum(dt.bucket_size(bidx), 1)
    bid = dt.bucket_ids[bidx]
    pr = _u32(r).astype(jnp.int32) % n

    # the reference's while loop runs steps p = 0..pr INCLUSIVE
    # (while perm_n <= pr), and the pr==0 shortcut + its 0xFFFF
    # expansion reduce to exactly step p=0, so one loop covers all
    def step(p, perm):
        gap = jnp.maximum(n - p, 1)
        i = (hashing.jx_hash3(_u32(x), bid, _u32(p)) % _u32(gap)) \
            .astype(jnp.int32)
        do = (p < n - 1) & (i != 0)
        pi = perm[jnp.clip(p, 0, S - 1)]
        pj = perm[jnp.clip(p + i, 0, S - 1)]
        perm = perm.at[jnp.clip(p, 0, S - 1)].set(
            jnp.where(do, pj, pi))
        perm = perm.at[jnp.clip(p + i, 0, S - 1)].set(
            jnp.where(do, pi, pj))
        return perm

    perm = lax.fori_loop(0, pr + 1, step,
                         jnp.arange(S, dtype=jnp.int32))
    items_row, _, _, _ = dt.bucket_row(bidx, jnp.int32(0))
    return dt.item_at(items_row, jnp.clip(perm[jnp.clip(pr, 0, S - 1)],
                                          0, S - 1))


def _list_choose(dt: DeviceTables, bidx, x, r):
    """bucket_list_choose (mapper.c:139-160): scan from the list tail;
    take the highest index whose 16-bit draw scaled by the prefix sum
    undercuts the item weight, else items[0]."""
    S = dt.S
    items_row, _, w, size = dt.bucket_row(bidx, jnp.int32(0))
    sums = dt.sum_weights[bidx].astype(jnp.int64)
    h = hashing.jx_hash4(
        jnp.broadcast_to(_u32(x), (S,)),
        items_row.astype(jnp.uint32),
        jnp.broadcast_to(_u32(r), (S,)),
        jnp.broadcast_to(dt.bucket_ids[bidx], (S,))) & jnp.uint32(0xFFFF)
    draw = (h.astype(jnp.int64) * sums) >> 16
    ok = (draw < w.astype(jnp.int64)) & (jnp.arange(S, dtype=jnp.int32) < size)
    idx = jnp.max(jnp.where(ok, jnp.arange(S, dtype=jnp.int32), -1))
    return dt.item_at(items_row, jnp.maximum(idx, 0))


def _tree_choose(dt: DeviceTables, bidx, x, r):
    """bucket_tree_choose (mapper.c:180-219): descend the interior
    weight tree; at node n draw 32.32-scaled t against the left child's
    weight."""
    nw = dt.node_weights[bidx]
    n0 = (dt.num_nodes[bidx] >> 1).astype(jnp.int32)
    NW = nw.shape[0]
    bid = dt.bucket_ids[bidx]

    def height(n):
        # trailing zeros of n (n > 0, n < 2S)
        h = jnp.int32(0)
        m = n

        def hb(i, carry):
            h, m = carry
            is_even = (m & 1) == 0
            return (jnp.where(is_even, h + 1, h),
                    jnp.where(is_even, m >> 1, m))
        bits = max(1, NW.bit_length())
        h, m = lax.fori_loop(0, bits, hb, (h, m))
        return h

    def cond(n):
        return (n & 1) == 0

    def body(n):
        # the 32.32 draw is u64 in the reference (bucket_tree_choose,
        # mapper.c:180-219): hash (< 2^32) * node weight overflows
        # SIGNED int64 once a node weight reaches 2^31, so the multiply,
        # shift and left-weight compare all stay in uint64
        w = nw[jnp.clip(n, 0, NW - 1)].astype(jnp.uint64)
        t = (hashing.jx_hash4(_u32(x), _u32(n), _u32(r), bid)
             .astype(jnp.uint64) * w) >> jnp.uint64(32)
        h = height(n)
        step = jnp.int32(1) << jnp.maximum(h - 1, 0)
        left = n - step
        right = n + step
        lw = nw[jnp.clip(left, 0, NW - 1)].astype(jnp.uint64)
        return jnp.where(t < lw, left, right)

    n = lax.while_loop(cond, body, n0)
    items_row, _, _, _ = dt.bucket_row(bidx, jnp.int32(0))
    return dt.item_at(items_row, jnp.clip(n >> 1, 0, dt.S - 1))


def _straw_choose(dt: DeviceTables, bidx, x, r):
    """bucket_straw_choose (mapper.c:224-241): 16-bit draw times the
    precomputed straw scaler, argmax with first-index tie-break."""
    S = dt.S
    items_row, _, _, size = dt.bucket_row(bidx, jnp.int32(0))
    straws = dt.straws[bidx].astype(jnp.int64)
    h = hashing.jx_hash3(
        jnp.broadcast_to(_u32(x), (S,)),
        items_row.astype(jnp.uint32),
        jnp.broadcast_to(_u32(r), (S,))) & jnp.uint32(0xFFFF)
    draw = h.astype(jnp.int64) * straws            # <= 2^48, exact
    draw = jnp.where(jnp.arange(S, dtype=jnp.int32) < size,
                     draw, jnp.int64(-1))
    return dt.item_at(items_row, jnp.argmax(draw))


def _bucket_choose(dt: DeviceTables, bidx, x, r, pos):
    """Per-algorithm dispatch (crush_bucket_choose, mapper.c:387-418).
    Static fast path when the whole map is straw2 (no switch emitted)."""
    if dt.cm.all_straw2:
        return _straw2_choose(dt, bidx, x, r, pos)
    alg = dt.algs[bidx]
    branches = [
        lambda: _uniform_choose(dt, bidx, x, r),       # BUCKET_UNIFORM=1
        lambda: _list_choose(dt, bidx, x, r),          # BUCKET_LIST=2
        lambda: _tree_choose(dt, bidx, x, r),          # BUCKET_TREE=3
        lambda: _straw_choose(dt, bidx, x, r),         # BUCKET_STRAW=4
        lambda: _straw2_choose(dt, bidx, x, r, pos),   # BUCKET_STRAW2=5
    ]
    return lax.switch(jnp.clip(alg - 1, 0, 4),
                      [lambda _, f=f: f() for f in branches], 0)


def _is_out(weights, item, x):
    """Device overload rejection (mapper.c:424-438); item must be >= 0."""
    n = weights.shape[0]
    w = weights[jnp.clip(item, 0, n - 1)].astype(jnp.int64)
    oob = item >= n
    hashed = (hashing.jx_hash2(_u32(x), _u32(item)) &
              jnp.uint32(0xFFFF)).astype(jnp.int64) >= w
    return oob | jnp.where(w >= 0x10000, False,
                           jnp.where(w == 0, True, hashed))


# descend outcome codes
_OK, _REJECT, _SKIP = 0, 1, 2


def _descend(cm: CompiledMap, dt: DeviceTables, start_bidx,
             target_type: int, x, r, pos):
    """Walk from bucket index down to an item of target_type.

    Mirrors the inner retry_bucket walk of mapper.c:495-546 for straw2:
    returns (item, status) with status OK (item has target type), REJECT
    (empty bucket on the path → costs a retry), or SKIP (escaped the map →
    abandon this replica slot).
    """

    def body(carry, _):
        cur, done, status, result = carry
        empty = dt.bucket_size(cur) == 0
        item = _bucket_choose(dt, cur, x, r, pos)
        is_dev = item >= 0
        bad_dev = is_dev & (item >= cm.max_devices)
        bidx = jnp.where(is_dev, 0, -1 - item)
        bad_bucket = (~is_dev) & (bidx >= cm.n_buckets)
        itype = jnp.where(is_dev, 0, dt.bucket_type(bidx))
        match = itype == target_type
        # classify this level's outcome (only if not already done)
        lvl_reject = empty
        lvl_skip = (~empty) & (bad_dev |
                               ((~match) & (is_dev | bad_bucket)))
        lvl_done = lvl_reject | lvl_skip | ((~empty) & match)
        new_status = jnp.where(
            done, status,
            jnp.where(lvl_reject, _REJECT,
                      jnp.where(lvl_skip, _SKIP, _OK)))
        new_result = jnp.where(done | ~match | empty, result, item)
        new_done = done | lvl_done
        new_cur = jnp.where(new_done, cur, bidx)
        return (new_cur, new_done, new_status, new_result), None

    init = (start_bidx, jnp.asarray(False), jnp.int32(_REJECT),
            jnp.int32(ITEM_NONE))
    (cur, done, status, result), _ = lax.scan(
        body, init, None, length=cm.max_depth)
    # not terminating within max_depth == malformed map → treat as SKIP
    status = jnp.where(done, status, _SKIP)
    return result, status


# --------------------------------------------------------------- firstn ----

def _leaf_firstn(cm, dt, bucket_item, weights, x, sub_r, recurse_tries,
                 stable, out2, outpos, pos):
    """The chooseleaf recursion (mapper.c:564-581 → recursive
    crush_choose_firstn with numrep=1): pick one device inside
    ``bucket_item``'s subtree, with collision checks against out2[:outpos].
    Returns (device, ok)."""
    rep_base = jnp.int32(0) if stable else outpos
    R = out2.shape[0]

    def cond(s):
        ftotal, done, ok, dev = s
        return (~done) & (ftotal < recurse_tries)

    def body(s):
        ftotal, done, ok, dev = s
        r = rep_base + sub_r + ftotal
        item, status = _descend(cm, dt, -1 - bucket_item, 0, x, r, pos)
        collide = jnp.any((jnp.arange(R, dtype=jnp.int32) <
                           outpos) & (out2 == item))
        out_dev = jnp.where(status == _OK, _is_out(weights, item, x), False)
        success = (status == _OK) & (~collide) & (~out_dev)
        hard_fail = status == _SKIP
        return (ftotal + 1, success | hard_fail, success,
                jnp.where(success, item, dev))

    init = (jnp.int32(0), jnp.asarray(False), jnp.asarray(False),
            jnp.int32(ITEM_NONE))
    _, _, ok, dev = lax.while_loop(cond, body, init)
    return dev, ok


def _choose_firstn(cm, dt, root_item, target_type: int, numrep: int,
                   recurse_to_leaf: bool, tries: int, recurse_tries: int,
                   vary_r: int, stable: bool, weights, x, count_limit):
    """crush_choose_firstn (mapper.c:460-648) for one x, modern tunables.

    root_item: bucket id (negative, traced).  Returns (out, out2, outpos):
    out/out2 are [numrep] i32 padded with ITEM_NONE.
    """
    R = numrep
    out = jnp.full((R,), ITEM_NONE, dtype=jnp.int32)
    out2 = jnp.full((R,), ITEM_NONE, dtype=jnp.int32)
    outpos = jnp.int32(0)

    for rep in range(numrep):  # static unroll; mapper.c:478 rep loop
        def cond(s):
            ftotal, placed, skipped, item, leaf = s
            return (~placed) & (~skipped) & (ftotal < tries)

        def body(s, rep=rep):
            ftotal, placed, skipped, item_prev, leaf_prev = s
            r = rep + ftotal  # parent_r == 0 at rule level
            item, status = _descend(
                cm, dt, -1 - root_item, target_type, x, r, outpos)
            collide = jnp.any((jnp.arange(R, dtype=jnp.int32) <
                               outpos) & (out == item))
            reject = status == _REJECT
            skip = status == _SKIP
            leaf = jnp.int32(ITEM_NONE)
            if recurse_to_leaf:
                sub_r = (r >> (vary_r - 1)) if vary_r else jnp.int32(0)
                is_bucket = item < 0
                leaf_dev, leaf_ok = _leaf_firstn(
                    cm, dt, jnp.where(is_bucket, item, -1), weights, x,
                    sub_r, recurse_tries, stable, out2, outpos, outpos)
                # device-typed direct hit keeps itself as leaf
                leaf = jnp.where(is_bucket, leaf_dev, item)
                reject = reject | (
                    (status == _OK) & (~collide) & is_bucket & (~leaf_ok))
            if target_type == 0:
                reject = reject | jnp.where(
                    (status == _OK) & (~collide),
                    _is_out(weights, item, x), False)
            ok = (status == _OK) & (~collide) & (~reject)
            fail = (~ok) & (~skip)
            return (ftotal + jnp.where(fail, 1, 0),
                    placed | ok, skipped | skip,
                    jnp.where(ok, item, item_prev),
                    jnp.where(ok, leaf, leaf_prev))

        init = (jnp.int32(0), jnp.asarray(False), jnp.asarray(False),
                jnp.int32(ITEM_NONE), jnp.int32(ITEM_NONE))
        ftotal, placed, skipped, item, leaf = lax.while_loop(
            cond, body, init)
        placed = placed & (outpos < count_limit)
        out = jnp.where(placed, out.at[outpos].set(item), out)
        if recurse_to_leaf:
            out2 = jnp.where(placed, out2.at[outpos].set(leaf), out2)
        outpos = outpos + jnp.where(placed, 1, 0)
    return out, out2, outpos


# ---------------------------------------------------------------- indep ----

def _leaf_indep(cm, dt, bucket_item, weights, x, parent_r, rep,
                numrep: int, recurse_tries: int, pos):
    """Leaf recursion of crush_choose_indep (mapper.c:777-792): one device
    in the subtree, positionally stable; no collision window (the recursion
    window is a single slot).  Returns device or ITEM_NONE."""
    def cond(s):
        ftotal, done, dev = s
        return (~done) & (ftotal < recurse_tries)

    def body(s):
        ftotal, done, dev = s
        r = rep + parent_r + numrep * ftotal
        item, status = _descend(cm, dt, -1 - bucket_item, 0, x, r, pos)
        out_dev = jnp.where(status == _OK, _is_out(weights, item, x), False)
        success = (status == _OK) & (~out_dev)
        hard_fail = status == _SKIP
        return (ftotal + 1, success | hard_fail,
                jnp.where(success, item, dev))

    init = (jnp.int32(0), jnp.asarray(False), jnp.int32(ITEM_NONE))
    _, _, dev = lax.while_loop(cond, body, init)
    return dev


def _choose_indep(cm, dt, root_item, target_type: int, numrep: int,
                  recurse_to_leaf: bool, tries: int, recurse_tries: int,
                  weights, x, out_size_limit):
    """crush_choose_indep (mapper.c:655-843) for one x: breadth-first,
    positionally stable; failed slots become ITEM_NONE."""
    R = numrep
    UNDEF = jnp.int32(ITEM_UNDEF)
    NONE = jnp.int32(ITEM_NONE)
    active = jnp.arange(R, dtype=jnp.int32) < out_size_limit
    out = jnp.where(active, UNDEF, NONE)
    out2 = jnp.where(active, UNDEF, NONE)

    def round_body(s):
        ftotal, out, out2 = s
        for rep in range(R):  # static; collision sees earlier same-round reps
            pending = active[rep] & (out[rep] == UNDEF)
            r = rep + numrep * ftotal
            # choose_args weight-set position is outpos (0 at rule level),
            # NOT rep: crush_choose_indep passes outpos down to
            # bucket_choose (mapper.c:655-843); only the leaf recursion
            # uses rep as its outpos.
            item, status = _descend(
                cm, dt, -1 - root_item, target_type, x, r, jnp.int32(0))
            collide = jnp.any(out == item)
            hard = status == _SKIP
            leaf = NONE
            if recurse_to_leaf:
                is_bucket = item < 0
                leaf_dev = _leaf_indep(
                    cm, dt, jnp.where(is_bucket, item, -1), weights, x,
                    r, rep, numrep, recurse_tries, rep)
                leaf = jnp.where(is_bucket, leaf_dev, item)
                leaf_fail = is_bucket & (leaf_dev == NONE)
            else:
                leaf_fail = jnp.asarray(False)
            out_dev = jnp.where(
                (status == _OK) & (target_type == 0),
                _is_out(weights, item, x), False)
            ok = (status == _OK) & ~collide & ~leaf_fail & ~out_dev
            place = pending & ok
            out = jnp.where(place, out.at[rep].set(item), out)
            if recurse_to_leaf:
                out2 = jnp.where(place, out2.at[rep].set(leaf), out2)
            # hard failure pins the slot to NONE permanently
            pin = pending & hard & ~ok
            out = jnp.where(pin, out.at[rep].set(NONE), out)
            out2 = jnp.where(pin & recurse_to_leaf,
                             out2.at[rep].set(NONE), out2)
        return (ftotal + 1, out, out2)

    def round_cond(s):
        ftotal, out, out2 = s
        return (ftotal < tries) & jnp.any(out == UNDEF)

    _, out, out2 = lax.while_loop(
        round_cond, round_body, (jnp.int32(0), out, out2))
    out = jnp.where(out == UNDEF, NONE, out)
    out2 = jnp.where(out2 == UNDEF, NONE, out2)
    return out, out2


# ------------------------------------------------------------- rule driver --

class XlaMapper:
    """Compiled batched do_rule for one CrushMap.

    Usage::

        mapper = XlaMapper(cmap)
        osds = mapper.map_batch(ruleno, xs, result_max, weights)  # [N, R]

    ``weights`` is the device in/out vector ([max_devices] 16.16 fixed,
    like the reference's __u32 *weight argument); results are padded with
    ITEM_NONE.  One XLA compilation per (ruleno, result_max).
    """

    def __init__(self, cmap: CrushMap, choose_args_key: object = None,
                 n_positions: int = 8, strategy: Optional[str] = None,
                 fast: Optional[bool] = None):
        self.cmap = cmap
        self.choose_args_key = choose_args_key
        self.compiled = compile_map(cmap, choose_args_key, n_positions)
        if fast is None:
            fast = bool(_config().get("fastmap_enabled"))
        self._fast_enabled = fast
        self._fast = None                 # lazy FastMapper
        self._fast_unsupported = set()    # rule keys outside fast subset
        self._exact_fallback = None       # lazy NativeMapper/scalar fn
        auto = False
        if strategy is None:
            cfg = _config().get("lookup_strategy")
            strategy = None if cfg == "auto" else cfg
        if strategy is None:
            # one-hot matmul lookups on real accelerators; row gathers on
            # CPU where XLA lowers them efficiently
            auto = True
            platform = jax.devices()[0].platform
            strategy = "gather" if platform == "cpu" else "onehot"
        if strategy not in ("gather", "onehot"):
            raise ValueError(
                f"lookup strategy must be gather|onehot, got {strategy!r}")
        # tables materialized OUTSIDE any jit trace (constants created
        # inside a trace leak as tracers through the cache)
        try:
            self.tables = self.compiled.tables(strategy)
        except UnsupportedMapError:
            if not auto:
                raise
            # auto-selected onehot but ids exceed f32-exact range
            self.tables = self.compiled.tables("gather")
        self._jitted = {}

    # -- trace-time rule interpretation (steps are static data) ------------
    def _trace_rule(self, ruleno: int, result_max: int, xs, weights):
        cmap, cm = self.cmap, self.compiled
        rule = cmap.rules[ruleno]
        t = cmap.tunables
        dt = self.tables

        choose_tries = t.choose_total_tries + 1
        choose_leaf_tries = 0
        vary_r = t.chooseleaf_vary_r
        stable = bool(t.chooseleaf_stable)

        def per_x(x, weights):
            result = jnp.full((result_max,), ITEM_NONE, dtype=jnp.int32)
            rpos = jnp.int32(0)
            # working vector: static list of (kind, payload) sources
            sources: List = []   # each: dict(items=array [n] per-x, count)
            nonlocal choose_tries, choose_leaf_tries, vary_r, stable
            for op, arg1, arg2 in rule.steps:
                if op == RULE_TAKE:
                    ok = (0 <= arg1 < cmap.max_devices) or \
                        (cmap.bucket(arg1) is not None)
                    if ok:
                        sources = [dict(
                            items=jnp.full((1,), arg1, dtype=jnp.int32),
                            count=jnp.int32(1))]
                    else:
                        sources = []
                elif op == RULE_SET_CHOOSE_TRIES:
                    if arg1 > 0:
                        choose_tries = arg1
                elif op == RULE_SET_CHOOSELEAF_TRIES:
                    if arg1 > 0:
                        choose_leaf_tries = arg1
                elif op == RULE_SET_CHOOSE_LOCAL_TRIES:
                    if arg1 > 0:
                        raise UnsupportedMapError("local_tries rule step")
                elif op == RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
                    if arg1 > 0:
                        raise UnsupportedMapError("local_fallback rule step")
                elif op == RULE_SET_CHOOSELEAF_VARY_R:
                    if arg1 >= 0:
                        vary_r = arg1
                elif op == RULE_SET_CHOOSELEAF_STABLE:
                    if arg1 >= 0:
                        stable = bool(arg1)
                elif op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSELEAF_FIRSTN,
                            RULE_CHOOSE_INDEP, RULE_CHOOSELEAF_INDEP):
                    firstn = op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSELEAF_FIRSTN)
                    leaf = op in (RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP)
                    numrep = arg1
                    if numrep <= 0:
                        numrep += result_max
                        if numrep <= 0:
                            continue
                    if firstn:
                        if choose_leaf_tries:
                            recurse_tries = choose_leaf_tries
                        elif t.chooseleaf_descend_once:
                            recurse_tries = 1
                        else:
                            recurse_tries = choose_tries
                    else:
                        recurse_tries = choose_leaf_tries or 1
                    new_items = jnp.full((result_max,), ITEM_NONE,
                                         dtype=jnp.int32)
                    osize = jnp.int32(0)
                    for src in sources:
                        n_src = src["items"].shape[0]
                        for i in range(n_src):
                            live = (i < src["count"])
                            bid = src["items"][i]
                            is_bucket = bid < 0
                            root = jnp.where(is_bucket, bid, -1)
                            live = live & is_bucket
                            if firstn:
                                o, o2, got = _choose_firstn(
                                    cm, dt, root, arg2, numrep, leaf,
                                    choose_tries, recurse_tries, vary_r,
                                    stable, weights, x,
                                    count_limit=result_max - osize)
                            else:
                                o, o2 = _choose_indep(
                                    cm, dt, root, arg2, numrep, leaf,
                                    choose_tries, recurse_tries, weights, x,
                                    out_size_limit=jnp.minimum(
                                        numrep, result_max - osize))
                                got = jnp.minimum(numrep,
                                                  result_max - osize)
                            vals = o2 if leaf else o
                            idx = osize + jnp.arange(numrep, dtype=jnp.int32)
                            valid = live & (jnp.arange(
                                numrep, dtype=jnp.int32) < got)
                            idx = jnp.where(valid, idx, result_max)
                            new_items = new_items.at[idx].set(
                                jnp.where(valid, vals, ITEM_NONE),
                                mode="drop")
                            osize = osize + jnp.where(live, got, 0)
                    sources = [dict(items=new_items, count=osize)]
                elif op == RULE_EMIT:
                    for src in sources:
                        n_src = src["items"].shape[0]
                        take = jnp.minimum(src["count"], result_max - rpos)
                        idx = rpos + jnp.arange(n_src, dtype=jnp.int32)
                        valid = jnp.arange(n_src, dtype=jnp.int32) < take
                        idx = jnp.where(valid, idx, result_max)
                        result = result.at[idx].set(
                            jnp.where(valid, src["items"][:n_src],
                                      ITEM_NONE), mode="drop")
                        rpos = rpos + take
                    sources = []
            return result

        return jax.vmap(per_x, in_axes=(0, None))(xs, weights)

    # ----------------------------------------------------------- public ---
    def _get_jitted(self, ruleno: int, result_max: int, mesh=None):
        from ..parallel.mesh import mesh_cache_key
        key = (ruleno, result_max,
               mesh_cache_key(mesh) if mesh is not None else None)
        # compile-vs-cached tagged onto whatever client op triggered
        # this dispatch (a fresh executable is seconds of latency the
        # op's latency histogram must be able to explain)
        _mark_active("dispatched_device", component="crush.mapper",
                     compiled=key not in self._jitted)
        if key not in self._jitted:
            inner = functools.partial(self._trace_rule, ruleno, result_max)

            # one-hot table values reach 2^16; TPU DEFAULT matmuls run
            # bf16 on the MXU and round them (see fast_mapper._get_jitted)
            def fn(xs, weights):
                with jax.default_matmul_precision("highest"):
                    return inner(xs, weights)

            from ..common.jit_profile import wrap as _jit_wrap
            sig = f"rule{ruleno}:max{result_max}"
            if mesh is None:
                self._jitted[key] = _jit_wrap(
                    jax.jit(fn), "crush.mapper", sig)
            else:
                from ..parallel.mesh import lane_shardings
                batch, repl = lane_shardings(mesh)
                self._jitted[key] = _jit_wrap(
                    jax.jit(fn, in_shardings=(batch, repl),
                            out_shardings=batch),
                    "crush.mapper", f"{sig}:sharded")
        return self._jitted[key]


    def _exact_rows(self, ruleno: int, xs_rows, result_max: int, weights):
        """Bit-exact recompute for fallback lanes: the native C++
        interpreter when buildable, else the scalar oracle."""
        if self._exact_fallback is None:
            try:
                from ..native_bridge import NativeMapper
                nm = NativeMapper(self.cmap,
                                  choose_args_key=self.choose_args_key)
                self._exact_fallback = (
                    lambda rn, xr, rm, w: nm.map_batch(rn, xr, rm, w))
            except Exception:
                args = self.cmap.choose_args.get(self.choose_args_key) \
                    if self.choose_args_key is not None else None

                def scalar_rows(rn, xr, rm, w):
                    res = np.full((len(xr), rm), ITEM_NONE, dtype=np.int32)
                    for i, xv in enumerate(xr):
                        got = scalar_do_rule(self.cmap, rn, int(xv), rm,
                                             list(w), choose_args=args)
                        res[i, :len(got)] = got
                    return res

                from .scalar_mapper import do_rule as scalar_do_rule
                self._exact_fallback = scalar_rows
        return self._exact_fallback(ruleno, xs_rows, result_max, weights)

    def map_batch_delta(self, ruleno: int, xs, result_max: int,
                        old_weights, new_weights,
                        before: np.ndarray) -> np.ndarray:
        """Epoch-delta remap: O(changed) instead of O(all PGs) for
        MONOTONIC device-weight decreases — the mark-out/failure case
        that drives recovery (the reference pays the full
        OSDMapMapping sweep here, src/osd/OSDMapMapping.h:18;
        CrushTester.cc:612 loops every x).

        ``before`` is the cached full mapping under ``old_weights``
        (a live mon/mgr always holds the current epoch's mapping).
        Only rows whose mapping CONTAINS a changed device recompute;
        every other row provably keeps its result:

          * the crush map (bucket weights, items, choose_args) is
            unchanged, so every straw2 draw sequence is unchanged —
            each lane SELECTS the same item sequence at every bucket
            and retry step;
          * a lane that never ACCEPTED a changed device either never
            selected it (identical draws), or selected-and-REJECTED
            it: collision rejection is weight-independent, and the
            probabilistic is_out rejection (mapper.c:424-438,
            hash(x,d) & 0xffff >= w) is monotone — a weight that only
            DECREASES keeps every past rejection a rejection.  By
            induction the whole retry path, including exhausted
            (ITEM_NONE) slots, is bit-identical;
          * a lane that accepted a changed device is exactly a lane
            whose ``before`` row contains it.

        Weight INCREASES (revive/mark-in) can attract lanes that
        never probed the device, so there is no sound affected-set
        short of a sweep — those fall back to the full map_batch."""
        old = np.asarray(old_weights, dtype=np.int64)
        new = np.asarray(new_weights, dtype=np.int64)
        pc = _perf("crush.mapper")
        if (new > old).any():
            pc.inc("delta_full_fallbacks")
            return self.map_batch(ruleno, xs, result_max, new_weights)
        changed = np.flatnonzero(new != old)
        if not len(changed):
            return before.copy()
        affected = np.isin(before, changed).any(axis=1)
        rows = np.flatnonzero(affected)
        pc.inc("delta_calls")
        pc.inc("delta_affected_lanes", len(rows))
        out = before.copy()
        if len(rows):
            out[rows] = self.map_batch(
                ruleno, np.asarray(xs)[rows], result_max, new_weights)
        return out

    def map_batch(self, ruleno: int, xs, result_max: int,
                  weights: Sequence[int], mesh=None) -> np.ndarray:
        """[N] x values -> [N, result_max] i32 osd ids (ITEM_NONE padded).

        With ``mesh``, the x axis is sharded across the device mesh (the
        multi-chip ParallelPGMapper); N is padded to the mesh size.
        Mesh-shape agnostic: ``lane_shardings`` splits the batch over
        EVERY mesh axis row-major, so the 1-D shard ring and the 2-D
        (stripe, shard) plane run the same sweep bit-identically
        (asserted by dryrun_multichip's 2-D section).

        Dispatch: the level-synchronous FastMapper handles supported
        rules (with incomplete lanes recomputed bit-exactly host-side);
        rules outside its subset run the general vmapped trace below.
        """
        if ruleno < 0 or ruleno >= self.cmap.max_rules or \
                self.cmap.rules[ruleno] is None:
            raise ValueError(f"no rule {ruleno}")
        pc = _perf("crush.mapper")
        pc.inc("map_batch_calls")
        pc.inc("lanes", len(xs))
        fkey = (ruleno, result_max)
        if self._fast_enabled and fkey not in self._fast_unsupported:
            try:
                if self._fast is None:
                    from .fast_mapper import FastMapper
                    self._fast = FastMapper(
                        self.cmap, choose_args_key=self.choose_args_key,
                        strategy=self.tables.strategy)
                _mark_active("dispatched_device",
                             component="crush.fastmap", lanes=len(xs))
                with pc.time("fast_map_s"):
                    out, inc = self._fast.map_batch(
                        ruleno, xs, result_max, weights, mesh=mesh)
                if inc.any():
                    rows = np.flatnonzero(inc)
                    pc.inc("fallback_lanes", len(rows))
                    xs_np = np.asarray(xs, dtype=np.int64)[rows]
                    out = np.array(out)    # jax arrays are read-only
                    out[rows] = self._exact_rows(
                        ruleno, xs_np, result_max, weights)
                return out
            except UnsupportedMapError:
                self._fast_unsupported.add(fkey)
                pc.inc("fast_unsupported_rules")
        jitted = self._get_jitted(ruleno, result_max, mesh)
        w = np.zeros(self.compiled.max_devices, dtype=np.int32)
        w_in = np.asarray(weights, dtype=np.int64)
        w[:min(len(w_in), len(w))] = w_in[:len(w)]
        xs_np = np.asarray(xs, dtype=np.int64).astype(np.uint32) \
            .astype(np.int32)
        n = len(xs_np)
        cap = int(_config().get("mapper_max_lanes_per_call"))
        cap *= (mesh.size if mesh is not None else 1)
        if n > cap:
            # pad to a multiple of cap so every chunk reuses one
            # executable; chunk results stay on device until ONE final
            # readback (tunnel transfers cost ~0.25s latency each)
            pad = (-n) % cap
            xs_pad = np.concatenate([xs_np, xs_np[:1].repeat(pad)]) \
                if pad else xs_np
            w_dev = jnp.asarray(w)
            with pc.time("general_map_s"):
                parts = [jitted(jnp.asarray(xs_pad[i:i + cap]), w_dev)
                         for i in range(0, len(xs_pad), cap)]
                out_d = parts[0] if len(parts) == 1 \
                    else jnp.concatenate(parts)
                return np.asarray(out_d)[:n]
        if mesh is not None:
            pad = (-n) % mesh.size
            if pad:
                xs_np = np.concatenate([xs_np, xs_np[:1].repeat(pad)])
        with pc.time("general_map_s"):
            out = np.asarray(jitted(jnp.asarray(xs_np), jnp.asarray(w)))
        return out[:n]
