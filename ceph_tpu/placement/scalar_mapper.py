"""Bit-exact scalar CRUSH mapper — the correctness oracle for the TPU mapper.

A from-scratch Python implementation of the placement semantics of the
reference interpreter (src/crush/mapper.c): the rule program machine
(crush_do_rule, mapper.c:900-1105), depth-first firstn selection with
collision/out/retry handling (crush_choose_firstn, mapper.c:460-648),
breadth-first positionally-stable indep selection (crush_choose_indep,
mapper.c:655-843), and the five bucket choose algorithms
(mapper.c:73-418).  Everything is pure integer math on Python ints.

This module is deliberately scalar and slow: it exists to define behavior for
tests and to cross-check the batched XLA mapper and the C++ native mapper.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..ops import hashing
from . import lntable
from .crush_map import (
    BUCKET_LIST, BUCKET_STRAW, BUCKET_STRAW2, BUCKET_TREE, BUCKET_UNIFORM,
    ITEM_NONE, ITEM_UNDEF, RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP,
    RULE_CHOOSE_FIRSTN, RULE_CHOOSE_INDEP, RULE_EMIT,
    RULE_SET_CHOOSELEAF_STABLE, RULE_SET_CHOOSELEAF_TRIES,
    RULE_SET_CHOOSELEAF_VARY_R, RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    RULE_SET_CHOOSE_LOCAL_TRIES, RULE_SET_CHOOSE_TRIES, RULE_TAKE,
    Bucket, ChooseArg, CrushMap, tree_left, tree_right,
)

S64_MIN = lntable.S64_MIN


class _PermState:
    """Per-bucket lazily-built random permutation (mapper.c:73-131)."""

    __slots__ = ("perm_x", "perm_n", "perm")

    def __init__(self, size: int):
        self.perm_x = 0
        self.perm_n = 0
        self.perm = [0] * size


class Workspace:
    """Mutable scratch state across one do_rule call (crush_init_workspace)."""

    def __init__(self, cmap: CrushMap):
        self._perm: Dict[int, _PermState] = {}
        for b in cmap.buckets:
            if b is not None:
                self._perm[b.id] = _PermState(b.size)

    def perm(self, bucket_id: int) -> _PermState:
        return self._perm[bucket_id]


# ------------------------------------------------------- bucket choosers ----

def bucket_perm_choose(bucket: Bucket, work: _PermState, x: int, r: int) -> int:
    pr = r % bucket.size
    if work.perm_x != (x & 0xFFFFFFFF) or work.perm_n == 0:
        work.perm_x = x & 0xFFFFFFFF
        if pr == 0:
            s = hashing.hash3(x, bucket.id & 0xFFFFFFFF, 0) % bucket.size
            work.perm[0] = s
            work.perm_n = 0xFFFF  # magic: only slot 0 is valid
            return bucket.items[s]
        work.perm = list(range(bucket.size))
        work.perm_n = 0
    elif work.perm_n == 0xFFFF:
        # expand the r=0 shortcut into a real prefix
        for i in range(1, bucket.size):
            work.perm[i] = i
        work.perm[work.perm[0]] = 0
        work.perm_n = 1
    while work.perm_n <= pr:
        p = work.perm_n
        if p < bucket.size - 1:
            i = hashing.hash3(x, bucket.id & 0xFFFFFFFF, p) % (bucket.size - p)
            if i:
                work.perm[p + i], work.perm[p] = work.perm[p], work.perm[p + i]
        work.perm_n += 1
    return bucket.items[work.perm[pr]]


def bucket_list_choose(bucket: Bucket, x: int, r: int) -> int:
    for i in range(bucket.size - 1, -1, -1):
        w = hashing.hash4(x, bucket.items[i] & 0xFFFFFFFF, r,
                          bucket.id & 0xFFFFFFFF) & 0xFFFF
        w = (w * bucket.sum_weights[i]) >> 16
        if w < bucket.weights[i]:
            return bucket.items[i]
    return bucket.items[0]


def bucket_tree_choose(bucket: Bucket, x: int, r: int) -> int:
    n = bucket.num_nodes >> 1
    while not (n & 1):
        w = bucket.node_weights[n]
        t = (hashing.hash4(x, n, r, bucket.id & 0xFFFFFFFF) * w) >> 32
        l = tree_left(n)
        n = l if t < bucket.node_weights[l] else tree_right(n)
    return bucket.items[n >> 1]


def bucket_straw_choose(bucket: Bucket, x: int, r: int) -> int:
    high, high_draw = 0, 0
    for i in range(bucket.size):
        draw = (hashing.hash3(x, bucket.items[i] & 0xFFFFFFFF, r) & 0xFFFF) \
            * bucket.straws[i]
        if i == 0 or draw > high_draw:
            high, high_draw = i, draw
    return bucket.items[high]


def bucket_straw2_choose(bucket: Bucket, x: int, r: int,
                         arg: Optional[ChooseArg], position: int) -> int:
    weights = bucket.weights
    ids = bucket.items
    if arg is not None and arg.weight_set is not None:
        pos = min(position, len(arg.weight_set) - 1)
        weights = arg.weight_set[pos]
    if arg is not None and arg.ids is not None:
        ids = arg.ids
    high, high_draw = 0, 0
    for i in range(bucket.size):
        if weights[i]:
            u = hashing.hash3(x, ids[i] & 0xFFFFFFFF, r) & 0xFFFF
            draw = lntable.straw2_draw(u, weights[i])
        else:
            draw = S64_MIN
        if i == 0 or draw > high_draw:
            high, high_draw = i, draw
    return bucket.items[high]


def bucket_choose(bucket: Bucket, work: _PermState, x: int, r: int,
                  arg: Optional[ChooseArg], position: int) -> int:
    if bucket.alg == BUCKET_UNIFORM:
        return bucket_perm_choose(bucket, work, x, r)
    if bucket.alg == BUCKET_LIST:
        return bucket_list_choose(bucket, x, r)
    if bucket.alg == BUCKET_TREE:
        return bucket_tree_choose(bucket, x, r)
    if bucket.alg == BUCKET_STRAW:
        return bucket_straw_choose(bucket, x, r)
    if bucket.alg == BUCKET_STRAW2:
        return bucket_straw2_choose(bucket, x, r, arg, position)
    return bucket.items[0]


def is_out(cmap: CrushMap, weight: Sequence[int], item: int, x: int) -> bool:
    """Device overload rejection (mapper.c:424-438)."""
    if item >= len(weight):
        return True
    w = weight[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (hashing.hash2(x, item) & 0xFFFF) >= w


# ------------------------------------------------------------- choosers -----

def _choose_arg_for(choose_args, bucket_id: int) -> Optional[ChooseArg]:
    if choose_args is None:
        return None
    idx = -1 - bucket_id
    if idx >= len(choose_args):
        return None
    return choose_args[idx]


def choose_firstn(cmap: CrushMap, work: Workspace, bucket: Bucket,
                  weight: Sequence[int], x: int, numrep: int, type_: int,
                  out: List[int], outpos: int, out_size: int,
                  tries: int, recurse_tries: int, local_retries: int,
                  local_fallback_retries: int, recurse_to_leaf: bool,
                  vary_r: int, stable: int, out2: Optional[List[int]],
                  parent_r: int, choose_args) -> int:
    """Depth-first draw-with-retry (mapper.c:460-648)."""
    count = out_size
    rep = 0 if stable else outpos
    while rep < numrep and count > 0:
        ftotal = 0
        skip_rep = False
        retry_descent = True
        item = 0
        while retry_descent:
            retry_descent = False
            in_bucket = bucket
            flocal = 0
            retry_bucket = True
            while retry_bucket:
                retry_bucket = False
                collide = False
                r = rep + parent_r + ftotal
                if in_bucket.size == 0:
                    reject = True
                else:
                    if (local_fallback_retries > 0 and
                            flocal >= (in_bucket.size >> 1) and
                            flocal > local_fallback_retries):
                        item = bucket_perm_choose(
                            in_bucket, work.perm(in_bucket.id), x, r)
                    else:
                        item = bucket_choose(
                            in_bucket, work.perm(in_bucket.id), x, r,
                            _choose_arg_for(choose_args, in_bucket.id), outpos)
                    if item >= cmap.max_devices:
                        skip_rep = True
                        break
                    itemtype = cmap.bucket(item).type if item < 0 else 0
                    if itemtype != type_:
                        if item >= 0 or (-1 - item) >= cmap.max_buckets:
                            skip_rep = True
                            break
                        in_bucket = cmap.bucket(item)
                        retry_bucket = True
                        continue
                    for i in range(outpos):
                        if out[i] == item:
                            collide = True
                            break
                    reject = False
                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = (r >> (vary_r - 1)) if vary_r else 0
                            got = choose_firstn(
                                cmap, work, cmap.bucket(item), weight, x,
                                1 if stable else outpos + 1, 0,
                                out2, outpos, count,
                                recurse_tries, 0, local_retries,
                                local_fallback_retries, False,
                                vary_r, stable, None, sub_r, choose_args)
                            if got <= outpos:
                                reject = True
                        else:
                            out2[outpos] = item
                    if not reject and not collide:
                        if itemtype == 0:
                            reject = is_out(cmap, weight, item, x)
                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (local_fallback_retries > 0 and
                          flocal <= in_bucket.size + local_fallback_retries):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                    else:
                        skip_rep = True
            if skip_rep:
                break
        if not skip_rep:
            out[outpos] = item
            outpos += 1
            count -= 1
        rep += 1
    return outpos


def choose_indep(cmap: CrushMap, work: Workspace, bucket: Bucket,
                 weight: Sequence[int], x: int, left: int, numrep: int,
                 type_: int, out: List[int], outpos: int,
                 tries: int, recurse_tries: int, recurse_to_leaf: bool,
                 out2: Optional[List[int]], parent_r: int, choose_args) -> None:
    """Breadth-first positionally-stable selection (mapper.c:655-843)."""
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = ITEM_UNDEF
        if out2 is not None:
            out2[rep] = ITEM_UNDEF
    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] != ITEM_UNDEF:
                continue
            in_bucket = bucket
            while True:
                r = rep + parent_r
                if (in_bucket.alg == BUCKET_UNIFORM and
                        in_bucket.size % numrep == 0):
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal
                if in_bucket.size == 0:
                    break
                item = bucket_choose(
                    in_bucket, work.perm(in_bucket.id), x, r,
                    _choose_arg_for(choose_args, in_bucket.id), outpos)
                if item >= cmap.max_devices:
                    out[rep] = ITEM_NONE
                    if out2 is not None:
                        out2[rep] = ITEM_NONE
                    left -= 1
                    break
                itemtype = cmap.bucket(item).type if item < 0 else 0
                if itemtype != type_:
                    if item >= 0 or (-1 - item) >= cmap.max_buckets:
                        out[rep] = ITEM_NONE
                        if out2 is not None:
                            out2[rep] = ITEM_NONE
                        left -= 1
                        break
                    in_bucket = cmap.bucket(item)
                    continue
                collide = False
                for i in range(outpos, endpos):
                    if out[i] == item:
                        collide = True
                        break
                if collide:
                    break
                if recurse_to_leaf:
                    if item < 0:
                        choose_indep(cmap, work, cmap.bucket(item), weight, x,
                                     1, numrep, 0, out2, rep,
                                     recurse_tries, 0, False, None, r,
                                     choose_args)
                        if out2 is not None and out2[rep] == ITEM_NONE:
                            break
                    elif out2 is not None:
                        out2[rep] = item
                if itemtype == 0 and is_out(cmap, weight, item, x):
                    break
                out[rep] = item
                left -= 1
                break
        ftotal += 1
    for rep in range(outpos, endpos):
        if out[rep] == ITEM_UNDEF:
            out[rep] = ITEM_NONE
        if out2 is not None and out2[rep] == ITEM_UNDEF:
            out2[rep] = ITEM_NONE


# -------------------------------------------------------------- do_rule -----

def do_rule(cmap: CrushMap, ruleno: int, x: int, result_max: int,
            weight: Sequence[int],
            choose_args=None) -> List[int]:
    """Run one rule program (mapper.c:900-1105). Returns the result vector."""
    if ruleno < 0 or ruleno >= cmap.max_rules or cmap.rules[ruleno] is None:
        return []
    rule = cmap.rules[ruleno]
    work = Workspace(cmap)

    result: List[int] = []
    # +1 so result_max == 0 degenerates gracefully (the C caller's scratch
    # buffer always has room for the TAKE slot; choose steps then no-op)
    w: List[int] = [0] * (result_max + 1)
    o: List[int] = [0] * (result_max + 1)
    c: List[int] = [0] * (result_max + 1)
    wsize = 0

    choose_tries = cmap.tunables.choose_total_tries + 1
    choose_leaf_tries = 0
    choose_local_retries = cmap.tunables.choose_local_tries
    choose_local_fallback_retries = cmap.tunables.choose_local_fallback_tries
    vary_r = cmap.tunables.chooseleaf_vary_r
    stable = cmap.tunables.chooseleaf_stable

    for op, arg1, arg2 in rule.steps:
        firstn = False
        if op == RULE_TAKE:
            if (0 <= arg1 < cmap.max_devices) or \
               (0 <= -1 - arg1 < cmap.max_buckets and cmap.bucket(arg1)):
                w[0] = arg1
                wsize = 1
        elif op == RULE_SET_CHOOSE_TRIES:
            if arg1 > 0:
                choose_tries = arg1
        elif op == RULE_SET_CHOOSELEAF_TRIES:
            if arg1 > 0:
                choose_leaf_tries = arg1
        elif op == RULE_SET_CHOOSE_LOCAL_TRIES:
            if arg1 >= 0:
                choose_local_retries = arg1
        elif op == RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if arg1 >= 0:
                choose_local_fallback_retries = arg1
        elif op == RULE_SET_CHOOSELEAF_VARY_R:
            if arg1 >= 0:
                vary_r = arg1
        elif op == RULE_SET_CHOOSELEAF_STABLE:
            if arg1 >= 0:
                stable = arg1
        elif op in (RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSE_FIRSTN,
                    RULE_CHOOSELEAF_INDEP, RULE_CHOOSE_INDEP):
            if wsize == 0:
                continue
            firstn = op in (RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSE_FIRSTN)
            recurse_to_leaf = op in (RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP)
            osize = 0
            for i in range(wsize):
                numrep = arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                bno = -1 - w[i]
                if bno < 0 or bno >= cmap.max_buckets or cmap.buckets[bno] is None:
                    continue
                bucket = cmap.buckets[bno]
                # the reference passes o+osize / c+osize with outpos=0, so
                # r-values and collision scans are relative to this take's
                # own output window (mapper.c:1036-1074)
                o_sub = o[osize:]
                c_sub = c[osize:]
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif cmap.tunables.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    placed = choose_firstn(
                        cmap, work, bucket, weight, x, numrep, arg2,
                        o_sub, 0, result_max - osize,
                        choose_tries, recurse_tries,
                        choose_local_retries, choose_local_fallback_retries,
                        recurse_to_leaf, vary_r, stable,
                        c_sub, 0, choose_args)
                    o[osize:osize + len(o_sub)] = o_sub
                    c[osize:osize + len(c_sub)] = c_sub
                    osize += placed
                else:
                    out_size = min(numrep, result_max - osize)
                    choose_indep(
                        cmap, work, bucket, weight, x, out_size, numrep,
                        arg2, o_sub, 0,
                        choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf, c_sub, 0, choose_args)
                    o[osize:osize + len(o_sub)] = o_sub
                    c[osize:osize + len(c_sub)] = c_sub
                    osize += out_size
            if recurse_to_leaf:
                for i in range(osize):
                    o[i] = c[i]
            w, o = o, w
            wsize = osize
        elif op == RULE_EMIT:
            for i in range(wsize):
                if len(result) >= result_max:
                    break
                result.append(w[i])
            wsize = 0
    return result
