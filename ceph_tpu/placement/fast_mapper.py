"""Level-synchronous batched CRUSH mapping — the fast TPU path.

The round-1 mapper vectorized crush_do_rule by vmapping a per-x rule
machine whose retry loops were lax.while_loops: every iteration re-ran
the full batch width, the whole batch spun until its WORST lane
converged, and every bucket row was padded to the global max bucket
size.  This module restructures the computation around two facts about
the algorithm (reference: src/crush/mapper.c:460-843):

  1. A descent's value depends only on (map, x, r) — collision/out
     rejections affect which descents are *kept*, never what they
     *return*.  So all retry candidates r ∈ [0, numrep+extra) are
     computed at once as one extra parallel axis, and the sequential
     accept/reject bookkeeping (crush_choose_firstn's ftotal loop,
     crush_choose_indep's rounds) collapses to a statically unrolled
     chain of cheap [N]-wide integer selects.  Within one replica slot,
     try number f always uses r = rep + f (firstn) or r = rep +
     numrep·f (indep), so the candidate grid is static.
  2. The hierarchy is layered: a descent from one root can only visit
     buckets reachable at that depth.  Tables are therefore built per
     level (root row alone at level 0, its bucket children at level 1,
     ...), so a 1000-host root costs S=1000-wide straw2 draws only at
     level 0 while the host level pays S=10 — not the global max.

Lanes that exhaust the candidate budget (or hit the rare
position-dependent cases the grid cannot represent, e.g. a skip under
chooseleaf_stable=0 or multi-position choose_args weight sets) are
flagged incomplete and recomputed bit-exactly by the caller through the
native C++ interpreter (ceph_tpu.native_bridge) or the scalar oracle —
same semantics, so the combined result is bit-exact for every lane.

Supported rules: sequences of TAKE/SET_*/CHOOSE*/EMIT where each TAKE
names a static bucket and each take block contains at most one choose
step (chains where a choose feeds another choose fall back to the
general XlaMapper trace).  Map subset: straw2 + modern tunables, as
compile_map enforces.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..common.options import config as _config
from ..ops import hashing
from .crush_map import (
    ITEM_NONE, ITEM_UNDEF,
    RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP, RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP, RULE_EMIT, RULE_SET_CHOOSELEAF_STABLE,
    RULE_SET_CHOOSELEAF_TRIES, RULE_SET_CHOOSELEAF_VARY_R,
    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES, RULE_SET_CHOOSE_LOCAL_TRIES,
    RULE_SET_CHOOSE_TRIES, RULE_TAKE, CrushMap,
)
from . import lntable
from .xla_mapper import (
    CompiledMap, DeviceTables, UnsupportedMapError, compile_map)

_INF = jnp.inf
_OK, _REJECT, _SKIP = 0, 1, 2

# ------------------------------------------------- approximate straw2 draw --
#
# The exact straw2 draw needs the quirky 2^48-fixed-point crush_ln LUT
# (ln_numer's one-hot limb matmuls — ~6.4k MXU flops and ~50 bytes of
# HBM traffic per item).  The selection, however, only needs the ARGMIN
# of the draws.  So: compute a cheap f32 approximation of the draw for
# every item (polynomial log2 — pure VPU arithmetic, no tables), then
# evaluate the EXACT draw only for the (at most two) items whose
# approximate draw lies within a conservative error margin of the
# minimum.  The margin is derived from the measured worst-case gap D
# between the f32 polynomial and the real LUT over all 65536 inputs, so
# the exact winner is provably inside the candidate set; lanes where
# more than two items fall inside the margin (probability ~ margin /
# draw-scale ≈ 2e-5 per selection) are flagged for exact fallback.

# minimax-ish fit of log2(m), m ∈ [1, 2), ascending coefficients
_LOG2_POLY = (-2.7868055642996064, 5.046852935530284, -3.4924660425578216,
              1.5938845482693522, -0.40486230941613244,
              0.04342836333164342)
_2P44_F = float(2.0 ** 44)


def _approx_numer_f32(u):
    """f32 approximation of ln_numer(u) = 2^48 - crush_ln(u)."""
    v = (u.astype(jnp.int32) + 1).astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(v, jnp.int32)
    e = (bits >> 23) - 127
    mant = jax.lax.bitcast_convert_type(
        (bits & 0x7FFFFF) | 0x3F800000, jnp.float32)
    p = jnp.float32(_LOG2_POLY[-1])
    for c in _LOG2_POLY[-2::-1]:
        p = p * mant + jnp.float32(c)
    log2v = e.astype(jnp.float32) + p
    return jnp.float32(_2P44_F) * (jnp.float32(16.0) - log2v)


# one module-level jitted wrapper: jax.jit keys its executable cache on
# backend+shape, so the CPU-then-TPU process re-traces per platform
# without building a fresh wrapper (and a retrace) per call
_approx_numer_dev = jax.jit(_approx_numer_f32)


@functools.lru_cache(maxsize=None)
def _approx_error_bound(backend: str) -> float:
    """Max |approx - LUT| of THIS backend's poly evaluation, measured by
    running the device computation over every u at init (one [65536]
    dispatch, cached per backend — callers pass jax.default_backend()
    as the key so a CPU-then-TPU process re-measures per platform).

    The bound is irreducible at ~2^29.5: the reference LUT is built from
    128-segment fixed-point tables (src/crush/crush_ln_table.h) and
    deviates from ANY smooth function by that much — a better polynomial
    cannot shrink it.  Measuring on-device replaces the old 4x
    reassociation slack with the true value, which is what keeps the
    candidate window narrow (~4 u-steps at host weights) so the exact
    top-K re-check below almost never overflows K.
    """
    u = jnp.arange(65536, dtype=jnp.int32)
    na = np.asarray(_approx_numer_dev(u)).astype(np.float64)
    n_exact = (-lntable.straw2_ln_lut()).astype(np.float64)
    d = float(np.abs(na - n_exact).max())
    return 1.25 * d + float(2 ** 20)


class UnsupportedRuleError(UnsupportedMapError):
    """Rule shape outside the fast subset (caller should fall back)."""


# ------------------------------------------------------------ level tables --

@dataclass
class _HostLevel:
    """One descent level, host-side (rows = buckets reachable here)."""
    bucket_ids: List[int]            # global bucket ids at this level
    items: np.ndarray                # i32 [Bl, Sl] child ids
    hash_ids: np.ndarray             # i32 [Bl, Sl]
    weights: np.ndarray              # i32 [Bl, P, Sl]
    sizes: np.ndarray                # i32 [Bl]
    child_row: np.ndarray            # i32 [Bl, Sl] row in next level (-1)
    child_type: np.ndarray           # i32 [Bl, Sl] (0 for devices)
    child_escape: np.ndarray         # bool [Bl, Sl] invalid child
    child_leafrow: np.ndarray        # i32 [Bl, Sl] row in leaf class (-1)


def _build_levels(cmap: CrushMap, cm: CompiledMap, roots: List[int],
                  target_type: int) -> Tuple[List[_HostLevel], List[int]]:
    """BFS the hierarchy from `roots` down to `target_type`.

    Returns (levels, leaf_class): leaf_class is the ordered list of
    target-type bucket ids encountered (the chooseleaf recursion roots).
    """
    levels: List[_HostLevel] = []
    leaf_class: List[int] = []
    leaf_index: Dict[int, int] = {}
    cur = list(dict.fromkeys(roots))
    for _ in range(cm.max_depth + 1):
        if not cur:
            break
        next_ids: List[int] = []
        next_index: Dict[int, int] = {}
        rows = [cmap.bucket(b) for b in cur]
        Sl = max((b.size for b in rows if b is not None), default=1)
        Sl = max(Sl, 1)
        Bl = len(cur)
        items = np.zeros((Bl, Sl), dtype=np.int32)
        hash_ids = np.zeros((Bl, Sl), dtype=np.int32)
        ws = np.zeros((Bl, cm.n_positions, Sl), dtype=np.int32)
        sizes = np.zeros(Bl, dtype=np.int32)
        child_row = np.full((Bl, Sl), -1, dtype=np.int32)
        child_type = np.zeros((Bl, Sl), dtype=np.int32)
        child_escape = np.zeros((Bl, Sl), dtype=bool)
        child_leafrow = np.full((Bl, Sl), -1, dtype=np.int32)
        for li, (bid, b) in enumerate(zip(cur, rows)):
            if b is None:
                continue
            gidx = -1 - bid
            n = b.size
            sizes[li] = n
            items[li, :n] = cm.items[gidx, :n]
            hash_ids[li, :n] = cm.hash_ids[gidx, :n]
            ws[li, :, :n] = cm.weight_sets[gidx, :, :n]
            for s, c in enumerate(b.items):
                if c >= 0:
                    if c >= cm.max_devices:
                        child_escape[li, s] = True
                    continue
                cb = cmap.bucket(c)
                if cb is None:
                    child_escape[li, s] = True
                    continue
                child_type[li, s] = cb.type
                if cb.type == target_type:
                    if c not in leaf_index:
                        leaf_index[c] = len(leaf_class)
                        leaf_class.append(c)
                    child_leafrow[li, s] = leaf_index[c]
                else:
                    if c not in next_index:
                        next_index[c] = len(next_ids)
                        next_ids.append(c)
                    child_row[li, s] = next_index[c]
        levels.append(_HostLevel(
            bucket_ids=list(cur), items=items, hash_ids=hash_ids,
            weights=ws, sizes=sizes, child_row=child_row,
            child_type=child_type, child_escape=child_escape,
            child_leafrow=child_leafrow))
        cur = next_ids
    if cur:
        raise UnsupportedMapError(
            "hierarchy deeper than max_depth (cycle?)")
    return levels, leaf_class


class _DevLevel:
    """Device-resident level tables for one static choose_args position.

    Strategy mirror of DeviceTables: 'gather' (CPU) row-indexes;
    'onehot' (TPU) turns every row select into a one-hot matmul so no
    serial gather is emitted.
    """

    def __init__(self, hl: _HostLevel, pos: int, strategy: str):
        self.strategy = strategy
        self.Bl, self.Sl = hl.items.shape
        pos_c = min(pos, hl.weights.shape[1] - 1)
        w = hl.weights[:, pos_c, :].astype(np.int64)
        # per-row margin: 2*bound/wmin bounds a candidate-pair gap; a
        # small relative term for f32 division rounding is added at
        # select time
        bound = _approx_error_bound(jax.default_backend())
        valid = (w > 0) & (np.arange(self.Sl)[None, :] < hl.sizes[:, None])
        wmin = np.where(valid, w, np.int64(1) << 40).min(
            axis=1, initial=np.int64(1) << 40)
        margin = (2.0 * bound / np.maximum(wmin, 1) + 64.0).astype(
            np.float32)
        self.margin = jnp.asarray(margin)
        if strategy == "gather":
            self.items = jnp.asarray(hl.items)
            self.hash_ids = jnp.asarray(hl.hash_ids.astype(np.uint32))
            self.w_hi = jnp.asarray((w >> 16).astype(np.float32))
            self.w_lo = jnp.asarray((w & 0xFFFF).astype(np.float32))
            self.sizes = jnp.asarray(hl.sizes)
            self.child_row = jnp.asarray(hl.child_row)
            self.child_type = jnp.asarray(hl.child_type)
            self.child_escape = jnp.asarray(hl.child_escape)
            self.child_leafrow = jnp.asarray(hl.child_leafrow)
            return
        for name, arr in (("items", hl.items), ("hash_ids", hl.hash_ids)):
            if np.abs(arr.astype(np.int64)).max(initial=0) >= (1 << 24):
                raise UnsupportedMapError(f"onehot requires |{name}| < 2^24")
        self.items_f = jnp.asarray(hl.items.astype(np.float32))
        self.ids_f = jnp.asarray(hl.hash_ids.astype(np.float32))
        self.w_hi = jnp.asarray((w >> 16).astype(np.float32))
        self.w_lo = jnp.asarray((w & 0xFFFF).astype(np.float32))
        self.sizes_f = jnp.asarray(hl.sizes.astype(np.float32))
        self.child_row_f = jnp.asarray(hl.child_row.astype(np.float32))
        self.child_type_f = jnp.asarray(hl.child_type.astype(np.float32))
        self.child_escape_f = jnp.asarray(hl.child_escape.astype(np.float32))
        self.child_leafrow_f = jnp.asarray(
            hl.child_leafrow.astype(np.float32))

    def rows(self, row):
        """row [L] → (items, ids, w_hi, w_lo, sizes, child_row,
        child_type, child_escape, child_leafrow, margin); [L, Sl] each
        except sizes/margin [L].  w_hi/w_lo are exact f32 16-bit halves
        of the 16.16 weights."""
        if self.Bl == 1:
            # single-bucket level (every TAKE root): broadcast the row —
            # no one-hot matmul, and XLA fuses broadcasts into consumers
            # without materializing [L, S] copies
            L = row.shape[0]

            def bc(t):
                return jnp.broadcast_to(t[0], (L,) + t.shape[1:])

            if self.strategy == "gather":
                return (bc(self.items), bc(self.hash_ids), bc(self.w_hi),
                        bc(self.w_lo), bc(self.sizes), bc(self.child_row),
                        bc(self.child_type), bc(self.child_escape),
                        bc(self.child_leafrow), bc(self.margin))
            return (bc(self.items_f).astype(jnp.int32),
                    bc(self.ids_f).astype(jnp.int32).astype(jnp.uint32),
                    bc(self.w_hi), bc(self.w_lo),
                    bc(self.sizes_f).astype(jnp.int32),
                    bc(self.child_row_f).astype(jnp.int32),
                    bc(self.child_type_f).astype(jnp.int32),
                    bc(self.child_escape_f) > 0.5,
                    bc(self.child_leafrow_f).astype(jnp.int32),
                    bc(self.margin))
        if self.strategy == "gather":
            r = jnp.clip(row, 0, self.Bl - 1)
            return (self.items[r], self.hash_ids[r], self.w_hi[r],
                    self.w_lo[r], self.sizes[r], self.child_row[r],
                    self.child_type[r], self.child_escape[r],
                    self.child_leafrow[r], self.margin[r])
        oh = (row[:, None] ==
              jnp.arange(self.Bl, dtype=jnp.int32)).astype(jnp.float32)
        items = (oh @ self.items_f).astype(jnp.int32)
        ids = (oh @ self.ids_f).astype(jnp.int32).astype(jnp.uint32)
        w_hi = oh @ self.w_hi
        w_lo = oh @ self.w_lo
        sizes = (oh @ self.sizes_f).astype(jnp.int32)
        child_row = (oh @ self.child_row_f).astype(jnp.int32)
        child_type = (oh @ self.child_type_f).astype(jnp.int32)
        child_escape = (oh @ self.child_escape_f) > 0.5
        child_leafrow = (oh @ self.child_leafrow_f).astype(jnp.int32)
        margin = oh @ self.margin
        return (items, ids, w_hi, w_lo, sizes, child_row, child_type,
                child_escape, child_leafrow, margin)

    def select(self, j, *tables):
        """tables[i][l, j[l]] for each [L, Sl] table, without gathers."""
        if self.strategy == "gather":
            jj = j[:, None]
            return tuple(jnp.take_along_axis(t, jj, axis=1)[:, 0]
                         for t in tables)
        sel = (j[:, None] == jnp.arange(self.Sl, dtype=jnp.int32))
        out = []
        for t in tables:
            if t.dtype == jnp.bool_:
                out.append(jnp.where(sel, t, False).any(axis=1))
            else:
                out.append(jnp.where(sel, t, 0).sum(axis=1, dtype=t.dtype))
        return tuple(out)


def _u32(v):
    return jnp.asarray(v).astype(jnp.uint32)


def _weight_at(weights, item, strategy):
    """weights[item] for item [L] (strategy-aware, exact: w ≤ 2^16)."""
    n = weights.shape[0]
    idx = jnp.clip(item, 0, n - 1)
    if strategy == "gather":
        return weights[idx].astype(jnp.int64)
    oh = (idx[:, None] == jnp.arange(n, dtype=jnp.int32)).astype(jnp.float32)
    return (oh @ weights.astype(jnp.float32)).astype(jnp.int64)


def _is_out_batch(weights, item, x, strategy):
    """Device overload rejection (mapper.c:424-438), batched over [L]."""
    n = weights.shape[0]
    w = _weight_at(weights, item, strategy)
    oob = item >= n
    hashed = (hashing.jx_hash2(_u32(x), _u32(item)) &
              jnp.uint32(0xFFFF)).astype(jnp.int64) >= w
    return oob | jnp.where(w >= 0x10000, False,
                           jnp.where(w == 0, True, hashed))


# ---------------------------------------------------------------- descent ---

def _exact_qk(dt: DeviceTables, uk, w_hik, w_lok):
    """Exact straw2 draws for [L, K] candidates: the full fixed-point
    LUT + trunc-div math, on K items per lane."""
    a = dt.ln_numer(uk)                          # [L, K] f64
    w = w_hik.astype(jnp.float64) * 65536.0 + w_lok.astype(jnp.float64)
    q = jnp.floor(a / jnp.maximum(w, 1.0))
    q = q - (q * w > a)
    q = q + ((q + 1.0) * w <= a)
    return jnp.where(w > 0, q, _INF)


_TOPK = 4          # approx candidates re-checked exactly per selection


def _straw2_select(dt: DeviceTables, u, w_hi, w_lo, sizes, margin,
                   exact: bool):
    """argmin of the straw2 draws over the item axis → (j [L], ambig).

    Approx mode: f32 polynomial draws prefilter to the top-K smallest;
    every candidate inside the proven error margin is then re-drawn with
    the EXACT fixed-point LUT math and the exact minimum wins
    (first-index tie-break preserved).  A lane is ambiguous only when
    more than K candidates fall inside the margin — with the measured
    on-device bound the in-margin count is ~0.06 expected, so
    P(ambiguous) ≈ 1e-7 per selection.  Masked min-reductions are used
    instead of lax.top_k, whose TPU lowering is a full [L, S] sort.

    Exact mode: full-width LUT math (CEPH_TPU_SELECT=exact)."""
    Sl = u.shape[1]
    valid = ((w_hi > 0) | (w_lo > 0)) & \
        (jnp.arange(Sl, dtype=jnp.int32) < sizes[:, None])
    if exact:
        a = dt.ln_numer(u)
        w = w_hi.astype(jnp.float64) * 65536.0 + w_lo.astype(jnp.float64)
        q = jnp.floor(a / jnp.maximum(w, 1.0))
        q = q - (q * w > a)
        q = q + ((q + 1.0) * w <= a)
        q = jnp.where(valid, q, _INF)
        return (jnp.argmin(q, axis=1).astype(jnp.int32),
                jnp.zeros(u.shape[0], dtype=bool))
    w_f = w_hi * jnp.float32(65536.0) + w_lo
    qa = _approx_numer_f32(u) / jnp.maximum(w_f, jnp.float32(1.0))
    qa = jnp.where(valid, qa, jnp.float32(_INF))
    qa = jax.lax.optimization_barrier(qa)
    cols = jnp.arange(Sl, dtype=jnp.int32)
    K = min(_TOPK, Sl)
    u_i = u.astype(jnp.int32)
    idxs, mins, us, whs, wls = [], [], [], [], []
    work = qa
    for _ in range(K):
        ik = jnp.argmin(work, axis=1).astype(jnp.int32)
        sel = cols[None, :] == ik[:, None]
        mk = jnp.where(sel, work, 0).sum(axis=1)
        us.append(jnp.where(sel, u_i, 0).sum(axis=1))
        whs.append(jnp.where(sel, w_hi, 0).sum(axis=1))
        wls.append(jnp.where(sel, w_lo, 0).sum(axis=1))
        idxs.append(ik)
        mins.append(mk)
        work = jnp.where(sel, jnp.float32(_INF), work)
    m1 = mins[0]
    # margin + relative term for f32 division rounding (~2 ulp)
    thr = m1 + margin + jnp.float32(2.0 ** -21) * jnp.abs(m1)
    # ambiguous only if the (K+1)-th smallest approx is still in margin
    if Sl > K:
        ambig = (jnp.min(work, axis=1) <= thr) & jnp.isfinite(m1)
    else:
        ambig = jnp.zeros(u.shape[0], dtype=bool)
    iK = jnp.stack(idxs, -1)                       # [L, K]
    within = jnp.stack(mins, -1) <= thr[:, None]
    q_ex = _exact_qk(dt, jnp.stack(us, -1),
                     jnp.stack(whs, -1), jnp.stack(wls, -1))
    q_ex = jnp.where(within, q_ex, _INF)
    q_min = jnp.min(q_ex, axis=1)
    # exact ties break on the smallest ORIGINAL index (the scalar scan
    # keeps the first item on '>' comparisons)
    j = jnp.min(jnp.where(q_ex == q_min[:, None], iK, Sl), axis=1)
    return j.astype(jnp.int32), ambig


def _descend_batch(levels: List[_DevLevel], dt: DeviceTables,
                   target_type: int, row0, x, r, want_leafrow: bool,
                   exact: bool = False):
    """Batched hierarchy walk: row0/x/r are [L]; returns
    (item [L], status [L], leafrow [L], ambig [L]).  Statically
    unrolled over levels; every level is one straw2 selection over that
    level's width."""
    L = x.shape[0]
    cur = jnp.maximum(row0, 0)
    done = row0 < 0
    status = jnp.where(done, jnp.int32(_SKIP), jnp.int32(_REJECT))
    result = jnp.full((L,), ITEM_NONE, dtype=jnp.int32)
    leafrow = jnp.full((L,), -1, dtype=jnp.int32)
    ambig = jnp.zeros((L,), dtype=bool)
    xb = _u32(x)
    rb = _u32(r)
    for lvl in levels:
        (items, ids, w_hi, w_lo, sizes, child_row, child_type,
         child_escape, child_leafrow, margin) = lvl.rows(cur)
        empty = sizes == 0
        u = hashing.jx_hash3(xb[:, None], ids, rb[:, None]) & \
            jnp.uint32(0xFFFF)
        # materialize u: it feeds the top_k draw AND the exact top-2
        # re-evaluation — without the barrier XLA re-runs the ~140-op
        # hash chain for every consumer
        u = jax.lax.optimization_barrier(u)
        j, amb = _straw2_select(dt, u, w_hi, w_lo, sizes, margin, exact)
        ambig = ambig | ((~done) & (~empty) & amb)
        item, ctype, nrow, esc, lrow = lvl.select(
            j, items, child_type, child_row, child_escape, child_leafrow)
        is_dev = item >= 0
        match = ctype == target_type
        lvl_reject = empty
        lvl_skip = (~empty) & (esc | ((~match) & is_dev))
        lvl_done = lvl_reject | lvl_skip | ((~empty) & match & (~esc))
        status = jnp.where(
            done, status,
            jnp.where(lvl_reject, _REJECT,
                      jnp.where(lvl_skip, _SKIP,
                                jnp.where(match, _OK, status))))
        keep = done | (~match) | empty | esc
        result = jnp.where(keep, result, item)
        if want_leafrow:
            leafrow = jnp.where(keep, leafrow, lrow)
        new_done = done | lvl_done
        cur = jnp.where(new_done, cur, nrow)
        done = new_done
    status = jnp.where(done, status, jnp.int32(_SKIP))
    return result, status, leafrow, ambig


# ------------------------------------------------------------- choose step --

@dataclass(frozen=True)
class _ChooseSpec:
    """Static description of one choose step inside a take block."""
    firstn: bool
    leaf: bool
    numrep: int
    target_type: int
    tries: int               # choose_total_tries + 1 (or rule override)
    recurse_tries: int
    vary_r: int
    stable: bool
    root: int                # static bucket id


class _FastChoose:
    """Candidate grids + unrolled resolve for one choose step."""

    def __init__(self, cmap: CrushMap, cm: CompiledMap, dt: DeviceTables,
                 spec: _ChooseSpec, strategy: str, extra: int,
                 exact_select: bool = False):
        self.spec = spec
        self.strategy = strategy
        self.dt = dt
        self.exact_select = exact_select
        self.max_devices = cm.max_devices
        self.P = cm.n_positions
        levels_h, leaf_class = _build_levels(
            cmap, cm, [spec.root], spec.target_type)
        # The compact [N, R] candidate grid models the weight-set
        # position as 0 and (for stable chooseleaf) the leaf rep_base as
        # 0.  That is exact when P == 1 (all positions identical) and
        # stable=1.  Otherwise candidates are per (rep, f) with pos=rep
        # assuming outpos == rep; a prior skip breaks the assumption and
        # flags the lane for exact fallback.
        self.per_rep = spec.firstn and (
            self.P > 1 or (spec.leaf and not spec.stable))
        if spec.firstn:
            self.R = spec.numrep + extra
            self.rounds = 0
        else:
            # indep reuses slot-r candidates across rounds, and late
            # slots collide with probability ~(numrep/domains) per
            # round: the round budget needs a floor independent of the
            # firstn extra (P(unresolved) ~ 0.6^rounds on tight maps) —
            # but never beyond the rule's try budget (a round the
            # reference would not attempt could fill a slot it leaves
            # NONE), and capping HERE also keeps the candidate grid
            # from descending rounds the resolve loop would discard
            self.rounds = min(spec.tries, max(5, 1 + extra // 2))
            self.R = spec.numrep * self.rounds
        par_pos = list(range(spec.numrep)) if self.per_rep else [0]
        self.levels = {p: [_DevLevel(h, p, strategy) for h in levels_h]
                       for p in par_pos}
        # leaf positions: firstn uses pos=outpos (grid: rep or 0);
        # indep leaf uses pos=rep — per-rep tables only needed when P>1
        self.leaf_levels: Dict[int, list] = {}
        self.has_leaf = bool(spec.leaf and leaf_class)
        if self.has_leaf:
            lh, sub = _build_levels(cmap, cm, leaf_class, 0)
            if sub:
                raise UnsupportedMapError(
                    "chooseleaf targets nest buckets of the same type")
            if spec.firstn:
                leaf_pos = par_pos
            else:
                leaf_pos = list(range(spec.numrep)) if self.P > 1 else [0]
            self.leaf_levels = {
                p: [_DevLevel(h, p, strategy) for h in lh]
                for p in leaf_pos}

    # ---- candidate grids -------------------------------------------------
    def _descend_grid(self, levels, target_type, x, row0, rvals,
                      want_leafrow):
        """x [N]; row0/rvals [N, K] → (item, status, leafrow, ambig),
        each [N, K]."""
        N, K = rvals.shape
        xg = jnp.repeat(x, K)
        item, status, leafrow, ambig = _descend_batch(
            levels, self.dt, target_type, row0.reshape(-1), xg,
            rvals.reshape(-1).astype(jnp.int32), want_leafrow,
            exact=self.exact_select)
        return (item.reshape(N, K), status.reshape(N, K),
                leafrow.reshape(N, K), ambig.reshape(N, K))

    def parent_cands(self, x):
        """→ (item, status, leafrow, ambig) each [N, G, R]."""
        spec = self.spec
        N = x.shape[0]
        groups = list(range(spec.numrep)) if self.per_rep else [0]
        rvals = jnp.broadcast_to(
            jnp.arange(self.R, dtype=jnp.int32), (N, self.R))
        outs = []
        for g in groups:
            row0 = jnp.zeros((N, self.R), dtype=jnp.int32)
            outs.append(self._descend_grid(
                self.levels[g], spec.target_type, x, row0, rvals,
                self.has_leaf))
        return tuple(jnp.stack([o[i] for o in outs], axis=1)
                     for i in range(4))

    def leaf_cands(self, x, p_leafrow):
        """Leaf grids per parent candidate: [N, G, R, F'] (dev, status).

        p_leafrow: [N, G, R].  The leaf r depends on the parent slot:
        firstn: r' = rep_base + sub_r + ft (rep_base 0 when stable, rep
        when per-rep); indep: r' = rep + r_parent + numrep·ft with
        rep = r_parent mod numrep (slots are unique per rep).
        """
        spec = self.spec
        N, G, R = p_leafrow.shape
        rs = jnp.arange(R, dtype=jnp.int32)
        devs, sts = [], []
        ambig = jnp.zeros((N,), dtype=bool)
        for g in range(G):
            row0 = p_leafrow[:, g]                       # [N, R]
            gdevs, gsts = [], []
            for ft in range(spec.recurse_tries):
                if spec.firstn:
                    sub_r = (rs >> (spec.vary_r - 1)) if spec.vary_r \
                        else jnp.zeros_like(rs)
                    rep_base = g if (self.per_rep and not spec.stable) \
                        else 0
                    r_leaf = jnp.broadcast_to(
                        rep_base + sub_r + ft, (N, R))
                    lv = self.leaf_levels[g if self.per_rep else 0]
                    dev, st, _, amb = self._descend_grid(
                        lv, 0, x, row0, r_leaf, False)
                    ambig = ambig | amb.any(axis=1)
                else:
                    # indep: rep = slot mod numrep; one sub-grid per rep
                    # so each slot gets its rep-dependent r and (P>1)
                    # its rep-positioned weight tables
                    dev = jnp.full((N, R), jnp.int32(ITEM_NONE))
                    st = jnp.full((N, R), jnp.int32(_SKIP))
                    for rep in range(spec.numrep):
                        slots = list(range(rep, R, spec.numrep))
                        if not slots:
                            continue
                        sl = jnp.asarray(slots, dtype=jnp.int32)
                        r_parent = jnp.broadcast_to(sl, (N, len(slots)))
                        r_leaf = rep + r_parent + spec.numrep * ft
                        lv = self.leaf_levels[rep if self.P > 1 else 0]
                        d, s, _, amb = self._descend_grid(
                            lv, 0, x, row0[:, sl], r_leaf, False)
                        dev = dev.at[:, sl].set(d)
                        st = st.at[:, sl].set(s)
                        ambig = ambig | amb.any(axis=1)
                gdevs.append(dev)
                gsts.append(st)
            devs.append(jnp.stack(gdevs, -1))
            sts.append(jnp.stack(gsts, -1))
        return jnp.stack(devs, 1), jnp.stack(sts, 1), ambig

    # ---- execution -------------------------------------------------------
    def run(self, x, weights, count_limit: int):
        """count_limit: static int (result_max at rule level).
        → (out [N,numrep], out2, got [N], incomplete [N])."""
        spec = self.spec
        N = x.shape[0]
        p_item, p_status, p_leafrow, p_ambig = self.parent_cands(x)
        # materialize the candidate grids: the resolve chains below read
        # dozens of [:, g, r] slices, and without a barrier XLA happily
        # recomputes the whole descent per consumer (measured 16x blowup)
        p_item, p_status, p_leafrow = jax.lax.optimization_barrier(
            (p_item, p_status, p_leafrow))
        ambig_lane = p_ambig.reshape(N, -1).any(axis=1)
        leaf_pack = None
        if spec.leaf:
            if self.has_leaf:
                l_dev, l_st, l_amb = self.leaf_cands(x, p_leafrow)
                ambig_lane = ambig_lane | l_amb
            else:
                shape = p_item.shape + (spec.recurse_tries,)
                l_dev = jnp.full(shape, jnp.int32(ITEM_NONE))
                l_st = jnp.full(shape, jnp.int32(_SKIP))
            l_out = _is_out_batch(
                weights, l_dev.reshape(-1),
                jnp.repeat(x, l_dev.size // N),
                self.strategy).reshape(l_dev.shape)
            leaf_pack = jax.lax.optimization_barrier((l_dev, l_st, l_out))
        if spec.target_type == 0:
            p_out = _is_out_batch(
                weights, p_item.reshape(-1),
                jnp.repeat(x, p_item.size // N),
                self.strategy).reshape(p_item.shape)
            p_out = jax.lax.optimization_barrier(p_out)
        else:
            p_out = jnp.zeros(p_item.shape, dtype=bool)
        if spec.firstn:
            out, out2, got, inc = self._resolve_firstn(
                p_item, p_status, p_out, leaf_pack, count_limit)
        else:
            out, out2, got, inc = self._resolve_indep(
                p_item, p_status, p_out, leaf_pack, count_limit)
        return out, out2, got, inc | ambig_lane

    def _leaf_resolve(self, leaf_pack, g, r, out2, outpos, windowed):
        """Walk the leaf retry chain for slot (g, r) against current
        out2 state → (leaf_dev [N], leaf_ok [N])."""
        l_dev, l_st, l_is_out = leaf_pack
        N = l_dev.shape[0]
        NONE = jnp.int32(ITEM_NONE)
        slot_ids = jnp.arange(out2.shape[1], dtype=jnp.int32)
        ldev = jnp.full((N,), NONE)
        lok = jnp.zeros((N,), dtype=bool)
        ldone = jnp.zeros((N,), dtype=bool)
        for ft in range(l_dev.shape[-1]):
            d = l_dev[:, g, r, ft]
            st = l_st[:, g, r, ft]
            lo = l_is_out[:, g, r, ft]
            if windowed:
                lcol = jnp.any(
                    (slot_ids[None, :] < outpos[:, None]) &
                    (out2 == d[:, None]), axis=1)
            else:
                lcol = jnp.zeros((N,), dtype=bool)
            succ = (~ldone) & (st == _OK) & (~lcol) & (~lo)
            hard = (~ldone) & (st == _SKIP)
            ldev = jnp.where(succ, d, ldev)
            lok = lok | succ
            ldone = ldone | succ | hard
        return ldev, lok

    def _resolve_firstn(self, p_item, p_status, p_out, leaf_pack,
                        count_limit: int):
        spec = self.spec
        N = p_item.shape[0]
        R_out = spec.numrep
        NONE = jnp.int32(ITEM_NONE)
        out = jnp.full((N, R_out), NONE)
        out2 = jnp.full((N, R_out), NONE)
        outpos = jnp.zeros((N,), dtype=jnp.int32)
        incomplete = jnp.zeros((N,), dtype=bool)
        slot_ids = jnp.arange(R_out, dtype=jnp.int32)
        for rep in range(spec.numrep):
            g = rep if self.per_rep else 0
            placed = jnp.zeros((N,), dtype=bool)
            skipped = jnp.zeros((N,), dtype=bool)
            item_sel = jnp.full((N,), NONE)
            leaf_sel = jnp.full((N,), NONE)
            budget = self.R - rep
            for f in range(min(budget, spec.tries)):
                r = rep + f
                item = p_item[:, g, r]
                status = p_status[:, g, r]
                collide = jnp.any(
                    (slot_ids[None, :] < outpos[:, None]) &
                    (out == item[:, None]), axis=1)
                reject = status == _REJECT
                if spec.leaf:
                    ldev, lok = self._leaf_resolve(
                        leaf_pack, g, r, out2, outpos, windowed=True)
                    is_bucket = item < 0
                    leaf_val = jnp.where(is_bucket, ldev, item)
                    reject = reject | (
                        (status == _OK) & (~collide) & is_bucket & (~lok))
                else:
                    leaf_val = jnp.full((N,), NONE)
                if spec.target_type == 0:
                    reject = reject | (
                        (status == _OK) & (~collide) & p_out[:, g, r])
                ok = (status == _OK) & (~collide) & (~reject)
                skip = status == _SKIP
                active = (~placed) & (~skipped)
                place_now = active & ok
                item_sel = jnp.where(place_now, item, item_sel)
                if spec.leaf:
                    leaf_sel = jnp.where(place_now, leaf_val, leaf_sel)
                placed = placed | place_now
                skipped = skipped | (active & skip)
            if budget < spec.tries:
                incomplete = incomplete | ((~placed) & (~skipped))
            if self.per_rep:
                # grids assumed outpos == rep (pos / leaf rep_base)
                incomplete = incomplete | (placed & (outpos != rep))
            do_place = placed & (outpos < count_limit)
            sel = do_place[:, None] & (slot_ids[None, :] == outpos[:, None])
            out = jnp.where(sel, item_sel[:, None], out)
            if spec.leaf:
                out2 = jnp.where(sel, leaf_sel[:, None], out2)
            outpos = outpos + do_place.astype(jnp.int32)
        return out, out2, outpos, incomplete

    def _resolve_indep(self, p_item, p_status, p_out, leaf_pack,
                       count_limit: int):
        spec = self.spec
        N = p_item.shape[0]
        R_out = spec.numrep
        limit = min(spec.numrep, count_limit)
        NONE = jnp.int32(ITEM_NONE)
        UNDEF = jnp.int32(ITEM_UNDEF)
        active = jnp.broadcast_to(
            jnp.arange(R_out, dtype=jnp.int32) < limit, (N, R_out))
        out = jnp.where(active, UNDEF, NONE)
        out2 = jnp.where(active, UNDEF, NONE)
        dummy_pos = jnp.zeros((N,), dtype=jnp.int32)
        for f in range(self.rounds):      # already capped at spec.tries
            for rep in range(min(spec.numrep, limit)):
                r = rep + spec.numrep * f
                if r >= self.R:
                    continue
                item = p_item[:, 0, r]
                status = p_status[:, 0, r]
                pending = active[:, rep] & (out[:, rep] == UNDEF)
                collide = jnp.any(out == item[:, None], axis=1)
                hard = status == _SKIP
                if spec.leaf:
                    ldev, _ = self._leaf_resolve(
                        leaf_pack, 0, r, out2, dummy_pos, windowed=False)
                    is_bucket = item < 0
                    leaf_val = jnp.where(is_bucket, ldev, item)
                    leaf_fail = is_bucket & (ldev == NONE)
                else:
                    leaf_val = jnp.full((N,), NONE)
                    leaf_fail = jnp.zeros((N,), dtype=bool)
                out_dev = (status == _OK) & p_out[:, 0, r] \
                    if spec.target_type == 0 \
                    else jnp.zeros((N,), dtype=bool)
                ok = (status == _OK) & (~collide) & (~leaf_fail) & \
                    (~out_dev)
                place = pending & ok
                out = out.at[:, rep].set(
                    jnp.where(place, item, out[:, rep]))
                out2 = out2.at[:, rep].set(
                    jnp.where(place, leaf_val, out2[:, rep]))
                pin = pending & hard & (~ok)
                out = out.at[:, rep].set(jnp.where(pin, NONE, out[:, rep]))
                out2 = out2.at[:, rep].set(
                    jnp.where(pin, NONE, out2[:, rep]))
        incomplete = jnp.any(out == UNDEF, axis=1) \
            if self.rounds < spec.tries \
            else jnp.zeros((N,), dtype=bool)
        out = jnp.where(out == UNDEF, NONE, out)
        out2 = jnp.where(out2 == UNDEF, NONE, out2)
        got = jnp.full((N,), jnp.int32(limit))
        return out, out2, got, incomplete


# ------------------------------------------------------------ rule driver ---

class FastMapper:
    """Candidate-parallel batched do_rule for one CrushMap.

    map_batch returns (results [N, result_max], incomplete [N]): lanes
    flagged incomplete must be recomputed by a bit-exact fallback (the
    native C++ mapper or the scalar oracle).
    """

    def __init__(self, cmap: CrushMap, choose_args_key: object = None,
                 strategy: Optional[str] = None,
                 extra_tries: Optional[int] = None):
        self.cmap = cmap
        self.compiled = compile_map(cmap, choose_args_key, n_positions=1)
        if not self.compiled.all_straw2:
            raise UnsupportedMapError(
                "fast mapper vectorizes straw2 buckets only; legacy "
                "algs run through the general mapper")
        if strategy is None:
            cfg = _config().get("lookup_strategy")
            strategy = None if cfg == "auto" else cfg
        if strategy is None:
            strategy = "gather" if jax.devices()[0].platform == "cpu" \
                else "onehot"
        self.strategy = strategy
        self.dt = self.compiled.tables(strategy)
        if extra_tries is None:
            extra_tries = int(_config().get("fastmap_extra_tries"))
        self.extra = max(2, extra_tries)
        self.exact_select = _config().get("straw2_select") == "exact"
        self._jitted = {}
        self._plans: Dict[Tuple[int, int], list] = {}

    # ---- host-side rule analysis ----------------------------------------
    def _plan(self, ruleno: int, result_max: int) -> list:
        """Parse the rule into a static plan:
        ("choose", _FastChoose) | ("choose_dead",) | ("emit_take", item)
        | ("emit",)."""
        key = (ruleno, result_max)
        if key in self._plans:
            return self._plans[key]
        cmap = self.cmap
        t = cmap.tunables
        rule = cmap.rules[ruleno]
        choose_tries = t.choose_total_tries + 1
        choose_leaf_tries = 0
        vary_r = t.chooseleaf_vary_r
        stable = bool(t.chooseleaf_stable)
        plan = []
        pending_take: Optional[int] = None
        took_choose = False
        for op, arg1, arg2 in rule.steps:
            if op == RULE_TAKE:
                pending_take = arg1
                took_choose = False
            elif op == RULE_SET_CHOOSE_TRIES:
                if arg1 > 0:
                    choose_tries = arg1
            elif op == RULE_SET_CHOOSELEAF_TRIES:
                if arg1 > 0:
                    choose_leaf_tries = arg1
            elif op in (RULE_SET_CHOOSE_LOCAL_TRIES,
                        RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES):
                if arg1 > 0:
                    raise UnsupportedMapError("local_tries rule step")
            elif op == RULE_SET_CHOOSELEAF_VARY_R:
                if arg1 >= 0:
                    vary_r = arg1
            elif op == RULE_SET_CHOOSELEAF_STABLE:
                if arg1 >= 0:
                    stable = bool(arg1)
            elif op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSELEAF_FIRSTN,
                        RULE_CHOOSE_INDEP, RULE_CHOOSELEAF_INDEP):
                if took_choose:
                    raise UnsupportedRuleError(
                        "chained choose steps (choose feeding choose)")
                took_choose = True
                firstn = op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSELEAF_FIRSTN)
                leaf = op in (RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP)
                numrep = arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        took_choose = False
                        continue
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif t.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                else:
                    recurse_tries = choose_leaf_tries or 1
                if recurse_tries > 4:
                    raise UnsupportedRuleError(
                        f"recurse_tries {recurse_tries} too large for "
                        "the candidate grid")
                if pending_take is None or pending_take >= 0 or \
                        cmap.bucket(pending_take) is None:
                    plan.append(("choose_dead",))
                    continue
                spec = _ChooseSpec(
                    firstn=firstn, leaf=leaf, numrep=numrep,
                    target_type=arg2, tries=choose_tries,
                    recurse_tries=recurse_tries, vary_r=vary_r,
                    stable=stable, root=pending_take)
                plan.append(("choose", _FastChoose(
                    cmap, self.compiled, self.dt, spec, self.strategy,
                    self.extra, exact_select=self.exact_select)))
            elif op == RULE_EMIT:
                if not took_choose and pending_take is not None:
                    ok = (0 <= pending_take < cmap.max_devices) or \
                        (cmap.bucket(pending_take) is not None)
                    plan.append(("emit_take",
                                 pending_take if ok else None))
                else:
                    plan.append(("emit",))
                pending_take = None
                took_choose = False
            else:
                raise UnsupportedRuleError(f"rule op {op}")
        self._plans[key] = plan
        return plan

    def _trace(self, plan, result_max: int, xs, weights):
        N = xs.shape[0]
        NONE = jnp.int32(ITEM_NONE)
        result = jnp.full((N, result_max), NONE)
        rpos = jnp.zeros((N,), dtype=jnp.int32)
        incomplete = jnp.zeros((N,), dtype=bool)
        res_ids = jnp.arange(result_max, dtype=jnp.int32)
        pend_out = None            # (vals [N, n], count [N]) awaiting emit
        x = xs.astype(jnp.int32)
        for entry in plan:
            kind = entry[0]
            if kind == "choose":
                fc: _FastChoose = entry[1]
                out, out2, got, inc = fc.run(x, weights, result_max)
                incomplete = incomplete | inc
                pend_out = (out2 if fc.spec.leaf else out, got)
            elif kind == "choose_dead":
                pend_out = (jnp.full((N, 1), NONE),
                            jnp.zeros((N,), dtype=jnp.int32))
            elif kind == "emit_take":
                if entry[1] is None:
                    pend_out = None
                    continue
                can = rpos < result_max
                sel = can[:, None] & (res_ids[None, :] == rpos[:, None])
                result = jnp.where(sel, jnp.int32(entry[1]), result)
                rpos = rpos + can.astype(jnp.int32)
                pend_out = None
            else:   # emit
                if pend_out is None:
                    continue
                vals, count = pend_out
                for i in range(vals.shape[1]):
                    ok = (i < count) & (rpos < result_max)
                    sel = ok[:, None] & (res_ids[None, :] == rpos[:, None])
                    result = jnp.where(sel, vals[:, i:i + 1], result)
                    rpos = rpos + ok.astype(jnp.int32)
                pend_out = None
        return result, incomplete

    # ---- public ----------------------------------------------------------
    def _get_jitted(self, ruleno: int, result_max: int, mesh=None):
        from ..parallel.mesh import mesh_cache_key
        key = (ruleno, result_max,
               mesh_cache_key(mesh) if mesh is not None else None)
        if key not in self._jitted:
            plan = self._plan(ruleno, result_max)
            inner = functools.partial(self._trace, plan, result_max)

            # one-hot tables hold integer values up to 2^16 (ids, row
            # indices, weight halves); TPU's DEFAULT f32 matmul runs the
            # MXU in bf16 and silently rounds them (observed: device id
            # 9693 -> 9728).  HIGHEST forces f32-exact passes.
            def fn(xs, weights):
                with jax.default_matmul_precision("highest"):
                    return inner(xs, weights)

            if mesh is None:
                self._jitted[key] = jax.jit(fn)
            else:
                from ..parallel.mesh import lane_shardings
                batch, repl = lane_shardings(mesh)
                self._jitted[key] = jax.jit(
                    fn, in_shardings=(batch, repl),
                    out_shardings=(batch, batch))
        return self._jitted[key]

    def grid_width(self, ruleno: int, result_max: int) -> int:
        return max((e[1].R * (e[1].spec.numrep if e[1].per_rep else 1)
                    for e in self._plan(ruleno, result_max)
                    if e[0] == "choose"), default=1)

    def max_level_width(self, ruleno: int, result_max: int) -> int:
        """Widest level table any descent touches (the S in the [rows, S]
        working set)."""
        width = 1
        for e in self._plan(ruleno, result_max):
            if e[0] != "choose":
                continue
            fc: _FastChoose = e[1]
            for levels in list(fc.levels.values()) + \
                    list(fc.leaf_levels.values()):
                for lvl in levels:
                    width = max(width, lvl.Sl)
        return width


    def map_batch(self, ruleno: int, xs, result_max: int,
                  weights: Sequence[int], mesh=None,
                  readback: bool = True):
        """→ (results [N, result_max] i32, incomplete [N] bool).

        Chunks stream through one compiled executable and stay ON DEVICE
        until a single final readback: device→host transfers through the
        driver tunnel cost ~0.25 s of latency each (measured), which at
        per-chunk granularity was 25x the actual compute time.

        ``readback=False`` returns the DEVICE arrays (padded to the
        chunk cap) — consumers that keep working on device (remap
        diffs, recovery planning) skip the multi-MB host transfer
        entirely, and benchmarks can meter compute vs readback.

        With ``mesh`` the chunk cap scales by ``mesh.size`` and the
        lanes shard over every mesh axis row-major (lane_shardings) —
        the sweep is layout-agnostic across the 1-D ring and the 2-D
        (stripe, shard) plane.
        """
        if ruleno < 0 or ruleno >= self.cmap.max_rules or \
                self.cmap.rules[ruleno] is None:
            raise ValueError(f"no rule {ruleno}")
        self._plan(ruleno, result_max)       # raise Unsupported early
        jitted = self._get_jitted(ruleno, result_max, mesh)
        w = np.zeros(self.compiled.max_devices, dtype=np.int32)
        w_in = np.asarray(weights, dtype=np.int64)
        w[:min(len(w_in), len(w))] = w_in[:len(w)]
        w_dev = jnp.asarray(w)
        xs_np = np.asarray(xs, dtype=np.int64).astype(np.uint32) \
            .astype(np.int32)
        n = len(xs_np)
        gw = self.grid_width(ruleno, result_max)
        # candidate grids multiply lane width by R*G, and each level
        # materializes ~4 [rows, S] f32 buffers (hash, qa, selects) —
        # cap lanes so rows*S stays inside the HBM budget
        max_grid = int(_config().get("fastmap_max_grid_lanes"))
        budget_rows_s = int(_config().get("fastmap_max_grid_mib")) \
            * (1 << 20) // 16          # bytes / (4 buffers x f32)
        width = self.max_level_width(ruleno, result_max)
        cap = max(1 << 10, min(max_grid // gw,
                               budget_rows_s // (gw * width)))
        cap *= (mesh.size if mesh is not None else 1)
        if n > cap:
            pad = (-n) % cap                    # cap is mesh-aligned
        elif mesh is not None:
            pad = (-n) % mesh.size
        else:
            pad = 0
        xs_pad = np.concatenate([xs_np, xs_np[:1].repeat(pad)]) \
            if pad else xs_np
        outs, incs = [], []
        for i in range(0, len(xs_pad), cap):
            o, inc = jitted(jnp.asarray(xs_pad[i:i + cap]), w_dev)
            outs.append(o)
            incs.append(inc)
        if len(outs) == 1:
            out_d, inc_d = outs[0], incs[0]
        else:
            out_d = jnp.concatenate(outs)
            inc_d = jnp.concatenate(incs)
        if not readback:
            return out_d, inc_d
        return np.asarray(out_d)[:n], np.asarray(inc_d)[:n]
