"""Map-construction helpers (the builder.c role for common topologies).

One canonical straw2 hierarchy builder shared by benchmarks, the driver
dry-run, and tests — root → [racks →] hosts → osds.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .crush_map import (BUCKET_STRAW2, Bucket, CrushMap, Tunables,
                        WEIGHT_ONE)

TYPE_OSD, TYPE_HOST, TYPE_RACK, TYPE_ROOT = 0, 1, 2, 3


def build_flat_cluster(n_hosts: int = 6, osds_per_host: int = 4,
                       n_racks: int = 0, seed: int = 0,
                       tunables: Optional[Tunables] = None,
                       weight_jitter: bool = False
                       ) -> Tuple[CrushMap, int]:
    """Build root → [racks →] hosts → osds, all straw2.

    Returns (map, root_bucket_id).  With weight_jitter, per-osd weights
    are randomized in [0.5, 1.5) to exercise weighted selection.
    """
    rng = np.random.default_rng(seed)
    m = CrushMap(tunables=tunables or Tunables.profile("jewel"))
    m.type_names = {TYPE_OSD: "osd", TYPE_HOST: "host", TYPE_RACK: "rack",
                    TYPE_ROOT: "root"}
    osd = 0
    host_ids = []
    for h in range(n_hosts):
        items, weights = [], []
        for _ in range(osds_per_host):
            items.append(osd)
            w = WEIGHT_ONE
            if weight_jitter:
                w = int(WEIGHT_ONE * (0.5 + rng.random()))
            weights.append(w)
            osd += 1
        hid = -1 - len(m.buckets)
        m.add_bucket(Bucket(id=hid, alg=BUCKET_STRAW2, type=TYPE_HOST,
                            items=items, weights=weights))
        m.bucket_names[hid] = f"host{h}"
        host_ids.append(hid)
    group_ids = host_ids
    if n_racks:
        racks = []
        per = max(1, len(host_ids) // n_racks)
        for r in range(n_racks):
            hs = host_ids[r * per:(r + 1) * per] or host_ids[-1:]
            rid = -1 - len(m.buckets)
            m.add_bucket(Bucket(
                id=rid, alg=BUCKET_STRAW2, type=TYPE_RACK, items=list(hs),
                weights=[sum(m.bucket(h).weights) for h in hs]))
            m.bucket_names[rid] = f"rack{r}"
            racks.append(rid)
        group_ids = racks
    root_id = -1 - len(m.buckets)
    m.add_bucket(Bucket(
        id=root_id, alg=BUCKET_STRAW2, type=TYPE_ROOT, items=list(group_ids),
        weights=[sum(m.bucket(g).weights) for g in group_ids]))
    m.bucket_names[root_id] = "default"
    m.finalize()
    return m, root_id
