"""Map-construction helpers (the builder.c role).

One canonical straw2 hierarchy builder shared by benchmarks, the driver
dry-run, and tests — root → [racks →] hosts → osds — plus the mutation
surface builder.c exposes: remove_item, reweight_item,
reweight_subtree, move_bucket (crush_remove_item / crush_reweight_* /
CrushWrapper::move_bucket roles), all with ancestor weight
propagation and derived-table refresh.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .crush_map import (BUCKET_STRAW2, BUCKET_UNIFORM, Bucket, CrushMap,
                        Tunables, WEIGHT_ONE)

TYPE_OSD, TYPE_HOST, TYPE_RACK, TYPE_ROOT = 0, 1, 2, 3


def build_flat_cluster(n_hosts: int = 6, osds_per_host: int = 4,
                       n_racks: int = 0, seed: int = 0,
                       tunables: Optional[Tunables] = None,
                       weight_jitter: bool = False
                       ) -> Tuple[CrushMap, int]:
    """Build root → [racks →] hosts → osds, all straw2.

    Returns (map, root_bucket_id).  With weight_jitter, per-osd weights
    are randomized in [0.5, 1.5) to exercise weighted selection.
    """
    rng = np.random.default_rng(seed)
    m = CrushMap(tunables=tunables or Tunables.profile("jewel"))
    m.type_names = {TYPE_OSD: "osd", TYPE_HOST: "host", TYPE_RACK: "rack",
                    TYPE_ROOT: "root"}
    osd = 0
    host_ids = []
    for h in range(n_hosts):
        items, weights = [], []
        for _ in range(osds_per_host):
            items.append(osd)
            w = WEIGHT_ONE
            if weight_jitter:
                w = int(WEIGHT_ONE * (0.5 + rng.random()))
            weights.append(w)
            osd += 1
        hid = -1 - len(m.buckets)
        m.add_bucket(Bucket(id=hid, alg=BUCKET_STRAW2, type=TYPE_HOST,
                            items=items, weights=weights))
        m.bucket_names[hid] = f"host{h}"
        host_ids.append(hid)
    group_ids = host_ids
    if n_racks:
        racks = []
        per = max(1, len(host_ids) // n_racks)
        for r in range(n_racks):
            hs = host_ids[r * per:(r + 1) * per] or host_ids[-1:]
            rid = -1 - len(m.buckets)
            m.add_bucket(Bucket(
                id=rid, alg=BUCKET_STRAW2, type=TYPE_RACK, items=list(hs),
                weights=[sum(m.bucket(h).weights) for h in hs]))
            m.bucket_names[rid] = f"rack{r}"
            racks.append(rid)
        group_ids = racks
    root_id = -1 - len(m.buckets)
    m.add_bucket(Bucket(
        id=root_id, alg=BUCKET_STRAW2, type=TYPE_ROOT, items=list(group_ids),
        weights=[sum(m.bucket(g).weights) for g in group_ids]))
    m.bucket_names[root_id] = "default"
    m.finalize()
    return m, root_id


# ------------------------------------------------------- map mutations ----

def find_parent(cmap: CrushMap, item_id: int) -> Optional[int]:
    """Bucket id containing ``item_id`` (items appear at most once in a
    well-formed map)."""
    for b in cmap.buckets:
        if b is not None and item_id in b.items:
            return b.id
    return None


def _ancestors(cmap: CrushMap, bucket_id: int) -> List[int]:
    out = []
    cur = find_parent(cmap, bucket_id)
    while cur is not None:
        out.append(cur)
        cur = find_parent(cmap, cur)
    return out


def _adjust_ancestor_weights(cmap: CrushMap, child_id: int,
                             delta: int) -> None:
    """Propagate a weight change up the chain (builder.c
    crush_reweight_bucket's role)."""
    cur = child_id
    parent = find_parent(cmap, cur)
    while parent is not None:
        pb = cmap.bucket(parent)
        if pb.alg == BUCKET_UNIFORM:
            break                # uniform interiors don't track items
        pos = pb.items.index(cur)
        pb.weights[pos] = max(0, pb.weights[pos] + delta)
        cur = parent
        parent = find_parent(cmap, cur)


def remove_item(cmap: CrushMap, item_id: int) -> None:
    """Detach a device or (empty) bucket from its parent, propagating
    the weight loss upward (crush_remove_item role); removing a bucket
    also frees its slot."""
    if item_id < 0:
        b = cmap.bucket(item_id)
        if b is None:
            raise KeyError(f"no bucket {item_id}")
        if b.items:
            raise ValueError(
                f"bucket {item_id} not empty: remove its items first")
    parent = find_parent(cmap, item_id)
    if parent is not None:
        pb = cmap.bucket(parent)
        pos = pb.items.index(item_id)
        w = pb.item_weight(pos)
        del pb.items[pos]
        if pb.alg != BUCKET_UNIFORM:
            del pb.weights[pos]
        _adjust_ancestor_weights(cmap, parent, -w)
    if item_id < 0:
        cmap.buckets[-1 - item_id] = None
        cmap.bucket_names.pop(item_id, None)
    cmap.finalize()


def reweight_item(cmap: CrushMap, item_id: int, new_weight: int) -> None:
    """Set one item's weight in its parent and propagate the delta
    (crush_reweight role)."""
    parent = find_parent(cmap, item_id)
    if parent is None:
        raise KeyError(f"item {item_id} not in any bucket")
    pb = cmap.bucket(parent)
    if pb.alg == BUCKET_UNIFORM:
        raise ValueError("cannot reweight one item of a uniform bucket")
    pos = pb.items.index(item_id)
    delta = new_weight - pb.weights[pos]
    pb.weights[pos] = new_weight
    _adjust_ancestor_weights(cmap, parent, delta)
    cmap.finalize()


def reweight_subtree(cmap: CrushMap, bucket_id: int,
                     leaf_weight: int) -> None:
    """Set EVERY device weight under the subtree and rebuild interior
    weights bottom-up (CrushWrapper::adjust_subtree_weight role)."""
    b = cmap.bucket(bucket_id)
    if b is None:
        raise KeyError(f"no bucket {bucket_id}")

    def rebuild(bid: int) -> int:
        bk = cmap.bucket(bid)
        total = 0
        for pos, child in enumerate(bk.items):
            w = rebuild(child) if child < 0 else leaf_weight
            if bk.alg != BUCKET_UNIFORM:
                bk.weights[pos] = w
            total += w
        if bk.alg == BUCKET_UNIFORM:
            bk.weights = [leaf_weight]
            total = leaf_weight * bk.size
        return total

    old = b.weight
    new = rebuild(bucket_id)
    _adjust_ancestor_weights(cmap, bucket_id, new - old)
    cmap.finalize()


def move_bucket(cmap: CrushMap, bucket_id: int,
                new_parent_id: int) -> None:
    """Detach a subtree and reattach it under another bucket with its
    weight (CrushWrapper::move_bucket role); cycles rejected."""
    b = cmap.bucket(bucket_id)
    np_b = cmap.bucket(new_parent_id)
    if b is None or np_b is None:
        raise KeyError("bucket and new parent must exist")
    if new_parent_id == bucket_id or \
            bucket_id in _ancestors(cmap, new_parent_id):
        raise ValueError("move would create a cycle")
    if np_b.alg == BUCKET_UNIFORM:
        raise ValueError("cannot move into a uniform bucket")
    w = b.weight
    parent = find_parent(cmap, bucket_id)
    if parent is not None:
        pb = cmap.bucket(parent)
        pos = pb.items.index(bucket_id)
        del pb.items[pos]
        if pb.alg != BUCKET_UNIFORM:
            del pb.weights[pos]
        _adjust_ancestor_weights(cmap, parent, -w)
    np_b.items.append(bucket_id)
    np_b.weights.append(w)
    _adjust_ancestor_weights(cmap, new_parent_id, w)
    cmap.finalize()
