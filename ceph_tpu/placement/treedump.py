"""CrushLocation + tree dumper.

Roles of src/crush/CrushLocation.{h,cc} (where does this host/device
sit in the hierarchy — the crush position a daemon announces on boot)
and src/crush/CrushTreeDumper.h (the `ceph osd tree` renderer walking
buckets depth-first with per-node type/name/weight).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .crush_map import CrushMap


def crush_location(cmap: CrushMap, item: int) -> Dict[str, str]:
    """{type_name: bucket_name} ancestors of a device or bucket —
    the CrushLocation lookup (e.g. {'host': 'node1', 'root':
    'default'})."""
    parents: Dict[int, int] = {}
    for b in cmap.buckets:
        if b is None:
            continue
        for it in b.items:
            parents[it] = b.id
    out: Dict[str, str] = {}
    cur = item
    seen = set()
    while cur in parents and cur not in seen:
        seen.add(cur)
        cur = parents[cur]
        b = cmap.bucket(cur)
        if b is None:
            break
        tname = cmap.type_names.get(b.type, f"type{b.type}")
        out[tname] = cmap.bucket_names.get(cur, f"bucket{-1 - cur}")
    return out


def _fmt_weight(w: int) -> str:
    return f"{w / 0x10000:.5f}"


def tree_dump(cmap: CrushMap,
              device_weights: Optional[Dict[int, int]] = None
              ) -> str:
    """`ceph osd tree`-style text: depth-first from roots, one row per
    node with id, class, weight, type and name."""
    shadows = set(cmap.class_bucket_ids.values())
    children = set()
    for b in cmap.buckets:
        if b is None or b.id in shadows:
            continue
        for it in b.items:
            if it < 0:
                children.add(it)
    roots = [b.id for b in cmap.buckets
             if b is not None and b.id not in children
             and b.id not in shadows]
    lines = ["ID    CLASS  WEIGHT    TYPE NAME"]

    def emit(node: int, depth: int, weight: int) -> None:
        pad = "    " * depth
        if node >= 0:
            cls = cmap.device_classes.get(node, "")
            name = cmap.device_names.get(node, f"osd.{node}")
            lines.append(f"{node:>4}  {cls:<5}  {_fmt_weight(weight):>8}"
                         f"  {pad}{name}")
            return
        b = cmap.bucket(node)
        if b is None:
            return
        tname = cmap.type_names.get(b.type, f"type{b.type}")
        name = cmap.bucket_names.get(node, f"bucket{-1 - node}")
        lines.append(f"{node:>4}         {_fmt_weight(b.weight):>8}"
                     f"  {pad}{tname} {name}")
        for pos, it in enumerate(b.items):
            emit(it, depth + 1, b.item_weight(pos))

    for r in sorted(roots, reverse=True):
        b = cmap.bucket(r)
        emit(r, 0, b.weight if b else 0)
    return "\n".join(lines) + "\n"
