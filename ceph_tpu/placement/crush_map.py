"""CRUSH map data model: buckets, rules, tunables.

Python-native equivalent of the reference's `struct crush_map` world
(src/crush/crush.h:354-465) plus the builder math that derives per-algorithm
auxiliary arrays (src/crush/builder.c): straw lengths for STRAW buckets,
prefix sums for LIST buckets, and the interior-node weight tree for TREE
buckets.  The map is a pure value — mapping never mutates it — which is what
makes the batched TPU mapper a pure jitted function of (map, x).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

# bucket algorithms (crush.h:140-190)
BUCKET_UNIFORM = 1
BUCKET_LIST = 2
BUCKET_TREE = 3
BUCKET_STRAW = 4
BUCKET_STRAW2 = 5

ALG_NAMES = {BUCKET_UNIFORM: "uniform", BUCKET_LIST: "list", BUCKET_TREE: "tree",
             BUCKET_STRAW: "straw", BUCKET_STRAW2: "straw2"}
ALG_BY_NAME = {v: k for k, v in ALG_NAMES.items()}

HASH_RJENKINS1 = 0

# rule opcodes (crush.h:55-69)
RULE_NOOP = 0
RULE_TAKE = 1
RULE_CHOOSE_FIRSTN = 2
RULE_CHOOSE_INDEP = 3
RULE_EMIT = 4
RULE_CHOOSELEAF_FIRSTN = 6
RULE_CHOOSELEAF_INDEP = 7
RULE_SET_CHOOSE_TRIES = 8
RULE_SET_CHOOSELEAF_TRIES = 9
RULE_SET_CHOOSE_LOCAL_TRIES = 10
RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
RULE_SET_CHOOSELEAF_VARY_R = 12
RULE_SET_CHOOSELEAF_STABLE = 13

OP_NAMES = {
    RULE_NOOP: "noop", RULE_TAKE: "take", RULE_CHOOSE_FIRSTN: "choose firstn",
    RULE_CHOOSE_INDEP: "choose indep", RULE_EMIT: "emit",
    RULE_CHOOSELEAF_FIRSTN: "chooseleaf firstn", RULE_CHOOSELEAF_INDEP: "chooseleaf indep",
    RULE_SET_CHOOSE_TRIES: "set_choose_tries",
    RULE_SET_CHOOSELEAF_TRIES: "set_chooseleaf_tries",
    RULE_SET_CHOOSE_LOCAL_TRIES: "set_choose_local_tries",
    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES: "set_choose_local_fallback_tries",
    RULE_SET_CHOOSELEAF_VARY_R: "set_chooseleaf_vary_r",
    RULE_SET_CHOOSELEAF_STABLE: "set_chooseleaf_stable",
}

ITEM_UNDEF = 0x7FFFFFFE  # crush.h:33
ITEM_NONE = 0x7FFFFFFF   # crush.h:37

WEIGHT_ONE = 0x10000     # 16.16 fixed point 1.0


@dataclass(frozen=True)
class Tunables:
    """Mapping behavior knobs (crush.h:377-447, profiles CrushWrapper.h:144-210)."""
    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    choose_total_tries: int = 50
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1
    straw_calc_version: int = 1
    allowed_bucket_algs: int = (1 << BUCKET_UNIFORM) | (1 << BUCKET_LIST) | \
        (1 << BUCKET_STRAW) | (1 << BUCKET_STRAW2) | (1 << BUCKET_TREE)

    @classmethod
    def profile(cls, name: str) -> "Tunables":
        profiles = {
            "argonaut": dict(choose_local_tries=2, choose_local_fallback_tries=5,
                             choose_total_tries=19, chooseleaf_descend_once=0,
                             chooseleaf_vary_r=0, chooseleaf_stable=0),
            "bobtail": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                            choose_total_tries=50, chooseleaf_descend_once=1,
                            chooseleaf_vary_r=0, chooseleaf_stable=0),
            "firefly": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                            choose_total_tries=50, chooseleaf_descend_once=1,
                            chooseleaf_vary_r=1, chooseleaf_stable=0),
            "hammer": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                           choose_total_tries=50, chooseleaf_descend_once=1,
                           chooseleaf_vary_r=1, chooseleaf_stable=0),
            "jewel": dict(choose_local_tries=0, choose_local_fallback_tries=0,
                          choose_total_tries=50, chooseleaf_descend_once=1,
                          chooseleaf_vary_r=1, chooseleaf_stable=1),
        }
        profiles["legacy"] = profiles["argonaut"]
        profiles["optimal"] = profiles["jewel"]
        profiles["default"] = profiles["jewel"]
        return cls(**profiles[name])


# -------------------------------------------------------------- builders ----

def _tree_height(n: int) -> int:
    h = 0
    while (n & 1) == 0:
        h += 1
        n >>= 1
    return h


def _tree_parent(n: int) -> int:
    h = _tree_height(n)
    if n & (1 << (h + 1)):
        return n - (1 << h)
    return n + (1 << h)


def tree_left(n: int) -> int:
    return n - (1 << (_tree_height(n) - 1))


def tree_right(n: int) -> int:
    return n + (1 << (_tree_height(n) - 1))


def _calc_tree_depth(size: int) -> int:
    if size == 0:
        return 0
    depth, t = 1, size - 1
    while t:
        t >>= 1
        depth += 1
    return depth


def calc_straws(weights: Sequence[int], version: int) -> List[int]:
    """straw-v1 scaler (builder.c:431-547) — kept for legacy STRAW buckets."""
    size = len(weights)
    # stable reverse-sort by weight, insertion order preserved for equals
    reverse = list(range(size))
    reverse.sort(key=lambda i: (weights[i], i))
    straws = [0] * size
    numleft = size
    straw = 1.0
    wbelow = 0.0
    lastw = 0.0
    i = 0
    while i < size:
        ri = reverse[i]
        if version == 0:
            if weights[ri] == 0:
                straws[ri] = 0
                i += 1
                continue
            straws[ri] = int(straw * 0x10000)
            i += 1
            if i == size:
                break
            if weights[reverse[i]] == weights[reverse[i - 1]]:
                continue
            wbelow += (float(weights[reverse[i - 1]]) - lastw) * numleft
            j = i
            while j < size:
                if weights[reverse[j]] == weights[reverse[i]]:
                    numleft -= 1
                else:
                    break
                j += 1
            wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = float(weights[reverse[i - 1]])
        else:
            if weights[ri] == 0:
                straws[ri] = 0
                i += 1
                numleft -= 1
                continue
            straws[ri] = int(straw * 0x10000)
            i += 1
            if i == size:
                break
            wbelow += (float(weights[reverse[i - 1]]) - lastw) * numleft
            numleft -= 1
            wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = float(weights[reverse[i - 1]])
    return straws


@dataclass
class Bucket:
    """One interior node of the CRUSH hierarchy (crush.h:229-341)."""
    id: int                      # negative
    alg: int
    type: int                    # user-defined type (0 = device)
    items: List[int]
    weights: List[int]           # 16.16 fixed per item (uniform: weights[0] applies)
    hash: int = HASH_RJENKINS1
    # derived (filled by finalize_derived)
    straws: Optional[List[int]] = None        # STRAW
    sum_weights: Optional[List[int]] = None   # LIST prefix sums
    node_weights: Optional[List[int]] = None  # TREE interior nodes
    num_nodes: int = 0

    @property
    def size(self) -> int:
        return len(self.items)

    @property
    def weight(self) -> int:
        if self.alg == BUCKET_UNIFORM:
            return (self.weights[0] if self.weights else 0) * self.size
        return sum(self.weights)

    def item_weight(self, pos: int) -> int:
        if self.alg == BUCKET_UNIFORM:
            return self.weights[0] if self.weights else 0
        return self.weights[pos]

    def finalize_derived(self, straw_calc_version: int) -> None:
        # derived tables are __u32 in the reference (crush.h
        # crush_bucket_list::sum_weights, crush_bucket_tree::node_weights,
        # crush_bucket_straw::straws, filled by builder.c) — wrap to
        # mod-2^32 HERE so every consumer (scalar oracle, xla mapper,
        # native bridge) sees identical u32 semantics
        if self.alg == BUCKET_LIST:
            acc, sums = 0, []
            for w in self.weights:
                acc = (acc + w) & 0xFFFFFFFF
                sums.append(acc)
            self.sum_weights = sums
        elif self.alg == BUCKET_TREE:
            depth = _calc_tree_depth(self.size)
            self.num_nodes = 1 << depth
            nw = [0] * self.num_nodes
            for i, w in enumerate(self.weights):
                node = ((i + 1) << 1) - 1
                nw[node] = w & 0xFFFFFFFF
                for _ in range(1, depth):
                    node = _tree_parent(node)
                    nw[node] = (nw[node] + w) & 0xFFFFFFFF
            self.node_weights = nw
        elif self.alg == BUCKET_STRAW:
            self.straws = [s & 0xFFFFFFFF
                           for s in calc_straws(self.weights,
                                                straw_calc_version)]


@dataclass
class ChooseArg:
    """Per-bucket positional weight-set override (crush.h choose_args;
    consumed at mapper.c:309-326)."""
    ids: Optional[List[int]] = None
    weight_set: Optional[List[List[int]]] = None  # [position][item] 16.16


@dataclass
class Rule:
    """A compiled placement rule: a list of (op, arg1, arg2) steps."""
    steps: List[Tuple[int, int, int]]
    name: str = ""
    ruleset: int = 0
    type: int = 1          # 1 replicated, 3 erasure (pool semantics)
    min_size: int = 1
    max_size: int = 10


@dataclass
class CrushMap:
    """The full placement policy value.

    `buckets[i]` holds bucket with id `-1-i` (may be None);
    devices are non-negative ids < max_devices.
    """
    buckets: List[Optional[Bucket]] = field(default_factory=list)
    rules: List[Optional[Rule]] = field(default_factory=list)
    tunables: Tunables = field(default_factory=Tunables)
    max_devices: int = 0
    choose_args: Dict[object, List[Optional[ChooseArg]]] = field(default_factory=dict)
    # CrushWrapper-level metadata (names, types, device classes)
    type_names: Dict[int, str] = field(default_factory=dict)
    bucket_names: Dict[int, str] = field(default_factory=dict)
    device_names: Dict[int, str] = field(default_factory=dict)
    device_classes: Dict[int, str] = field(default_factory=dict)
    # (original bucket id, class) -> shadow bucket id (CrushWrapper.h:66
    # class_bucket equivalent; shadow trees are materialized as ordinary
    # buckets so every mapper handles device classes natively)
    class_bucket_ids: Dict[Tuple[int, str], int] = field(default_factory=dict)

    # ------------------------------------------------------------- build ----

    def bucket(self, bid: int) -> Optional[Bucket]:
        idx = -1 - bid
        if idx < 0 or idx >= len(self.buckets):
            return None
        return self.buckets[idx]

    def add_bucket(self, bucket: Bucket) -> int:
        if bucket.id >= 0:
            raise ValueError("bucket ids must be negative")
        idx = -1 - bucket.id
        while len(self.buckets) <= idx:
            self.buckets.append(None)
        if self.buckets[idx] is not None:
            raise ValueError(f"bucket id {bucket.id} already in use")
        self.buckets[idx] = bucket
        return bucket.id

    def next_bucket_id(self) -> int:
        for i, b in enumerate(self.buckets):
            if b is None:
                return -1 - i
        return -1 - len(self.buckets)

    def add_rule(self, rule: Rule, ruleno: int = -1) -> int:
        if ruleno < 0:
            ruleno = len(self.rules)
        while len(self.rules) <= ruleno:
            self.rules.append(None)
        self.rules[ruleno] = rule
        return ruleno

    def finalize(self) -> None:
        """Compute max_devices and per-bucket derived arrays (builder.c:crush_finalize)."""
        maxdev = 0
        for b in self.buckets:
            if b is None:
                continue
            for it in b.items:
                if it >= 0:
                    maxdev = max(maxdev, it + 1)
            b.finalize_derived(self.tunables.straw_calc_version)
        self.max_devices = max(self.max_devices, maxdev)

    def build_class_shadow(self, root_id: int, cls: str,
                           preferred_ids: Optional[Dict[Tuple[int, str],
                                                        int]] = None) -> int:
        """Clone the hierarchy under ``root_id`` keeping only devices of
        device class ``cls`` (CrushWrapper device_class_clone semantics:
        per-class shadow trees that `step take <bucket> class <cls>`
        selects from; reference src/crush/CrushWrapper.h:66 class_bucket,
        CrushWrapper.cc device_class_clone).

        The shadow is materialized as ordinary buckets (same alg/hash,
        filtered items, reweighted interiors), so the scalar and batched
        mappers need no class awareness.  Idempotent per (bucket, class);
        ``preferred_ids`` pins shadow ids (the compiler's `id -N class c`
        lines).
        """
        if self.bucket(root_id) is None:
            raise ValueError(f"no bucket {root_id}")
        prefer = preferred_ids or {}

        def clone(bid: int) -> int:
            key = (bid, cls)
            if key in self.class_bucket_ids:
                return self.class_bucket_ids[key]
            b = self.bucket(bid)
            items: List[int] = []
            weights: List[int] = []
            for pos, it in enumerate(b.items):
                if it >= 0:
                    if self.device_classes.get(it) == cls:
                        items.append(it)
                        weights.append(b.item_weight(pos))
                elif self.bucket(it) is not None:
                    sid = clone(it)
                    sb = self.bucket(sid)
                    items.append(sid)
                    weights.append(sb.weight)
            sid = prefer.get(key)
            if sid is not None and self.bucket(sid) is not None:
                raise ValueError(
                    f"shadow id {sid} for ({bid}, {cls!r}) collides with "
                    "an existing bucket")
            if sid is None:
                sid = self.next_bucket_id()
            shadow = Bucket(id=sid, alg=b.alg, type=b.type, items=items,
                            weights=weights, hash=b.hash)
            shadow.finalize_derived(self.tunables.straw_calc_version)
            self.add_bucket(shadow)
            name = self.bucket_names.get(bid, f"bucket{-1 - bid}")
            self.bucket_names[sid] = f"{name}~{cls}"
            self.class_bucket_ids[key] = sid
            return sid

        return clone(root_id)

    @property
    def max_buckets(self) -> int:
        return len(self.buckets)

    @property
    def max_rules(self) -> int:
        return len(self.rules)

    # -------------------------------------------------------------- spec ----

    @classmethod
    def from_spec(cls, spec: dict) -> "CrushMap":
        """Build from the plain-dict format used by tests/golden vectors."""
        tun = Tunables(**{k: v for k, v in spec.get("tunables", {}).items()
                          if k in Tunables.__dataclass_fields__})
        if "straw_calc_version" not in spec.get("tunables", {}):
            # the golden generator builds via crush_create() which defaults to 0
            tun = replace(tun, straw_calc_version=0)
        m = cls(tunables=tun)
        for b in spec["buckets"]:
            m.add_bucket(Bucket(id=b["id"], alg=b["alg"], type=b["type"],
                                items=list(b["items"]), weights=list(b["weights"]),
                                hash=b.get("hash", HASH_RJENKINS1)))
        rules = spec.get("rules", [])
        for ruleno, r in enumerate(rules):
            if r is None:
                continue
            m.add_rule(Rule(steps=[tuple(s) for s in r["steps"]],
                            name=r.get("name", ""),
                            type=r.get("type", 1),
                            min_size=r.get("min_size", 1),
                            max_size=r.get("max_size", 10)),
                       r.get("id", ruleno))
        m.type_names = {int(k): v
                        for k, v in spec.get("type_names", {}).items()}
        m.bucket_names = {int(k): v
                          for k, v in spec.get("bucket_names", {}).items()}
        m.device_names = {int(k): v
                          for k, v in spec.get("device_names", {}).items()}
        m.device_classes = {int(k): v
                            for k, v in spec.get("device_classes",
                                                 {}).items()}
        m.class_bucket_ids = {(int(e["bucket"]), e["class"]): int(e["shadow"])
                              for e in spec.get("class_bucket_ids", [])}
        for key, entries in spec.get("choose_args", {}).items():
            args: List[Optional[ChooseArg]] = [None] * len(m.buckets)
            for e in entries:
                idx = -1 - int(e["bucket_id"])
                while len(args) <= idx:
                    args.append(None)
                args[idx] = ChooseArg(
                    ids=list(e["ids"]) if e.get("ids") else None,
                    weight_set=[list(row) for row in e["weight_set"]]
                    if e.get("weight_set") else None)
            try:
                k2: object = int(key)
            except (TypeError, ValueError):
                k2 = key
            m.choose_args[k2] = args
        if "num_devices" in spec:
            m.max_devices = max(m.max_devices, int(spec["num_devices"]))
        m.finalize()
        return m

    def to_spec(self) -> dict:
        spec = {
            "tunables": {k: getattr(self.tunables, k)
                         for k in Tunables.__dataclass_fields__},
            "buckets": [
                {"id": b.id, "alg": b.alg, "type": b.type, "hash": b.hash,
                 "items": list(b.items), "weights": list(b.weights)}
                for b in self.buckets if b is not None],
            "rules": [{"id": i, "steps": [list(s) for s in r.steps],
                       "name": r.name, "type": r.type,
                       "min_size": r.min_size, "max_size": r.max_size}
                      for i, r in enumerate(self.rules) if r is not None],
            "num_devices": self.max_devices,
        }
        if self.type_names:
            spec["type_names"] = {str(k): v
                                  for k, v in self.type_names.items()}
        if self.bucket_names:
            spec["bucket_names"] = {str(k): v
                                    for k, v in self.bucket_names.items()}
        if self.device_names:
            spec["device_names"] = {str(k): v
                                    for k, v in self.device_names.items()}
        if self.device_classes:
            spec["device_classes"] = {str(k): v
                                      for k, v in self.device_classes.items()}
        if self.class_bucket_ids:
            spec["class_bucket_ids"] = [
                {"bucket": b, "class": c, "shadow": s}
                for (b, c), s in sorted(self.class_bucket_ids.items())]
        if self.choose_args:
            spec["choose_args"] = {
                str(key): [{"bucket_id": -1 - idx,
                            "weight_set": arg.weight_set,
                            "ids": arg.ids}
                           for idx, arg in enumerate(args)
                           if arg is not None]
                for key, args in self.choose_args.items()}
        return spec
