"""jit_profile — compile-vs-execute attribution for device dispatches.

The blind spot this closes (PAPERS 2108.02692's program-optimization
lens): a jit cache miss in ``xla_mapper`` / ``gf_jax`` /
``data_plane`` stalls the triggering op for the XLA compile's wall
time — seconds on a cold process — and until now that cost was
invisible: the op's latency histogram showed a mystery spike, the
flame trace showed one fat ``device.dispatch`` span, and cold-compile
stalls repeatedly masqueraded as flakes and skewed benches.

``wrap()`` takes a FRESHLY-JITTED callable (jax compiles lazily, so
the cache-insert site knows "this will compile" but the cost lands on
the first invocation) and returns a wrapper that:

  * times the FIRST call inside a ``jit.compile`` child span (tagged
    with component + shape signature) linked under whatever op span
    is active — a cold-cache slow op's assembled trace now *says* it
    compiled, and where;
  * records perf counters in the ``jit`` group: ``compiles`` (the
    monotonic headline counter the metrics-history rate layer
    queries — lint CTL702 holds it inc-only), ``compile_s`` wall-time
    histogram, per-component ``<component>.compiles``, and
    ``execute_s`` for warm calls (the compile-vs-execute split).

Already-cached callables pass through ``wrap(..., compiled=False)``
unchanged — the warm path pays nothing new beyond what callers
already paid.
"""
from __future__ import annotations

import time
from typing import Any, Callable

from . import tracer as _trace
from .perf_counters import perf as _perf


def signature_of(*arrays: Any) -> str:
    """Compact shape/dtype signature for span tags ("8x256:int32,
    256:uint8") — enough to say WHICH executable family compiled."""
    parts = []
    for a in arrays:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is None:
            parts.append(type(a).__name__)
        else:
            parts.append("x".join(str(d) for d in shape) +
                         (f":{dtype}" if dtype is not None else ""))
    return ",".join(parts)


class ProfiledJit:
    """First call = compile event (span + counters); warm calls =
    execute accounting only."""

    __slots__ = ("fn", "component", "signature", "_cold")

    def __init__(self, fn: Callable, component: str, signature: str):
        self.fn = fn
        self.component = component
        self.signature = signature
        self._cold = True

    def __call__(self, *args, **kw):
        pc = _perf("jit")
        if self._cold:
            self._cold = False
            t0 = time.perf_counter()
            # child span only: an untraced caller must not spawn an
            # orphan root per compile, but a traced op's flame tree
            # gets the jit.compile stage it has been missing
            with _trace.child_span("jit.compile",
                                   component=self.component,
                                   signature=self.signature):
                out = self.fn(*args, **kw)
            dt = time.perf_counter() - t0
            pc.inc("compiles")
            pc.inc(f"{self.component}.compiles")
            pc.hinc("compile_s", dt)
            return out
        t0 = time.perf_counter()
        out = self.fn(*args, **kw)
        pc.hinc("execute_s", time.perf_counter() - t0)
        return out


class _CompileEvent:
    """Context manager around one known-cold device materialization
    (the gf_jax matrix upload shape, where the cost is a single call,
    not a cached callable)."""

    __slots__ = ("component", "signature", "_cm", "_t0")

    def __init__(self, component: str, signature: str):
        self.component = component
        self.signature = signature

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._cm = _trace.child_span("jit.compile",
                                     component=self.component,
                                     signature=self.signature)
        self._cm.__enter__()
        return self

    def __exit__(self, et, ev, tb):
        self._cm.__exit__(et, ev, tb)
        pc = _perf("jit")
        pc.inc("compiles")
        pc.inc(f"{self.component}.compiles")
        pc.hinc("compile_s", time.perf_counter() - self._t0)
        return False


class _NullCM:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullCM()


def compile_event(component: str, signature: str = "",
                  compiled: bool = True):
    """``with compile_event("ec.gf_jax", sig, compiled):`` — a no-op
    when the cache hit (``compiled`` False)."""
    return _CompileEvent(component, signature) if compiled else _NULL


def wrap(fn: Callable, component: str, signature: str = "",
         compiled: bool = True) -> Callable:
    """Wrap a jitted callable for compile attribution.  ``compiled``
    False (cache hit) returns ``fn`` untouched — the call site's
    existing cache-miss test decides, this module never second-
    guesses it."""
    if not compiled:
        return fn
    return ProfiledJit(fn, component, signature)
