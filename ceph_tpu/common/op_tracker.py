"""OpTracker — per-op lifecycle tracking from objecter to device dispatch.

Role of the reference's OpTracker/TrackedOp (src/common/TrackedOp.{h,cc}:
every client op carries a typed event trail — "initiated", "queued",
"reached_pg", "done" — with a bounded in-flight registry, ring buffers of
historic and historic-slow ops, and the `dump_ops_in_flight` /
`dump_historic_ops` / `dump_historic_slow_ops` admin commands; ops older
than `osd_op_complaint_time` feed the SLOW_OPS health check).

TPU-native shape: the interesting lifecycle here is

    initiated (objecter) -> queued (OSD native queue) -> reached_osd
    (batch formed, QoS-scheduled) -> dispatched_device (XLA executes,
    compile vs cached tagged) -> done

so the tracker records batch occupancy and queue depth at enqueue time
(the knobs that decide whether the MXU stays fed) and compile-vs-cached
on each device dispatch.  Per-stage durations land in log2-bucketed
``PerfHistogram``s (perf_counters.py) — averages hide exactly the
queueing/encode tails that dominate EC latency.

Cross-thread contract: the submitting thread owns the op and activates
it with ``tracker().track(op)`` (a thread-local stack, like the tracer's
span stack); code below the queue boundary — running on dispatcher
threads — marks events by op id via ``tracker().mark(op_id, ...)``.
All event appends serialize on the tracker lock.

Config (observed live, like ``perf_counters_enabled``):
    op_tracker_enabled          master switch (disabled -> null ops)
    op_tracker_complaint_time   seconds before an op counts as slow
    op_tracker_history_size     historic ring capacity
    op_tracker_history_slow_size  historic-slow ring capacity
    op_tracker_max_inflight     in-flight table bound (excess untracked)
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .options import OptionError, config
from .perf_counters import perf as _perf
from .tracer import pin_trace as _pin_trace

# canonical lifecycle events (free-form names are also accepted)
EVENT_INITIATED = "initiated"
EVENT_QUEUED = "queued"
EVENT_REACHED_OSD = "reached_osd"
EVENT_DISPATCHED_DEVICE = "dispatched_device"
# the op's work fanned out across the device mesh (sharded data
# plane, parallel/data_plane.py) — dump_historic_ops shows which
# client ops dispatched multi-chip and over how many shards
EVENT_DISPATCHED_MESH = "dispatched_mesh"
# the op's frames left on the asynchronous wire path (stream pool,
# cluster/async_objecter.py) — dump_ops_in_flight between this event
# and "done" IS the in-flight wire window
EVENT_DISPATCHED_WIRE = "dispatched_wire"
EVENT_DONE = "done"

# per-stage histogram keys: (from_event, to_event) -> perf key
_STAGE_HISTS = (
    (EVENT_INITIATED, EVENT_QUEUED, "stage_init_to_queue_s"),
    (EVENT_QUEUED, EVENT_REACHED_OSD, "stage_queue_to_osd_s"),
    (EVENT_REACHED_OSD, EVENT_DISPATCHED_DEVICE, "stage_osd_to_device_s"),
    (EVENT_DISPATCHED_DEVICE, EVENT_DONE, "stage_device_to_done_s"),
    (EVENT_DISPATCHED_MESH, EVENT_DONE, "stage_mesh_to_done_s"),
    (EVENT_DISPATCHED_WIRE, EVENT_DONE, "stage_wire_to_done_s"),
)

_ids = itertools.count(1)

# hot-path config cache, kept fresh by observers (the registry walk is
# too expensive per op; same pattern as perf_counters._counters_enabled)
_cfg_cache: Optional[Dict[str, Any]] = None
_cfg_lock = threading.Lock()

_CFG_KEYS = ("op_tracker_enabled", "op_tracker_complaint_time",
             "op_tracker_history_size", "op_tracker_history_slow_size",
             "op_tracker_max_inflight")
_CFG_DEFAULTS = {"op_tracker_enabled": True,
                 "op_tracker_complaint_time": 30.0,
                 "op_tracker_history_size": 100,
                 "op_tracker_history_slow_size": 20,
                 "op_tracker_max_inflight": 1024}


def _cfg(key: str) -> Any:
    global _cfg_cache
    cache = _cfg_cache
    if cache is None:
        with _cfg_lock:
            cache = _cfg_cache
            if cache is None:
                cache = {}
                cfg = config()
                for name in _CFG_KEYS:
                    try:
                        cache[name] = cfg.get(name)
                    except OptionError:
                        cache[name] = _CFG_DEFAULTS[name]

                    def _refresh(n, value):
                        cache[n] = value
                    try:
                        cfg.observe(name, _refresh)
                    except OptionError:
                        pass
                _cfg_cache = cache
    return cache[key]


class TrackedOp:
    """One client op's lifecycle record (TrackedOp analog)."""

    __slots__ = ("op_id", "optype", "service", "tags", "start", "start_ts",
                 "events", "duration", "error", "_tracker")

    def __init__(self, tracker: "OpTracker", optype: str, service: str,
                 tags: Dict[str, Any]):
        self.op_id = next(_ids)
        self.optype = optype
        self.service = service
        self.tags = tags
        self.start = time.perf_counter()
        self.start_ts = time.time()          # wall clock, log-correlatable
        self.events: List[Dict[str, Any]] = []
        self.duration: Optional[float] = None
        self.error: Optional[str] = None
        self._tracker = tracker

    @property
    def tracked(self) -> bool:
        return True

    def mark_event(self, event: str, **tags) -> None:
        self._tracker._append_event(self, event, tags)

    def age(self) -> float:
        return (time.perf_counter() - self.start
                if self.duration is None else self.duration)

    def first_event_t(self, event: str) -> Optional[float]:
        """perf_counter offset (seconds since initiation) of the first
        occurrence of ``event``, or None."""
        for e in self.events:
            if e["event"] == event:
                return e["dt_s"]
        return None

    def dump(self) -> Dict[str, Any]:
        d = {"op_id": self.op_id, "type": self.optype,
             "service": self.service,
             "initiated_at": round(self.start_ts, 6),
             "age_s": round(self.age(), 9)}
        d.update(self.tags)
        if self.duration is not None:
            d["duration_s"] = round(self.duration, 9)
        if self.error is not None:
            d["error"] = self.error
        d["events"] = [dict(e, dt_s=round(e["dt_s"], 9),
                            ts=round(e["ts"], 6))
                       for e in self.events]
        return d


class _NullOp:
    """Tracking disabled / table full: every call is a no-op."""

    __slots__ = ()
    op_id = 0
    optype = service = ""
    duration = error = None
    events: List[Dict[str, Any]] = []

    @property
    def tracked(self) -> bool:
        return False

    def mark_event(self, event: str, **tags) -> None:
        pass

    def age(self) -> float:
        return 0.0


_NULL_OP = _NullOp()


class OpTracker:
    """Bounded in-flight table + historic / historic-slow rings."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: Dict[int, TrackedOp] = {}
        self._historic: deque = deque(
            maxlen=int(_cfg("op_tracker_history_size")))
        self._historic_slow: deque = deque(
            maxlen=int(_cfg("op_tracker_history_slow_size")))
        # cumulative slow-op counts per daemon ("osd.3" -> n) plus a
        # recent-completion trail for the SLOW_OPS health window
        self._slow_by_daemon: Dict[str, int] = {}
        self._tls = threading.local()
        self._pc = _perf("op_tracker")

    # ---------------------------------------------------------- lifecycle --
    def create(self, optype: str, service: str = "objecter",
               **tags) -> TrackedOp:
        """Register a new tracked op (marks "initiated").  Returns a
        null op when tracking is off or the in-flight table is full —
        callers never branch on enablement."""
        if not _cfg("op_tracker_enabled"):
            return _NULL_OP
        op = TrackedOp(self, optype, service, tags)
        with self._lock:
            if len(self._inflight) >= int(_cfg("op_tracker_max_inflight")):
                self._pc.inc("ops_untracked")
                return _NULL_OP
            self._inflight[op.op_id] = op
            self._append_event_locked(op, EVENT_INITIATED, {})
        self._pc.inc("ops_tracked")
        return op

    def finish(self, op: TrackedOp, error: Optional[str] = None) -> None:
        """Complete an op: mark "done", move to the historic ring,
        record per-stage histograms, and classify slow ops."""
        if not op.tracked:
            return
        with self._lock:
            if self._inflight.pop(op.op_id, None) is None:
                return                      # double finish: keep first
            self._append_event_locked(op, EVENT_DONE,
                                      {} if error is None
                                      else {"error": error})
            op.duration = time.perf_counter() - op.start
            op.error = error
            self._resize_rings_locked()
            self._historic.append(op)
            complaint = float(_cfg("op_tracker_complaint_time"))
            slow = op.duration >= complaint
            if slow:
                self._historic_slow.append(op)
                for d in self._op_daemons(op):
                    self._slow_by_daemon[d] = \
                        self._slow_by_daemon.get(d, 0) + 1
        # histograms outside the tracker lock (they take the group lock)
        pc = _perf(op.service)
        pc.hinc("op_e2e_s", op.duration)
        tpc = self._pc
        for frm, to, key in _STAGE_HISTS:
            t0 = op.first_event_t(frm)
            t1 = op.first_event_t(to)
            if t0 is not None and t1 is not None and t1 >= t0:
                tpc.hinc(key, t1 - t0)
        if slow:
            tpc.inc("slow_ops")
            # auto-sampling (ISSUE 10): an op that crossed the
            # complaint time pins its trace, so the slow op's
            # end-to-end flame trace survives buffer churn and stays
            # retrievable by op id (`ceph trace <op>`)
            _pin_trace(op.tags.get("trace_id"))

    def mark(self, op_id: Optional[int], event: str, **tags) -> None:
        """Cross-thread event append by op id (below-queue code paths
        that only see the serialized op).  Unknown/finished ids drop."""
        if not op_id:
            return
        with self._lock:
            op = self._inflight.get(op_id)
            if op is not None:
                self._append_event_locked(op, event, tags)

    # ------------------------------------------------------- active-op tls --
    def track(self, op: TrackedOp):
        """Context manager: make ``op`` the thread's active op so code
        deeper in the pipeline can tag it without plumbing."""
        return _ActiveOp(self, op)

    def current(self) -> Optional[TrackedOp]:
        stack = getattr(self._tls, "stack", None)
        op = stack[-1] if stack else None
        return op if op is not None and op.tracked else None

    # ------------------------------------------------------------- events --
    def _resize_rings_locked(self) -> None:
        """Honor runtime changes to the history-size knobs: the deques'
        maxlen is fixed at construction, so rebuild (keeping the newest
        entries) whenever the observed config no longer matches."""
        hist = int(_cfg("op_tracker_history_size"))
        if self._historic.maxlen != hist:
            self._historic = deque(self._historic, maxlen=hist)
        slow = int(_cfg("op_tracker_history_slow_size"))
        if self._historic_slow.maxlen != slow:
            self._historic_slow = deque(self._historic_slow, maxlen=slow)

    def _append_event(self, op: TrackedOp, event: str,
                      tags: Dict[str, Any]) -> None:
        with self._lock:
            self._append_event_locked(op, event, tags)

    def _append_event_locked(self, op: TrackedOp, event: str,
                             tags: Dict[str, Any]) -> None:
        e = {"event": event, "ts": time.time(),
             "dt_s": time.perf_counter() - op.start}
        if tags:
            e.update(tags)
        op.events.append(e)

    @staticmethod
    def _op_daemons(op: TrackedOp) -> List[str]:
        seen = []
        for e in op.events:
            osd = e.get("osd")
            if osd is not None and f"osd.{osd}" not in seen:
                seen.append(f"osd.{osd}")
        return seen

    # --------------------------------------------------------------- dump --
    def dump_ops_in_flight(self) -> Dict[str, Any]:
        complaint = float(_cfg("op_tracker_complaint_time"))
        with self._lock:
            ops = sorted(self._inflight.values(), key=lambda o: o.op_id)
            out = [dict(o.dump(), slow=o.age() >= complaint) for o in ops]
        return {"num_ops": len(out), "complaint_time_s": complaint,
                "ops": out}

    def dump_historic_ops(self) -> Dict[str, Any]:
        with self._lock:
            self._resize_rings_locked()
            size = self._historic.maxlen
            ops = [o.dump() for o in self._historic]
        return {"size": size, "num_ops": len(ops), "ops": ops}

    def dump_historic_slow_ops(self) -> Dict[str, Any]:
        with self._lock:
            self._resize_rings_locked()
            size = self._historic_slow.maxlen
            ops = [o.dump() for o in self._historic_slow]
        return {"size": size, "num_ops": len(ops),
                "complaint_time_s": float(_cfg("op_tracker_complaint_time")),
                "ops": ops}

    # ------------------------------------------------------------- health --
    def slow_ops_summary(self, window_s: float = 600.0) -> Dict[str, Any]:
        """Input for the mon's SLOW_OPS check: currently-blocked ops
        (in flight past the complaint time) plus historic slow ops that
        completed within ``window_s``.  Daemons listed by osd tag."""
        complaint = float(_cfg("op_tracker_complaint_time"))
        now_wall = time.time()
        blocked = 0
        oldest = 0.0
        daemons: List[str] = []
        with self._lock:
            for op in self._inflight.values():
                a = op.age()
                if a >= complaint:
                    blocked += 1
                    oldest = max(oldest, a)
                    for d in self._op_daemons(op):
                        if d not in daemons:
                            daemons.append(d)
            recent = 0
            for op in self._historic_slow:
                done_ts = op.start_ts + (op.duration or 0.0)
                if now_wall - done_ts <= window_s:
                    recent += 1
                    oldest = max(oldest, op.duration or 0.0)
                    for d in self._op_daemons(op):
                        if d not in daemons:
                            daemons.append(d)
            by_daemon = dict(self._slow_by_daemon)
        return {"num": blocked + recent, "blocked": blocked,
                "recent": recent, "oldest_s": round(oldest, 6),
                "daemons": sorted(daemons), "by_daemon": by_daemon}

    def reset(self) -> None:
        """Drop all state (tests / `perf reset`-style hygiene)."""
        with self._lock:
            self._inflight.clear()
            self._historic.clear()
            self._historic_slow.clear()
            self._slow_by_daemon.clear()


class _ActiveOp:
    __slots__ = ("_tracker", "_op")

    def __init__(self, tracker: OpTracker, op):
        self._tracker = tracker
        self._op = op

    def __enter__(self):
        stack = getattr(self._tracker._tls, "stack", None)
        if stack is None:
            stack = self._tracker._tls.stack = []
        stack.append(self._op)
        return self._op

    def __exit__(self, *exc):
        self._tracker._tls.stack.pop()
        return False


_tracker: Optional[OpTracker] = None
_tracker_lock = threading.Lock()


def tracker() -> OpTracker:
    """The process-wide tracker (the per-daemon OpTracker analog)."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = OpTracker()
        return _tracker


def mark_active(event: str, **tags) -> None:
    """Tag the calling thread's active op, if any — the seam device
    dispatch layers (xla_mapper, gf_jax) use so compile-vs-cached lands
    on whatever client op triggered the dispatch."""
    t = _tracker
    if t is None:
        return
    op = t.current()
    if op is not None:
        op.mark_event(event, **tags)
