from .admin import AdminServer, admin_request
from .op_tracker import OpTracker, TrackedOp, tracker
from .options import Option, OptionError, Options, config
from .perf_counters import (PerfCounters, PerfCountersCollection,
                            PerfHistogram, perf)

__all__ = ["AdminServer", "admin_request",
           "OpTracker", "TrackedOp", "tracker",
           "Option", "OptionError", "Options", "config",
           "PerfCounters", "PerfCountersCollection", "PerfHistogram",
           "perf"]
