from .admin import AdminServer, admin_request
from .options import Option, OptionError, Options, config
from .perf_counters import PerfCounters, PerfCountersCollection, perf

__all__ = ["AdminServer", "admin_request",
           "Option", "OptionError", "Options", "config",
           "PerfCounters", "PerfCountersCollection", "perf"]
