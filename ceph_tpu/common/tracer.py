"""Tracing spans — the distributed-tracing substrate.

Roles of the reference's tracer (src/common/tracer.{h,cc}: jspan /
child_span wrappers over Jaeger/OpenTracing, threaded through ops e.g.
PrimaryLogPG.cc:11060) and the LTTng tracepoints in hot paths
(src/tracing/*.tp).  TPU-native shape: spans wrap host-side stages
around device dispatches (map sweep, encode, recovery) with parent /
child links and wall-time, collected in a bounded in-process buffer
dumped as JSON (the role the Jaeger agent plays).
"""
from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_ids = itertools.count(1)


@dataclass
class Span:
    trace_id: int
    span_id: int
    parent_id: Optional[int]
    name: str
    start: float                 # perf_counter (duration arithmetic)
    ts: float = 0.0              # wall clock at start: correlates spans
    #                              with log lines and tracked-op events
    end: Optional[float] = None
    tags: Dict[str, Any] = field(default_factory=dict)

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start


class Tracer:
    """Span factory + bounded finished-span buffer."""

    def __init__(self, max_spans: int = 10000):
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._finished: List[Span] = []
        self._tls = threading.local()

    # ------------------------------------------------------------- spans --
    def _current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    @contextmanager
    def start_span(self, name: str, **tags):
        """Root span, or child of the active span on this thread
        (child_span semantics, src/common/tracer.h:10-30)."""
        parent = self._current()
        span = Span(
            trace_id=parent.trace_id if parent else next(_ids),
            span_id=next(_ids),
            parent_id=parent.span_id if parent else None,
            name=name, start=time.perf_counter(), ts=time.time(),
            tags=dict(tags))
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(span)
        try:
            yield span
        finally:
            span.end = time.perf_counter()
            stack.pop()
            with self._lock:
                self._finished.append(span)
                if len(self._finished) > self.max_spans:
                    del self._finished[:len(self._finished) // 2]

    # -------------------------------------------------------------- dump --
    def dump(self) -> List[Dict[str, Any]]:
        with self._lock:
            spans = list(self._finished)
        return [{
            "trace_id": s.trace_id, "span_id": s.span_id,
            "parent_id": s.parent_id, "name": s.name,
            "ts": round(s.ts, 6),
            "duration_s": round(s.duration or 0.0, 9), "tags": s.tags,
        } for s in spans]

    def reset(self) -> None:
        with self._lock:
            self._finished.clear()


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def tracer() -> Tracer:
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            _tracer = Tracer()
        return _tracer
