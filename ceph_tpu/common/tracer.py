"""Tracing spans — the distributed-tracing substrate.

Roles of the reference's tracer (src/common/tracer.{h,cc}: jspan /
child_span wrappers over Jaeger/OpenTracing, threaded through ops e.g.
PrimaryLogPG.cc:11060) and the LTTng tracepoints in hot paths
(src/tracing/*.tp).  TPU-native shape: spans wrap host-side stages
around device dispatches (map sweep, encode, recovery) with parent /
child links and wall-time, collected in a bounded in-process buffer
dumped as JSON (the role the Jaeger agent plays).

ClusterTelemetry (ISSUE 10) grew this into CROSS-PROCESS tracing:

  * a ``(trace_id, span_id)`` trace context is stamped into every
    request a client submits (``stamp(req)`` at the objecter /
    AsyncObjecter submit path) and rides the typed request meta of
    both MSG_REQ and scatter-gather MSG_REQ_SG wire frames (key
    ``tctx``) as well as in-process dispatch op dicts — the
    reference's jaeger trace-context header propagation;
  * daemons open LINKED child spans around their queue / dispatch /
    store-barrier / device-dispatch stages via ``child_of`` remote
    parents, each tagged with the process's ``service`` entity, so
    one logical op's spans scatter across every process it touched;
  * ``assemble()`` is the collector: it merges span dumps fetched
    from many daemons' ``dump_traces`` asok surfaces into one tree
    per trace (the Jaeger query/assembly role) — ``ceph trace <op>``
    drives it cluster-wide;
  * slow ops AUTO-SAMPLE: when the OpTracker finishes an op past
    ``op_tracker_complaint_time`` it pins that op's trace
    (``pin_trace``), exempting its spans from buffer trimming, so a
    slow op always has its end-to-end flame trace retrievable.

Cost contract (the faults.fire dict-miss rule): span and stamp sites
sit on put/get hot paths, so DISARMED tracing is a single dict
membership test — no config resolve, no lock, no allocation.  Span
ids are drawn from a per-process RNG (not a counter) so ids never
collide across the processes one trace spans.
"""
from __future__ import annotations

import random
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .perf_counters import perf as _perf

# armed-state fast path: ``"on" in _armed`` is the whole disarmed
# cost (the faults registry pattern — see common/faults.py)
_armed: Dict[str, bool] = {"on": True}

# this process's service entity ("client", "osd.3", "mon.1"), stamped
# on every span so cross-process assembly can attribute stages
_service: Dict[str, str] = {"name": "client"}

# cluster-unique span/trace ids: a counter collides across processes,
# so ids come from a per-process RNG (ids carry no schedule state —
# seeded thrash determinism never reads them)
_rng = random.Random()


def enabled() -> bool:
    """One dict-miss check — safe on any hot path."""
    return "on" in _armed


def arm() -> None:
    _armed["on"] = True


def disarm() -> None:
    _armed.pop("on", None)


def set_service(name: str) -> None:
    """Name this process for span attribution (daemons call it at
    boot with their entity; clients default to "client")."""
    _service["name"] = str(name)


def service() -> str:
    return _service["name"]


def stamp(req: Dict[str, Any]) -> Dict[str, Any]:
    """Propagate the active trace context into an outbound request
    dict (key ``tctx`` — the trace-context wire format for MSG_REQ /
    MSG_REQ_SG meta and in-process dispatch ops).  Disarmed: one
    dict-miss, the dict passes through untouched.  The CTL701 lint
    rule requires every data-path send site to route through here."""
    if "on" not in _armed:
        return req
    t = _tracer
    if t is None:
        return req
    span = t._current()
    if span is not None:
        req["tctx"] = [span.trace_id, span.span_id]
    return req


@dataclass
class Span:
    trace_id: int
    span_id: int
    parent_id: Optional[int]
    name: str
    start: float                 # perf_counter (duration arithmetic)
    ts: float = 0.0              # wall clock at start: correlates spans
    #                              with log lines and tracked-op events
    end: Optional[float] = None
    service: str = "client"      # owning process's entity
    tags: Dict[str, Any] = field(default_factory=dict)

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def ctx(self) -> Tuple[int, int]:
        """The (trace_id, span_id) context children link under."""
        return (self.trace_id, self.span_id)

    def dump(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "service": self.service, "ts": round(self.ts, 6),
            "duration_s": round(self.duration or 0.0, 9),
            "tags": self.tags,
        }


class _NullSpan:
    """Disarmed span: every call a no-op (the OpTracker _NullOp
    pattern) — callers never branch on enablement."""

    __slots__ = ()
    trace_id = span_id = 0
    parent_id = None
    name = service = ""
    duration = end = None
    tags: Dict[str, Any] = {}

    def set_tag(self, key: str, value: Any) -> None:
        pass

    def ctx(self) -> Tuple[int, int]:
        return (0, 0)


_NULL_SPAN = _NullSpan()


class _NullSpanCM:
    __slots__ = ()

    def __enter__(self):
        return _NULL_SPAN

    def __exit__(self, *exc):
        return False


_NULL_CM = _NullSpanCM()


class _SpanCM:
    """Context-managed span: an exception propagating through the
    body finishes the span WITH an ``error`` tag (the leaked-span
    satellite's contract — an abandoned stage must not dump as a
    mysteriously fast clean stage)."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> Span:
        self._tracer._push(self.span)
        return self.span

    def __exit__(self, et, ev, tb):
        if et is not None:
            self.span.tags.setdefault("error", et.__name__)
        self._tracer._pop_finish(self.span)
        return False


class Tracer:
    """Span factory + bounded finished-span buffer.

    The buffer bound used to drop silently; drops are now counted
    (``tracer.spans_dropped`` perf counter + a cumulative tally) and
    ``dump_traces`` exposes buffer occupancy.  Pinned (auto-sampled
    slow) traces are exempt from trimming, bounded by
    ``MAX_PINNED_TRACES`` with LRU eviction.
    """

    MAX_PINNED_TRACES = 32
    # manual-open spans (callback paths that cannot hold a context
    # manager) older than this are force-finished with error="leaked"
    LEAK_AGE_S = 300.0

    def __init__(self, max_spans: int = 10000):
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._finished: List[Span] = []
        self._tls = threading.local()
        self.spans_dropped = 0
        # trace_id -> [spans] rescued from trimming (sampled traces)
        self._pinned: "OrderedDict[int, List[Span]]" = OrderedDict()
        self._sampled: set = set()
        # manually opened spans (span_open) awaiting finish_span
        self._open: Dict[int, Span] = {}

    # ------------------------------------------------------------- spans --
    def _current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def current_ctx(self) -> Optional[Tuple[int, int]]:
        """(trace_id, span_id) of this thread's active span, or None
        (what submit paths stamp into outbound requests)."""
        span = self._current()
        return None if span is None else span.ctx()

    def _make_span(self, name: str,
                   child_of: Optional[Iterable[int]],
                   tags: Dict[str, Any],
                   service: Optional[str] = None) -> Span:
        parent = self._current()
        if parent is not None:
            tid, pid = parent.trace_id, parent.span_id
        elif child_of:
            # remote parent: a (trace_id, span_id) context carried in
            # from another process/thread (wire frames, dispatch ops)
            tid, pid = int(child_of[0]), int(child_of[1])
        else:
            tid, pid = _rng.getrandbits(48), None
        return Span(trace_id=tid, span_id=_rng.getrandbits(48),
                    parent_id=pid, name=name,
                    start=time.perf_counter(), ts=time.time(),
                    service=service or _service["name"],
                    tags=dict(tags))

    def start_span(self, name: str,
                   child_of: Optional[Iterable[int]] = None,
                   service: Optional[str] = None, **tags):
        """Root span, child of the active span on this thread
        (child_span semantics, src/common/tracer.h:10-30), or child
        of a REMOTE parent via ``child_of=(trace_id, span_id)``.
        ``service`` overrides the process entity for this span — the
        sim tier's attribution fix: one process hosts MANY logical
        entities (client, every osd.N, the mon), and a span must name
        the entity that EXECUTED the stage, not whoever owns the
        process (which is always "client" in-process).
        Disarmed: returns a shared null context manager."""
        if "on" not in _armed:
            return _NULL_CM
        return _SpanCM(self, self._make_span(name, child_of, tags,
                                             service))

    def child_span(self, name: str, service: Optional[str] = None,
                   **tags):
        """A span ONLY when a parent is active on this thread (stage
        sites deep in daemons — an untraced op must not spawn orphan
        root spans at every stage it passes)."""
        if "on" not in _armed or self._current() is None:
            return _NULL_CM
        return _SpanCM(self, self._make_span(name, None, tags,
                                             service))

    # ----------------------------------------------- manual open/finish --
    def span_open(self, name: str,
                  child_of: Optional[Iterable[int]] = None, **tags):
        """Open a span WITHOUT entering it on this thread's stack —
        for completion-callback paths where open and finish happen on
        different threads (the async objecter).  Must be closed with
        ``finish_span``; leaked spans are swept by ``finish_leaked``
        with an error tag."""
        if "on" not in _armed:
            return _NULL_SPAN
        span = self._make_span(name, child_of, tags)
        with self._lock:
            self._open[span.span_id] = span
        return span

    def finish_span(self, span, error: Optional[str] = None) -> None:
        if span is None or span is _NULL_SPAN or \
                not isinstance(span, Span):
            return
        with self._lock:
            was_open = self._open.pop(span.span_id, None) is not None
        if not was_open:
            # already finished — the leak sweep won the race (an op
            # stalled past LEAK_AGE_S then completed): finishing
            # again would insert the same span twice and inflate
            # occupancy; the sweep's error=leaked verdict stands
            return
        if error is not None:
            span.tags.setdefault("error", error)
        self._finish(span)

    def finish_leaked(self, max_age_s: Optional[float] = None) -> int:
        """Force-finish manual-open spans older than ``max_age_s``
        with an ``error: leaked`` tag (exception paths that dropped
        their span on the floor must still show up in the dump, as
        errors, not vanish)."""
        bound = self.LEAK_AGE_S if max_age_s is None else max_age_s
        now = time.perf_counter()
        with self._lock:
            leaked = [s for s in self._open.values()
                      if now - s.start >= bound]
            for s in leaked:
                del self._open[s.span_id]
        for s in leaked:
            s.tags.setdefault("error", "leaked")
            self._finish(s)
        return len(leaked)

    # ----------------------------------------------------- stack/finish --
    def _push(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(span)

    def _pop_finish(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack and stack[-1] is span:
            stack.pop()
        self._finish(span)

    def _finish(self, span: Span) -> None:
        if span.end is None:
            span.end = time.perf_counter()
        dropped = 0
        with self._lock:
            self._finished.append(span)
            if len(self._finished) > self.max_spans:
                cut = len(self._finished) // 2
                trimmed, self._finished = (self._finished[:cut],
                                           self._finished[cut:])
                for s in trimmed:
                    if s.trace_id in self._sampled:
                        # auto-sampled slow trace: rescue, not drop
                        self._pinned.setdefault(s.trace_id,
                                                []).append(s)
                    else:
                        dropped += 1
                self.spans_dropped += dropped
        if dropped:
            _perf("tracer").inc("spans_dropped", dropped)

    # --------------------------------------------------------- sampling --
    def pin_trace(self, trace_id: int) -> None:
        """Auto-sampling hook (OpTracker.finish on a slow op): this
        trace's spans survive buffer trims, so the slow op's flame
        trace stays retrievable long after the buffer churned."""
        if not trace_id:
            return
        with self._lock:
            self._sampled.add(int(trace_id))
            self._pinned.setdefault(int(trace_id), [])
            self._pinned.move_to_end(int(trace_id))
            while len(self._pinned) > self.MAX_PINNED_TRACES:
                old, _spans = self._pinned.popitem(last=False)
                self._sampled.discard(old)

    def sampled_traces(self) -> List[int]:
        with self._lock:
            return sorted(self._sampled)

    # -------------------------------------------------------------- dump --
    def _all_spans_locked(self) -> List[Span]:
        pinned = [s for spans in self._pinned.values() for s in spans]
        return pinned + list(self._finished)

    def dump(self) -> List[Dict[str, Any]]:
        with self._lock:
            spans = self._all_spans_locked()
        return [s.dump() for s in spans]

    def dump_traces(self) -> Dict[str, Any]:
        """The ``ceph daemon <name> dump_traces`` surface: spans plus
        the buffer health the drop-counting satellite demands."""
        self.finish_leaked()
        with self._lock:
            spans = self._all_spans_locked()
            occupancy = len(self._finished)
            open_spans = len(self._open)
            sampled = sorted(self._sampled)
            dropped = self.spans_dropped
        return {"service": _service["name"],
                "occupancy": occupancy, "max_spans": self.max_spans,
                "open_spans": open_spans,
                "spans_dropped": dropped, "sampled": sampled,
                "num_spans": len(spans),
                "spans": [s.dump() for s in spans]}

    def spans_for(self, trace_id: int) -> List[Dict[str, Any]]:
        with self._lock:
            spans = [s for s in self._all_spans_locked()
                     if s.trace_id == int(trace_id)]
        return [s.dump() for s in spans]

    def reset(self) -> None:
        with self._lock:
            self._finished.clear()
            self._pinned.clear()
            self._sampled.clear()
            self._open.clear()
            self.spans_dropped = 0


# ---------------------------------------------------------- assembly ----

def assemble(spans: Iterable[Dict[str, Any]]) -> Dict[int, Dict]:
    """The trace collector: merge span dicts gathered from MANY
    processes' dump_traces into one tree per trace_id (the Jaeger
    query-service assembly role).  Spans whose parent never arrived
    (buffer churn on one daemon) surface as extra roots rather than
    vanishing — a partial trace is still evidence.

    -> {trace_id: {"spans": n, "services": [...], "duration_s": ...,
                   "roots": [node...]}}, node = span dict +
    "children": [node...] sorted by start wall-clock.
    """
    by_trace: Dict[int, List[Dict[str, Any]]] = {}
    for s in spans:
        by_trace.setdefault(int(s["trace_id"]), []).append(dict(s))
    out: Dict[int, Dict] = {}
    for tid, group in by_trace.items():
        # dedup (the same daemon may be dumped twice by a collector)
        seen: Dict[int, Dict[str, Any]] = {}
        for s in group:
            seen.setdefault(int(s["span_id"]), s)
        nodes = {sid: dict(s, children=[])
                 for sid, s in seen.items()}
        roots = []
        for sid, node in nodes.items():
            pid = node.get("parent_id")
            parent = nodes.get(int(pid)) if pid else None
            if parent is not None and parent is not node:
                parent["children"].append(node)
            else:
                roots.append(node)
        for node in nodes.values():
            node["children"].sort(key=lambda n: n.get("ts", 0.0))
        roots.sort(key=lambda n: n.get("ts", 0.0))
        ts0 = min(n.get("ts", 0.0) for n in nodes.values())
        ts1 = max(n.get("ts", 0.0) + n.get("duration_s", 0.0)
                  for n in nodes.values())
        out[tid] = {
            "trace_id": tid,
            "spans": len(nodes),
            "services": sorted({n.get("service", "")
                                for n in nodes.values()}),
            "duration_s": round(ts1 - ts0, 9),
            "roots": roots,
        }
    return out


def stage_breakdown(spans: Iterable[Dict[str, Any]]
                    ) -> Dict[str, Dict[str, Any]]:
    """Per-stage wall-time attribution over assembled/raw spans:
    {span name: {count, total_s, max_s}} — the bench satellite's
    'WHY is this tier slow' datapoint."""
    out: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        d = out.setdefault(s["name"], {"count": 0, "total_s": 0.0,
                                       "max_s": 0.0})
        dur = float(s.get("duration_s") or 0.0)
        d["count"] += 1
        d["total_s"] = round(d["total_s"] + dur, 9)
        d["max_s"] = round(max(d["max_s"], dur), 9)
    return out


def render_trace(tree: Dict, indent: str = "  ") -> str:
    """Human flame-tree rendering of one assemble() entry."""
    lines = [f"trace {tree['trace_id']:x}: {tree['spans']} spans "
             f"across {', '.join(tree['services'])} "
             f"({tree['duration_s'] * 1e3:.3f} ms)"]

    def walk(node, depth):
        dur = node.get("duration_s", 0.0) * 1e3
        err = node.get("tags", {}).get("error")
        suffix = f"  ERROR={err}" if err else ""
        lines.append(f"{indent * depth}{node['service']}: "
                     f"{node['name']} {dur:.3f} ms{suffix}")
        for c in node["children"]:
            walk(c, depth + 1)

    for r in tree["roots"]:
        walk(r, 1)
    return "\n".join(lines)


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def tracer() -> Tracer:
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            _tracer = Tracer(max_spans=_buffer_bound())
        return _tracer


def pin_trace(trace_id) -> None:
    """Module-level auto-sampling hook (cheap when tracing never ran:
    no tracer is constructed just to pin into an empty buffer)."""
    t = _tracer
    if t is not None and trace_id:
        t.pin_trace(int(trace_id))


def child_span(name: str, service: Optional[str] = None, **tags):
    """Module-level stage-span fast path: one dict-miss when
    disarmed, null when no parent is active (see Tracer.child_span).
    Deep fire sites (scheduler dequeue, store barriers, device
    dispatch) call this unconditionally."""
    if "on" not in _armed:
        return _NULL_CM
    t = _tracer
    if t is None:
        return _NULL_CM
    return t.child_span(name, service=service, **tags)


def start_span(name: str, child_of=None,
               service: Optional[str] = None, **tags):
    """Module-level span fast path: the disarmed case is one
    dict-miss with no singleton lock (fire sites run per op)."""
    if "on" not in _armed:
        return _NULL_CM
    return tracer().start_span(name, child_of=child_of,
                               service=service, **tags)


def linked_span(name: str, child_of,
                service: Optional[str] = None, **tags):
    """Open a span ONLY when a remote trace context arrived (or a
    local parent is active): the daemon-side rule — an op that was
    never stamped must not litter the buffer with orphan roots.
    ``service`` attributes the span to the EXECUTING logical entity
    (sim-tier daemons share one process whose default entity is
    "client")."""
    if "on" not in _armed:
        return _NULL_CM
    if child_of:
        return tracer().start_span(name, child_of=child_of,
                                   service=service, **tags)
    return child_span(name, service=service, **tags)


def _buffer_bound() -> int:
    try:
        from .options import OptionError, config
        return int(config().get("trace_max_spans"))
    except Exception:
        return 10000


# config binding: ``trace_enabled`` drives the armed dict (observed
# live, like perf_counters_enabled).  Import-time so daemons spawned
# with CEPH_TPU_TRACE_ENABLED=0 never arm; failure leaves the
# default (armed) — tracing must not break a process missing the
# options registry.
def _bind_config() -> None:
    try:
        from .options import OptionError, config
        cfg = config()
        try:
            on = bool(cfg.get("trace_enabled"))
        except OptionError:
            return
        (arm if on else disarm)()

        def _refresh(_name, value):
            (arm if bool(value) else disarm)()
        cfg.observe("trace_enabled", _refresh)
    except Exception:
        pass


_bind_config()
