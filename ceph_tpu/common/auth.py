"""cephx-style ticket authentication (the src/auth/cephx/ role).

The reference's cephx (CephxProtocol.h:1-60, CephxServiceHandler.cc)
is Kerberos-shaped: every entity shares a secret with the monitor; a
client asks the mon for a TICKET for a target service; the ticket holds
a fresh session key and is sealed under the SERVICE's secret, so the
service can unseal it without talking to the mon; the client proves
possession of the session key with an authorizer; both sides then share
the session key for per-message authentication.

This module re-creates that shape on the stdlib only:

  * Keyring — entity name -> 32-byte secret (mon holds all of them;
    daemons hold their own), JSON file on disk.
  * seal/unseal — authenticated encryption.  AES-256-GCM when the
    `cryptography` package is importable (the reference's secure-mode
    AES-GCM, src/msg/async/crypto_onwire.cc — hardware AES moves the
    wire from ~10 MB/s to ~1 GB/s per stream); otherwise a
    stdlib-only fallback: SHAKE-256 XOF keystream XORed over the
    plaintext with an encrypt-then-MAC HMAC-SHA256 tag.  Blobs are
    format-tagged ("G"/"P"): a host with AES support opens both
    formats; a stdlib-only host opens only "P", so MIXED-capability
    deployments must run every peer stdlib-only (all daemons and
    clients of one cluster share a venv here — heterogeneous installs
    would need a capability handshake this module does not provide).
  * TicketServer (mon side): grant(entity, service) -> (ticket_blob,
    sealed_session_key) where ticket_blob is sealed under the service
    secret and the session key copy under the requesting entity's
    secret — the CephxServiceHandler build_session_auth_info role.
  * verify_authorizer (service side): unseal the ticket with the
    service secret, check expiry, then check the client's
    HMAC(session_key, nonce) proof — CephxAuthorizeHandler::verify.

Every daemon connection in the process cluster (cluster/daemon.py)
performs this handshake before any op frame is accepted; frames after
the handshake carry per-message HMACs keyed by the ticket's session
key (msg/wire.py).
"""
from __future__ import annotations

import hmac
import json
import os
import secrets
import struct
import time
from hashlib import sha256
from typing import Dict, Optional, Tuple

TICKET_TTL_S = 3600.0


class AuthError(PermissionError):
    pass


# ------------------------------------------------ HMAC-CTR sealed boxes ---

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    _HAVE_AESGCM = True
except ImportError:                       # stdlib-only environment
    _HAVE_AESGCM = False


def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    """SHAKE-256 XOF keystream: ONE C call for the whole frame
    (~170 MB/s) instead of an HMAC invocation per 32 bytes (~10 MB/s
    with Python-loop overhead on MB-scale secure-mode frames)."""
    from hashlib import shake_256
    return shake_256(b"ks" + key + nonce).digest(n)


def _xor(a: bytes, b: bytes) -> bytes:
    """Constant-width XOR via big-int ops (C-speed; a per-byte Python
    zip is ~1000x slower on MB-scale secure-mode frames)."""
    n = len(a)
    return (int.from_bytes(a, "little") ^
            int.from_bytes(b, "little")).to_bytes(n, "little")


def seal(key: bytes, plaintext: bytes) -> bytes:
    """Format-tagged authenticated encryption:
    "G" | nonce12 | AES-GCM(ct||tag16)          (hardware AES path)
    "P" | nonce16 | ct | hmac-sha256 tag32      (stdlib fallback)"""
    if _HAVE_AESGCM:
        nonce = secrets.token_bytes(12)
        return b"G" + nonce + AESGCM(key).encrypt(nonce, plaintext,
                                                  b"seal")
    nonce = secrets.token_bytes(16)
    ct = _xor(plaintext, _keystream(key, nonce, len(plaintext)))
    tag = hmac.new(key, b"seal" + nonce + ct, sha256).digest()
    return b"P" + nonce + ct + tag


def seal_parts(key: bytes, parts) -> list:
    """``seal`` over a scatter-gather payload WITHOUT first joining it:
    returns the sealed blob as a list of buffers suitable for
    ``socket.sendmsg`` (wire.py's scatter-gather frame path).  Each
    plaintext byte is touched exactly once by the cipher XOR and once
    by the MAC — no intermediate whole-payload assembly.  The AES-GCM
    path has no streaming API here, so it joins (hardware AES makes
    the copy irrelevant next to the cipher win)."""
    if _HAVE_AESGCM:
        return [seal(key, b"".join(bytes(p) for p in parts))]
    nonce = secrets.token_bytes(16)
    total = sum(len(p) for p in parts)
    ks = _keystream(key, nonce, total)
    out = [b"P" + nonce]
    tag = hmac.new(key, b"seal" + nonce, sha256)
    off = 0
    for p in parts:
        n = len(p)
        ct = _xor(bytes(p), ks[off:off + n])
        off += n
        tag.update(ct)
        out.append(ct)
    out.append(tag.digest())
    return out


def unseal(key: bytes, blob: bytes) -> bytes:
    fmt = blob[:1]
    if fmt == b"G":
        if not _HAVE_AESGCM:
            raise AuthError("AES-GCM sealed blob but no AES support")
        if len(blob) < 29:
            raise AuthError("sealed blob too short")
        try:
            return AESGCM(key).decrypt(blob[1:13], blob[13:], b"seal")
        except Exception:
            raise AuthError("sealed blob rejected") from None
    if fmt == b"P":
        body = blob[1:]
        if len(body) < 48:
            raise AuthError("sealed blob too short")
        nonce, ct, tag = body[:16], body[16:-32], body[-32:]
        want = hmac.new(key, b"seal" + nonce + ct, sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise AuthError("sealed blob MAC rejected")
        return _xor(ct, _keystream(key, nonce, len(ct)))
    raise AuthError(f"unknown sealed-blob format {fmt!r}")


# ------------------------------------------------------------- keyring ---

class Keyring:
    """entity name -> secret; JSON-file backed (the keyring file role)."""

    def __init__(self, entries: Optional[Dict[str, bytes]] = None):
        self.entries: Dict[str, bytes] = dict(entries or {})

    @staticmethod
    def generate(names) -> "Keyring":
        return Keyring({n: secrets.token_bytes(32) for n in names})

    def secret(self, name: str) -> bytes:
        try:
            return self.entries[name]
        except KeyError:
            raise AuthError(f"no key for entity {name!r}") from None

    def subset(self, *names: str) -> "Keyring":
        return Keyring({n: self.secret(n) for n in names})

    def save(self, path: str) -> None:
        blob = {n: s.hex() for n, s in self.entries.items()}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(blob, f)
        os.replace(tmp, path)
        os.chmod(path, 0o600)

    @staticmethod
    def load(path: str) -> "Keyring":
        with open(path) as f:
            blob = json.load(f)
        return Keyring({n: bytes.fromhex(s) for n, s in blob.items()})


# ------------------------------------------------------------- tickets ---

def _ticket_payload(entity: str, service: str, session_key: bytes,
                    expires: float) -> bytes:
    return json.dumps({"entity": entity, "service": service,
                       "key": session_key.hex(),
                       "expires": expires}).encode()


class TicketServer:
    """Mon-side ticket granting (CephxServiceHandler role)."""

    def __init__(self, keyring: Keyring):
        self.keyring = keyring

    def grant(self, entity: str, service: str,
              ttl: float = TICKET_TTL_S) -> Tuple[bytes, bytes]:
        """-> (ticket sealed under the SERVICE secret, session key
        sealed under the ENTITY secret)."""
        entity_secret = self.keyring.secret(entity)
        service_secret = self.keyring.secret(service)
        session_key = secrets.token_bytes(32)
        expires = time.time() + ttl
        ticket = seal(service_secret,
                      _ticket_payload(entity, service, session_key,
                                      expires))
        key_box = seal(entity_secret, session_key +
                       struct.pack("<d", expires))
        return ticket, key_box


def open_key_box(entity_secret: bytes, key_box: bytes) -> bytes:
    """Client side: recover the session key from the mon's grant."""
    blob = unseal(entity_secret, key_box)
    if len(blob) != 40:
        raise AuthError("malformed key box")
    return blob[:32]


def make_authorizer(session_key: bytes, nonce: bytes) -> bytes:
    """Proof of session-key possession for the connection nonce."""
    return hmac.new(session_key, b"authorizer" + nonce, sha256).digest()


def verify_authorizer(service_secret: bytes, ticket: bytes,
                      authorizer: bytes, nonce: bytes) -> Tuple[str, bytes]:
    """Service side: -> (entity name, session key); raises AuthError on
    any forgery, expiry, or wrong-service ticket."""
    payload = json.loads(unseal(service_secret, ticket).decode())
    if payload["expires"] < time.time():
        raise AuthError("ticket expired")
    session_key = bytes.fromhex(payload["key"])
    want = hmac.new(session_key, b"authorizer" + nonce, sha256).digest()
    if not hmac.compare_digest(authorizer, want):
        raise AuthError("authorizer rejected")
    return payload["entity"], session_key
