"""Perf counters — the L0 metrics substrate.

Role of the reference's `PerfCounters` (src/common/perf_counters.h:
typed u64 counters / gauges / long-run latency averages, grouped per
subsystem, dumped as JSON over the admin socket via `perf dump`) and of
the OSD's counter set (src/osd/osd_perf_counters.cc).

TPU-native counter set: what matters on this runtime is device
dispatches (compiles vs cached executions), bytes moved host<->device,
batch occupancies, and table-cache hit rates — those are the knobs that
decide whether the MXU stays fed.  Counters are cheap (dict + lock) and
always safe to leave enabled; `perf_counters_enabled=false` turns the
`inc` calls into no-ops for hot host loops.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .options import OptionError, config

COUNTER = "counter"      # monotonically increasing u64
GAUGE = "gauge"          # instantaneous value
TIME_AVG = "time_avg"    # (sum_seconds, count) -> avg latency

# hot-path switch: counter updates happen per device dispatch, so the
# enabled flag is cached module-level and kept fresh by a config
# observer instead of re-resolving the layered registry per inc()
_enabled: Optional[bool] = None


def _counters_enabled() -> bool:
    global _enabled
    if _enabled is None:
        cfg = config()
        try:
            _enabled = bool(cfg.get("perf_counters_enabled"))
        except OptionError:
            _enabled = True

        def _refresh(_name, value):
            global _enabled
            _enabled = bool(value)

        cfg.observe("perf_counters_enabled", _refresh)
    return _enabled


class PerfCounters:
    """One named group of counters (a daemon-subsystem analog)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._types: Dict[str, str] = {}
        self._vals: Dict[str, Any] = {}

    def add_counter(self, key: str, desc: str = "") -> None:
        self._declare(key, COUNTER, 0)

    def add_gauge(self, key: str, desc: str = "") -> None:
        self._declare(key, GAUGE, 0)

    def add_time_avg(self, key: str, desc: str = "") -> None:
        self._declare(key, TIME_AVG, (0.0, 0))

    def _declare(self, key: str, typ: str, init: Any) -> None:
        with self._lock:
            if key not in self._types:
                self._types[key] = typ
                self._vals[key] = init

    # ------------------------------------------------------------ update --
    def inc(self, key: str, by: int = 1) -> None:
        if not _counters_enabled():
            return
        with self._lock:
            if key not in self._types:
                self._types[key] = COUNTER
                self._vals[key] = 0
            self._vals[key] += by

    def set(self, key: str, value: Any) -> None:
        if not _counters_enabled():
            return
        with self._lock:
            self._types[key] = GAUGE
            self._vals[key] = value

    def tinc(self, key: str, seconds: float) -> None:
        if not _counters_enabled():
            return
        with self._lock:
            if self._types.get(key) != TIME_AVG:
                self._types[key] = TIME_AVG
                self._vals[key] = (0.0, 0)
            s, n = self._vals[key]
            self._vals[key] = (s + seconds, n + 1)

    def time(self, key: str):
        """Context manager: `with counters.time("map_batch_s"): ...`."""
        return _Timer(self, key)

    # -------------------------------------------------------------- read --
    def get(self, key: str) -> Any:
        with self._lock:
            return self._vals.get(key)

    def dump(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        with self._lock:
            for key, typ in sorted(self._types.items()):
                v = self._vals[key]
                if typ == TIME_AVG:
                    s, n = v
                    out[key] = {"avgcount": n, "sum": round(s, 9),
                                "avgtime": round(s / n, 9) if n else 0.0}
                else:
                    out[key] = v
        return out

    def reset(self) -> None:
        with self._lock:
            for key, typ in self._types.items():
                self._vals[key] = (0.0, 0) if typ == TIME_AVG else 0


class _Timer:
    def __init__(self, pc: PerfCounters, key: str):
        self.pc, self.key = pc, key

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.pc.tinc(self.key, time.perf_counter() - self.t0)
        return False


class PerfCountersCollection:
    """All groups in the process; `perf dump` analog
    (src/common/perf_counters_collection.h)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: Dict[str, PerfCounters] = {}

    def get(self, name: str) -> PerfCounters:
        with self._lock:
            pc = self._groups.get(name)
            if pc is None:
                pc = self._groups[name] = PerfCounters(name)
            return pc

    def dump(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            groups = list(self._groups.items())
        return {name: pc.dump() for name, pc in sorted(groups)}

    def reset(self) -> None:
        with self._lock:
            groups = list(self._groups.values())
        for pc in groups:
            pc.reset()


_collection: Optional[PerfCountersCollection] = None
_collection_lock = threading.Lock()


def perf(name: str = None) -> Any:
    """perf() -> the collection; perf("group") -> that group."""
    global _collection
    with _collection_lock:
        if _collection is None:
            _collection = PerfCountersCollection()
    return _collection if name is None else _collection.get(name)
