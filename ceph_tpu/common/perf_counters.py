"""Perf counters — the L0 metrics substrate.

Role of the reference's `PerfCounters` (src/common/perf_counters.h:
typed u64 counters / gauges / long-run latency averages, grouped per
subsystem, dumped as JSON over the admin socket via `perf dump`) and of
the OSD's counter set (src/osd/osd_perf_counters.cc).

TPU-native counter set: what matters on this runtime is device
dispatches (compiles vs cached executions), bytes moved host<->device,
batch occupancies, and table-cache hit rates — those are the knobs that
decide whether the MXU stays fed.  Counters are cheap (dict + lock) and
always safe to leave enabled; `perf_counters_enabled=false` turns the
`inc` calls into no-ops for hot host loops.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .options import OptionError, config

COUNTER = "counter"      # monotonically increasing u64
GAUGE = "gauge"          # instantaneous value
TIME_AVG = "time_avg"    # (sum_seconds, count) -> avg latency
HISTOGRAM = "histogram"  # log2-bucketed latency distribution

# hot-path switch: counter updates happen per device dispatch, so the
# enabled flag is cached module-level and kept fresh by a config
# observer instead of re-resolving the layered registry per inc()
_enabled: Optional[bool] = None


def _counters_enabled() -> bool:
    global _enabled
    if _enabled is None:
        cfg = config()
        try:
            _enabled = bool(cfg.get("perf_counters_enabled"))
        except OptionError:
            _enabled = True

        def _refresh(_name, value):
            global _enabled
            _enabled = bool(value)

        cfg.observe("perf_counters_enabled", _refresh)
    return _enabled


class PerfHistogram:
    """Log2-bucketed latency histogram (src/common/perf_histogram.h
    role).  Bucket i holds values in (base*2^(i-1), base*2^i]; one
    overflow bucket catches everything past the last bound.  Averages
    hide queueing/encode tails — this is the per-stage distribution the
    OpTracker records into, and it renders directly as a Prometheus
    histogram family (cumulative `_bucket` + `_sum`/`_count`)."""

    __slots__ = ("base", "n_buckets", "counts", "sum", "count")

    def __init__(self, base: float = 1e-6, n_buckets: int = 28):
        if base <= 0 or n_buckets < 1:
            raise ValueError("histogram needs base > 0, n_buckets >= 1")
        self.base = float(base)          # le bound of bucket 0
        self.n_buckets = int(n_buckets)
        self.counts = [0] * (self.n_buckets + 1)   # +1 = overflow (+Inf)
        self.sum = 0.0
        self.count = 0

    def bucket_index(self, v: float) -> int:
        if v <= self.base:
            return 0
        idx = int(math.ceil(math.log2(v / self.base)))
        # float-error guard at exact power-of-two bounds: the smallest
        # bucket whose le bound still covers v wins
        if idx > 0 and v <= self.base * (2.0 ** (idx - 1)):
            idx -= 1
        return min(idx, self.n_buckets)

    def record(self, v: float) -> None:
        self.counts[self.bucket_index(v)] += 1
        self.sum += v
        self.count += 1

    def bounds(self) -> List[float]:
        """le upper bound per finite bucket (overflow bucket is +Inf)."""
        return [self.base * (2.0 ** i) for i in range(self.n_buckets)]

    def dump(self) -> Dict[str, Any]:
        """Non-cumulative counts + bounds; consumers (Prometheus)
        cumulate.  Only populated buckets are listed, keyed by le."""
        buckets = []
        bounds = self.bounds()
        for i, c in enumerate(self.counts[:-1]):
            if c:
                buckets.append([bounds[i], c])
        if self.counts[-1]:
            buckets.append(["+Inf", self.counts[-1]])
        return {"count": self.count, "sum": round(self.sum, 9),
                "buckets": buckets}

    def reset(self) -> None:
        self.counts = [0] * (self.n_buckets + 1)
        self.sum = 0.0
        self.count = 0


class PerfCounters:
    """One named group of counters (a daemon-subsystem analog)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._types: Dict[str, str] = {}
        self._vals: Dict[str, Any] = {}

    def add_counter(self, key: str, desc: str = "") -> None:
        self._declare(key, COUNTER, 0)

    def add_gauge(self, key: str, desc: str = "") -> None:
        self._declare(key, GAUGE, 0)

    def add_time_avg(self, key: str, desc: str = "") -> None:
        self._declare(key, TIME_AVG, (0.0, 0))

    def add_histogram(self, key: str, desc: str = "",
                      base: float = 1e-6, n_buckets: int = 28) -> None:
        self._declare(key, HISTOGRAM, PerfHistogram(base, n_buckets))

    def _declare(self, key: str, typ: str, init: Any) -> None:
        with self._lock:
            if key not in self._types:
                self._types[key] = typ
                self._vals[key] = init

    # ------------------------------------------------------------ update --
    def inc(self, key: str, by: int = 1) -> None:
        if not _counters_enabled():
            return
        with self._lock:
            declared = self._types.get(key)
            if declared is None:
                self._types[key] = COUNTER
                self._vals[key] = 0
            elif declared not in (COUNTER, GAUGE):
                # inc on a gauge is legitimate (up/down adjustments);
                # on a TIME_AVG/HISTOGRAM it is a typo — same friendly
                # raise as set/tinc/hinc instead of a tuple TypeError
                raise ValueError(
                    f"{self.name}.{key}: inc() on a {declared} "
                    f"(declared types are immutable)")
            self._vals[key] += by

    def set(self, key: str, value: Any) -> None:
        if not _counters_enabled():
            return
        with self._lock:
            declared = self._types.get(key)
            if declared is not None and declared != GAUGE:
                # a typo'd set() used to silently retype a COUNTER /
                # TIME_AVG / HISTOGRAM to GAUGE, changing the dump shape
                # under the exporter mid-scrape
                raise ValueError(
                    f"{self.name}.{key}: set() on a {declared} "
                    f"(declared types are immutable; use "
                    f"inc/tinc/hinc)")
            self._types[key] = GAUGE
            self._vals[key] = value

    def tinc(self, key: str, seconds: float) -> None:
        if not _counters_enabled():
            return
        with self._lock:
            declared = self._types.get(key)
            if declared is None:
                self._types[key] = TIME_AVG
                self._vals[key] = (0.0, 0)
            elif declared != TIME_AVG:
                raise ValueError(
                    f"{self.name}.{key}: tinc() on a {declared} "
                    f"(declared types are immutable)")
            s, n = self._vals[key]
            self._vals[key] = (s + seconds, n + 1)

    def hinc(self, key: str, value: float) -> None:
        """Record one observation into a log2 histogram (auto-declared
        with default bucketing on first use, like inc/tinc)."""
        if not _counters_enabled():
            return
        with self._lock:
            declared = self._types.get(key)
            if declared is None:
                self._types[key] = HISTOGRAM
                self._vals[key] = PerfHistogram()
            elif declared != HISTOGRAM:
                raise ValueError(
                    f"{self.name}.{key}: hinc() on a {declared} "
                    f"(declared types are immutable)")
            self._vals[key].record(value)

    def time(self, key: str):
        """Context manager: `with counters.time("map_batch_s"): ...`."""
        return _Timer(self, key)

    # -------------------------------------------------------------- read --
    def get(self, key: str) -> Any:
        with self._lock:
            return self._vals.get(key)

    def type_of(self, key: str) -> Optional[str]:
        with self._lock:
            return self._types.get(key)

    def histogram(self, key: str) -> Optional[PerfHistogram]:
        """The live histogram object (exporters need bounds + counts)."""
        with self._lock:
            v = self._vals.get(key)
            return v if isinstance(v, PerfHistogram) else None

    def _dump_one(self, key: str, typ: str) -> Any:
        v = self._vals[key]
        if typ == TIME_AVG:
            s, n = v
            return {"avgcount": n, "sum": round(s, 9),
                    "avgtime": round(s / n, 9) if n else 0.0}
        if typ == HISTOGRAM:
            return v.dump()
        return v

    def dump(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        with self._lock:
            for key, typ in sorted(self._types.items()):
                out[key] = self._dump_one(key, typ)
        return out

    def dump_typed(self) -> Dict[str, Tuple[str, Any]]:
        """{key: (type, dumped value)} — exporters render by type."""
        out: Dict[str, Tuple[str, Any]] = {}
        with self._lock:
            for key, typ in sorted(self._types.items()):
                out[key] = (typ, self._dump_one(key, typ))
        return out

    def reset(self) -> None:
        with self._lock:
            for key, typ in self._types.items():
                if typ == TIME_AVG:
                    self._vals[key] = (0.0, 0)
                elif typ == HISTOGRAM:
                    self._vals[key].reset()
                else:
                    self._vals[key] = 0


class _Timer:
    def __init__(self, pc: PerfCounters, key: str):
        self.pc, self.key = pc, key

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.pc.tinc(self.key, time.perf_counter() - self.t0)
        return False


class PerfCountersCollection:
    """All groups in the process; `perf dump` analog
    (src/common/perf_counters_collection.h)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: Dict[str, PerfCounters] = {}

    def get(self, name: str) -> PerfCounters:
        with self._lock:
            pc = self._groups.get(name)
            if pc is None:
                pc = self._groups[name] = PerfCounters(name)
            return pc

    def dump(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            groups = list(self._groups.items())
        return {name: pc.dump() for name, pc in sorted(groups)}

    def dump_typed(self) -> Dict[str, Dict[str, Tuple[str, Any]]]:
        with self._lock:
            groups = list(self._groups.items())
        return {name: pc.dump_typed() for name, pc in sorted(groups)}

    def reset(self) -> None:
        with self._lock:
            groups = list(self._groups.values())
        for pc in groups:
            pc.reset()


_collection: Optional[PerfCountersCollection] = None
_collection_lock = threading.Lock()


def perf(name: str = None) -> Any:
    """perf() -> the collection; perf("group") -> that group."""
    global _collection
    with _collection_lock:
        if _collection is None:
            _collection = PerfCountersCollection()
    return _collection if name is None else _collection.get(name)
