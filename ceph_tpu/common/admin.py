"""Admin command server — the AdminSocket analog.

The reference exposes per-daemon JSON commands over a Unix socket
(src/common/admin_socket.{h,cc}: `ceph daemon <name> perf dump`,
`config show`, `config set`, ...).  Here the same surface is a command
registry dispatchable in-process (for tools/tests) or served over a
Unix domain socket (for a live runtime): newline-delimited JSON
requests {"prefix": "...", ...args} -> JSON replies.

Built-ins registered on every AdminServer:
  config show / config get / config set    (options.py registry)
  perf dump / perf reset                   (perf_counters.py collection)
"""
from __future__ import annotations

import json
import os
import socket
import threading
from typing import Any, Callable, Dict, Optional

from .options import OptionError, config
from .perf_counters import perf

Handler = Callable[[Dict[str, Any]], Any]


class AdminServer:
    def __init__(self):
        self._handlers: Dict[str, Handler] = {}
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._path: Optional[str] = None
        self.register("config show", lambda a: config().dump())
        self.register("config get",
                      lambda a: {a["key"]: config().get(a["key"])})
        self.register("config set", self._config_set)
        self.register("perf dump", lambda a: perf().dump())
        self.register("perf reset", self._perf_reset)
        from .tracer import tracer
        self.register("trace dump", lambda a: tracer().dump())
        self.register("trace reset",
                      lambda a: (tracer().reset(), {"success": True})[1])
        # cross-process trace collection surface (`ceph daemon <name>
        # dump_traces` / the `ceph trace <op>` assembler's per-daemon
        # fetch): spans + buffer occupancy/drop health
        self.register("dump_traces", lambda a: tracer().dump_traces())
        from .op_tracker import tracker
        self.register("dump_ops_in_flight",
                      lambda a: tracker().dump_ops_in_flight())
        self.register("dump_historic_ops",
                      lambda a: tracker().dump_historic_ops())
        self.register("dump_historic_slow_ops",
                      lambda a: tracker().dump_historic_slow_ops())
        # runtime fault-injection control (the thrasher's per-daemon
        # arming surface; fire counts prove injections happened)
        from .faults import admin_handler as _fault_admin
        self.register("fault_injection", _fault_admin)
        self.register("help", lambda a: sorted(self._handlers))

    @staticmethod
    def _config_set(args: Dict[str, Any]) -> Any:
        v = config().set(args["key"], args["value"])
        return {"success": True, "value": v}

    @staticmethod
    def _perf_reset(args: Dict[str, Any]) -> Any:
        perf().reset()
        return {"success": True}

    # ---------------------------------------------------------- registry --
    def register(self, prefix: str, handler: Handler) -> None:
        if prefix in self._handlers:
            raise ValueError(f"duplicate admin command {prefix!r}")
        self._handlers[prefix] = handler

    def handle(self, request: Dict[str, Any]) -> Dict[str, Any]:
        prefix = request.get("prefix", "")
        handler = self._handlers.get(prefix)
        if handler is None:
            return {"error": f"unknown command {prefix!r}",
                    "commands": sorted(self._handlers)}
        try:
            return {"result": handler(request)}
        except (KeyError, OptionError, ValueError) as e:
            return {"error": str(e)}

    def handle_json(self, line: str) -> str:
        try:
            req = json.loads(line)
        except json.JSONDecodeError as e:
            return json.dumps({"error": f"bad json: {e}"})
        return json.dumps(self.handle(req))

    # ------------------------------------------------------------ socket --
    def serve(self, path: str) -> None:
        """Listen on a Unix socket; one JSON request per line."""
        if self._sock is not None:
            raise RuntimeError("already serving")
        if os.path.exists(path):
            os.unlink(path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(path)
        self._sock.listen(8)
        self._path = path
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            sock = self._sock
            if sock is None:
                return
            try:
                conn, _ = sock.accept()
            except (OSError, ValueError):
                return            # closed
            with conn:
                try:
                    # a silent client must not wedge the admin socket
                    conn.settimeout(5.0)
                    buf = b""
                    while b"\n" not in buf:
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        buf += chunk
                    if buf:
                        line = buf.split(b"\n", 1)[0].decode()
                        conn.sendall(
                            self.handle_json(line).encode() + b"\n")
                except OSError:
                    continue       # timeout / reset: drop this client

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
            if self._path and os.path.exists(self._path):
                os.unlink(self._path)


def admin_request(path: str, request: Dict[str, Any],
                  timeout: float = 5.0) -> Dict[str, Any]:
    """Client side: one request to a served AdminServer socket."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout)
        s.connect(path)
        s.sendall(json.dumps(request).encode() + b"\n")
        buf = b""
        while b"\n" not in buf:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.split(b"\n", 1)[0].decode())
