"""Deterministic exponential backoff with jitter + a sim-tick clock.

The retry sweeps the thrasher hardens (objecter resends, the remote
client's map-refresh loops, daemon boot) previously slept on bare
linear schedules (``0.05 * (attempt + 1)``) — synchronized retries
from many clients stampede a recovering daemon, and unseeded sleeps
make soak runs unreproducible.  This module is the shared policy:

  * ``ExpBackoff`` — capped exponential delay with DETERMINISTIC
    seeded jitter (full-jitter shape: delay drawn uniformly from
    (1-jitter)*d .. d), so two runs with the same seed sleep the same
    schedule while distinct seeds decorrelate.
  * ``TickClock`` — a simulation clock whose ``sleep`` advances a
    counter instead of the wall (the in-process objecter's clock: its
    retry loop must be instantaneous and deterministic under test).

Reference shape: the OSD's exponential backoff on mon reconnect
(OSD::ms_handle_connect retry ladder) and qa's thrasher timing model.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional


class TickClock:
    """Sim-tick clock: ``sleep`` advances ``now`` without wall time."""

    def __init__(self) -> None:
        self.now = 0.0
        self.sleeps = 0

    def sleep(self, seconds: float) -> None:
        self.now += float(seconds)
        self.sleeps += 1


class ExpBackoff:
    """Capped exponential backoff, deterministically jittered.

    ``delay(attempt)`` is pure given the construction seed and the
    call sequence; ``sleep(attempt)`` applies it through the injected
    sleep function (wall-clock by default, a TickClock in sims).
    """

    def __init__(self, base: float = 0.05, factor: float = 2.0,
                 cap: float = 1.0, jitter: float = 0.5,
                 seed: Optional[int] = 0,
                 sleep: Callable[[float], None] = time.sleep):
        if base <= 0 or factor < 1.0 or cap < base:
            raise ValueError("backoff needs base > 0, factor >= 1, "
                             "cap >= base")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.base = float(base)
        self.factor = float(factor)
        self.cap = float(cap)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)
        self._sleep = sleep

    def delay(self, attempt: int) -> float:
        d = min(self.cap, self.base * self.factor ** max(0, attempt))
        if self.jitter:
            d *= 1.0 - self.jitter * self._rng.random()
        return d

    def sleep(self, attempt: int) -> float:
        d = self.delay(attempt)
        self._sleep(d)
        return d
