"""Fault injection registry — named faultpoints with seeded schedules.

Role of the reference's scattered injection knobs
(``ms_inject_socket_failures`` in src/common/options.cc consumed by the
messenger, bluestore's read-error injection, the ``kill_osd`` hooks
teuthology's thrashosds drives): ONE process-wide registry of *named*
faultpoints.  Each point is declared exactly once, where its fire site
lives, with a docstring — ``faults.declare("wire.drop_frame", "...")``
— and fire sites ask ``faults.fire("wire.drop_frame", **ctx)``.

Cost contract: a DISARMED faultpoint is a single dict-miss check
(``name not in armed``) — no locks, no rng, no allocation — so fire
sites are safe on the put/get hot path.  Armed points pay one lock +
one schedule evaluation.

Schedules (all deterministic, seeded — the thrasher's reproducibility
contract):

  * ``always``      fire on every evaluation
  * ``one_in``      fire when ``Random(seed).randrange(n) == 0``
                    (the ms_inject_socket_failures shape)
  * ``nth``         fire exactly once, on the nth evaluation
  * ``predicate``   fire when ``predicate(ctx)`` is truthy (API-only;
                    not armable over the admin wire)

An optional ``match={"cmd": "put_shard"}`` filter gates evaluation on
the fire-site context (the "chosen phase" selector for crash/hang
points) and ``count`` bounds total fires.  ``fire()`` returns None
(not armed / schedule says no) or the armed ``params`` dict, so sites
can carry knobs like hang seconds through the registry.

Every fire increments a counter in the ``faults`` perf group (and a
cumulative in-registry tally that survives disarm), so tests prove
injections actually happened: ``perf dump`` / the ``fault_injection``
admin command expose them per daemon (each process owns its registry).

Static closure: cephtpu-lint CTL601 requires every ``faults.fire``
literal to name a declared point; CTL602 bans ``faults.fire`` inside
jit-reachable code (a host-side branch would burn the compiled path).

Partition faults: ``net.partition`` is the cross-layer netsplit
faultpoint (the iptables-drop teuthology uses between daemon hosts).
It is armed with ``groups`` — a list of entity-name lists — and
severs traffic whose ``src`` and ``dst`` context entities fall in
DIFFERENT groups (entities in no group are unaffected).  The
``oneway`` param makes the cut asymmetric: only frames FROM
``groups[0]`` TOWARD the other groups are dropped, the reverse
direction still delivers (half-open links, the nastier real-world
shape).  Arming goes through the normal grammar — the registry builds
the membership predicate itself, so the asok path works:

    fault_injection arm net.partition
        params={"groups": [["osd.0","osd.1"], ["mon","client",...]],
                "oneway": false}

Fire sites ask ``faults.partitioned(src, dst)`` (or fire() with
src/dst ctx); a fire is counted only when the cut actually severed
that (src, dst) pair, so fire counts prove the partition carried.
"""
from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from .perf_counters import perf as _perf

MODES = ("always", "one_in", "nth", "predicate")


class FaultError(ValueError):
    """Bad declaration/arming (unknown point, bad mode, dup doc)."""


def _partition_predicate(params: Dict[str, Any]) -> Callable:
    """Membership predicate for ``net.partition``: severed iff src and
    dst sit in different groups (oneway: only groups[0] -> others)."""
    try:
        groups = [frozenset(g) for g in params["groups"]]
    except (TypeError, KeyError):
        raise FaultError("net.partition needs groups=[[entity,...],"
                         "...] (lists of entity names)")
    if len(groups) < 2 or any(not g for g in groups):
        raise FaultError("net.partition needs >= 2 non-empty groups")
    oneway = bool(params.get("oneway", False))

    def severed(ctx: Dict[str, Any]) -> bool:
        src, dst = ctx.get("src"), ctx.get("dst")
        gi = next((i for i, g in enumerate(groups) if src in g), None)
        gj = next((i for i, g in enumerate(groups) if dst in g), None)
        if gi is None or gj is None or gi == gj:
            return False          # unlisted or same-side: delivered
        if oneway:
            return gi == 0        # only groups[0] -> others is cut
        return True

    return severed


@dataclass
class _Armed:
    mode: str
    n: int = 0
    seed: int = 0
    count: Optional[int] = None              # max fires; None = unbounded
    predicate: Optional[Callable[[Dict[str, Any]], bool]] = None
    match: Optional[Dict[str, Any]] = None   # ctx filter (phase choice)
    params: Dict[str, Any] = field(default_factory=dict)
    calls: int = 0
    fires: int = 0
    rng: Optional[random.Random] = None


class FaultRegistry:
    """Process-wide faultpoint registry (one per daemon process)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._declared: Dict[str, str] = {}      # name -> docstring
        self._armed: Dict[str, _Armed] = {}
        self._fired: Dict[str, int] = {}         # cumulative, survives disarm
        self._pc = _perf("faults")

    # ------------------------------------------------------- declaration --
    def declare(self, name: str, doc: str) -> None:
        """Declare a faultpoint once, where its fire site lives.
        Idempotent for an identical doc (module re-import); a second
        declaration with a DIFFERENT doc is a name collision."""
        with self._lock:
            existing = self._declared.get(name)
            if existing is not None and existing != doc:
                raise FaultError(
                    f"faultpoint {name!r} already declared with a "
                    f"different docstring")
            self._declared[name] = doc

    def declared(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._declared)

    # ------------------------------------------------------------ arming --
    def arm(self, name: str, mode: str = "always", n: int = 0,
            seed: int = 0, count: Optional[int] = None,
            predicate: Optional[Callable] = None,
            match: Optional[Dict[str, Any]] = None,
            **params: Any) -> None:
        if mode not in MODES:
            raise FaultError(f"unknown fault mode {mode!r}; "
                             f"known: {MODES}")
        if mode == "one_in" and n < 1:
            raise FaultError(f"{name}: one_in needs n >= 1")
        if mode == "nth" and n < 1:
            raise FaultError(f"{name}: nth needs n >= 1")
        if mode == "predicate" and predicate is None:
            raise FaultError(f"{name}: predicate mode needs a callable")
        if match is not None and not isinstance(match, dict):
            # a stringly-typed match (e.g. un-parsed CLI JSON) would
            # poison every subsequent fire with an AttributeError
            raise FaultError(f"{name}: match must be a dict of "
                             f"context key -> expected value, got "
                             f"{type(match).__name__}")
        if name == "net.partition" and predicate is None:
            # partition arming carries groups, not a schedule: the
            # registry builds the membership predicate itself so the
            # asok grammar (which cannot ship callables) arms it
            predicate = _partition_predicate(params)
            mode = "predicate"
        with self._lock:
            if name not in self._declared:
                raise FaultError(
                    f"unknown faultpoint {name!r}; declared: "
                    f"{sorted(self._declared)}")
            self._armed[name] = _Armed(
                mode=mode, n=int(n), seed=int(seed), count=count,
                predicate=predicate, match=match, params=dict(params),
                rng=random.Random(seed))

    def disarm(self, name: Optional[str] = None) -> None:
        """Disarm one point (or all).  Cumulative fire counts persist."""
        with self._lock:
            if name is None:
                self._armed.clear()
            else:
                self._armed.pop(name, None)

    def reset(self) -> None:
        """Disarm everything and zero the cumulative fire tallies
        (test teardown; perf counters are reset separately)."""
        with self._lock:
            self._armed.clear()
            self._fired.clear()

    # ------------------------------------------------------------ firing --
    def fire(self, name: str, **ctx: Any) -> Optional[Dict[str, Any]]:
        """None when disarmed or the schedule says no; the armed params
        dict on a fire.  The disarmed path is one dict-miss check."""
        if name not in self._armed:
            return None
        return self._evaluate(name, ctx)

    def _evaluate(self, name: str,
                  ctx: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        with self._lock:
            a = self._armed.get(name)
            if a is None:                      # raced a disarm
                return None
            if a.match is not None and any(
                    ctx.get(k) != v for k, v in a.match.items()):
                return None                    # wrong phase: not a call
            a.calls += 1
            if a.count is not None and a.fires >= a.count:
                return None
            if a.mode == "always":
                hit = True
            elif a.mode == "one_in":
                hit = a.rng.randrange(a.n) == 0
            elif a.mode == "nth":
                hit = a.calls == a.n
            else:                              # predicate
                hit = bool(a.predicate(ctx))
            if not hit:
                return None
            a.fires += 1
            self._fired[name] = self._fired.get(name, 0) + 1
            params = dict(a.params)
        self._pc.inc(name)                     # fire proof for tests
        return params

    # ------------------------------------------------------------- query --
    def fire_counts(self) -> Dict[str, int]:
        """Cumulative fires per faultpoint (survives disarm)."""
        with self._lock:
            return dict(self._fired)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "declared": dict(self._declared),
                "armed": {
                    name: {"mode": a.mode, "n": a.n, "seed": a.seed,
                           "count": a.count, "match": a.match,
                           "params": dict(a.params),
                           "calls": a.calls, "fires": a.fires}
                    for name, a in sorted(self._armed.items())},
                "fire_counts": dict(self._fired),
            }


_REGISTRY = FaultRegistry()


def registry() -> FaultRegistry:
    return _REGISTRY


# declared HERE (not at a single fire site): the partition cut is a
# cross-layer point — wire frames, in-process queue admission, peer
# heartbeats and quorum traffic all consult the same armed groups
registry().declare(
    "net.partition",
    "sever traffic between named daemon groups (both directions; "
    "params oneway=True cuts only groups[0] -> others) — the "
    "netsplit axis; arm with params={'groups': [[entity,...],...]}; "
    "fires count only actually-severed (src, dst) frames")


# declared HERE like net.partition: the power-loss axes are
# cross-layer points — the BlockDevice shim (cluster/blockdev.py)
# fires them on real store files, and the sim tier (SimOSD.put)
# mirrors the contract on its in-memory store, so one declaration
# covers both fire sites and the asok grammar arms either
registry().declare(
    "device.power_loss",
    "the process browns out AT a barrier (fsync never completes) — "
    "params exit=False raises PowerLoss in-process instead of dying; "
    "a POWER_LOSS marker makes the next boot run fsck(repair)")
registry().declare(
    "device.torn_write",
    "a device write persists only a prefix (params keep=bytes) and "
    "the process dies mid-write — the torn-write half of the "
    "power-loss crash model (params exit=False raises in-process)")
registry().declare(
    "device.lost_write",
    "the device acks a write that never reaches media (firmware "
    "write loss); the process continues — per-block checksums, "
    "fsck and scrub are the detectors")


def declare(name: str, doc: str) -> None:
    _REGISTRY.declare(name, doc)


def arm(name: str, mode: str = "always", **kw: Any) -> None:
    _REGISTRY.arm(name, mode=mode, **kw)


def disarm(name: Optional[str] = None) -> None:
    _REGISTRY.disarm(name)


def reset() -> None:
    _REGISTRY.reset()


def fire_counts() -> Dict[str, int]:
    return _REGISTRY.fire_counts()


def status() -> Dict[str, Any]:
    return _REGISTRY.status()


def fire(name: str, **ctx: Any) -> Optional[Dict[str, Any]]:
    """Module-level fast path: the disarmed case is ONE dict-miss
    check against the singleton's armed table (no method dispatch on
    the registry object, no lock)."""
    if name not in _REGISTRY._armed:
        return None
    return _REGISTRY._evaluate(name, ctx)


def partitioned(src: str, dst: str) -> bool:
    """True when an armed ``net.partition`` severs src -> dst traffic
    (counts a fire).  The disarmed case is one dict-miss check, so
    heartbeat/dispatch hot paths may call this unconditionally."""
    if "net.partition" not in _REGISTRY._armed:
        return False
    return _REGISTRY._evaluate("net.partition",
                               {"src": src, "dst": dst}) is not None


def admin_handler(args: Dict[str, Any]) -> Dict[str, Any]:
    """The ``fault_injection`` admin command (registered on every
    daemon's asok by AdminServer): runtime arm/disarm/status.

        {"prefix": "fault_injection"}                          -> status
        {"prefix": "fault_injection", "action": "arm",
         "name": "wire.drop_frame", "mode": "one_in",
         "n": 5, "seed": 3, "count": 2, "match": {...}}        -> arm
        {"prefix": "fault_injection", "action": "disarm",
         "name": "wire.drop_frame"}          -> disarm (no name: all)

    ``predicate`` mode is API-only: callables do not travel the wire.
    """
    action = args.get("action", "status")
    if action in ("status", "list"):
        return _REGISTRY.status()
    if action == "arm":
        mode = args.get("mode", "always")
        if mode == "predicate":
            raise ValueError("predicate mode is not armable over the "
                             "admin socket (callables don't serialize)")
        kw: Dict[str, Any] = {}
        if args.get("count") is not None:
            kw["count"] = int(args["count"])
        if args.get("match") is not None:
            kw["match"] = dict(args["match"])
        for p, v in (args.get("params") or {}).items():
            kw[p] = v
        _REGISTRY.arm(args["name"], mode=mode,
                      n=int(args.get("n", 0)),
                      seed=int(args.get("seed", 0)), **kw)
        return {"armed": args["name"], "mode": mode}
    if action == "disarm":
        _REGISTRY.disarm(args.get("name"))
        return {"disarmed": args.get("name") or "all"}
    raise ValueError(f"unknown fault_injection action {action!r} "
                     f"(status|list|arm|disarm)")
