"""lockdep — runtime lock-ordering cycle detection (src/common/lockdep.cc
+ mutex_debug.h roles).

The reference's mutex wrappers register every named lock and record the
ORDER graph between locks held together; an acquisition that would
create a cycle in that graph (an inversion: A-then-B somewhere,
B-then-A elsewhere) aborts with a backtrace before it can deadlock in
production.  Same contract here:

    from ceph_tpu.common.lockdep import LockdepLock, enable
    enable()
    a, b = LockdepLock("a"), LockdepLock("b")
    with a:
        with b: ...          # records a -> b
    with b:
        with a: ...          # raises LockOrderError (cycle a->b->a)

Disabled by default (zero overhead beyond a boolean); enable() in
tests/debug builds (the lockdep config option role).  Detection is
per-process across threads: the order graph is global, held-lock
stacks are thread-local.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Set


class LockOrderError(RuntimeError):
    pass


_enabled = False
_graph_lock = threading.Lock()
_order: Dict[str, Set[str]] = {}        # edges: earlier -> later
_tls = threading.local()


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    with _graph_lock:
        _order.clear()


def _held() -> List[str]:
    if not hasattr(_tls, "held"):
        _tls.held = []
    return _tls.held


def held_locks() -> List[str]:
    """This thread's currently-held lock names, outermost first
    (test/debug surface: proves the stack unwinds on exception
    paths — a stale entry would poison every later order check)."""
    return list(_held())


def _reaches(src: str, dst: str) -> bool:
    """DFS over the order graph (callers hold _graph_lock)."""
    stack, seen = [src], set()
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(_order.get(cur, ()))
    return False


def _before_acquire(name: str, recursive: bool = True) -> None:
    held = _held()
    if not held:
        return
    with _graph_lock:
        for h in held:
            if h == name:
                if recursive:
                    continue           # recursive re-acquire
                # a non-recursive lock re-acquired by its own holder
                # would deadlock right here — abort loudly instead
                raise LockOrderError(
                    f"recursive acquire of non-recursive lock "
                    f"{name!r} (self-deadlock)")
            # adding h -> name: a cycle exists iff name already
            # reaches h
            if _reaches(name, h):
                raise LockOrderError(
                    f"lock order inversion: acquiring {name!r} while "
                    f"holding {h!r}, but {name!r} -> ... -> {h!r} "
                    "was recorded earlier")
            _order.setdefault(h, set()).add(name)


class LockdepLock:
    """Lock wrapper with order registration.  ``recursive=True``
    (default) wraps an RLock; ``recursive=False`` wraps a plain Lock —
    converted daemon-plane locks keep their original self-deadlock
    semantics (and with lockdep enabled, a same-thread re-acquire
    raises LockOrderError instead of hanging).  Non-recursive locks
    need per-instance names: same-name re-acquire is indistinguishable
    from recursion."""

    def __init__(self, name: str, recursive: bool = True):
        self.name = name
        self.recursive = recursive
        self._lock = threading.RLock() if recursive else \
            threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _enabled:
            _before_acquire(self.name, self.recursive)
        got = self._lock.acquire(blocking, timeout)
        if got:
            _held().append(self.name)
        return got

    def release(self) -> None:
        held = _held()
        if self.name in held:
            # remove the most recent occurrence (recursive locks)
            for i in range(len(held) - 1, -1, -1):
                if held[i] == self.name:
                    del held[i]
                    break
        self._lock.release()

    def __enter__(self) -> "LockdepLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()
