"""Compressor plugins — the second dlopen-plugin family.

The reference ships compressors behind the same plugin pattern as the
EC codecs (src/compressor/ + src/common/PluginRegistry.cc: zlib,
snappy, zstd, lz4 selected by name, used by BlueStore and messenger
on-wire compression).  Same seam here: a registry keyed by name with a
factory, a conformance surface (compress/decompress + name), and the
algorithms Python ships natively (zlib, lzma, bz2, zstd when
available) — raising cleanly for ones this build lacks, like the
reference does for plugins compiled out.
"""
from __future__ import annotations

import bz2
import lzma
import threading
import zlib
from typing import Callable, Dict, Optional


class CompressorError(RuntimeError):
    pass


class Compressor:
    """Plugin surface (reference: src/compressor/Compressor.h)."""
    name = "none"

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, data: bytes) -> bytes:
        raise NotImplementedError


class _Zlib(Compressor):
    name = "zlib"

    def __init__(self, level: int = 5):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes) -> bytes:
        try:
            return zlib.decompress(data)
        except zlib.error as e:
            raise CompressorError(f"zlib: {e}") from e


class _Lzma(Compressor):
    name = "lzma"

    def compress(self, data: bytes) -> bytes:
        return lzma.compress(data)

    def decompress(self, data: bytes) -> bytes:
        try:
            return lzma.decompress(data)
        except lzma.LZMAError as e:
            raise CompressorError(f"lzma: {e}") from e


class _Bz2(Compressor):
    name = "bz2"

    def compress(self, data: bytes) -> bytes:
        return bz2.compress(data)

    def decompress(self, data: bytes) -> bytes:
        try:
            return bz2.decompress(data)
        except (OSError, ValueError) as e:
            raise CompressorError(f"bz2: {e}") from e


class _Zstd(Compressor):
    name = "zstd"

    def __init__(self):
        try:
            import zstandard
        except ImportError as e:
            raise CompressorError(
                "zstd support not built (zstandard module missing)") from e
        self._mod = zstandard

    def compress(self, data: bytes) -> bytes:
        return self._mod.ZstdCompressor().compress(data)

    def decompress(self, data: bytes) -> bytes:
        return self._mod.ZstdDecompressor().decompress(data)


class CompressorRegistry:
    """PluginRegistry analog: name -> factory, lazy instantiation."""

    def __init__(self):
        self._lock = threading.Lock()
        self._factories: Dict[str, Callable[[], Compressor]] = {}
        self.add("zlib", _Zlib)
        self.add("lzma", _Lzma)
        self.add("bz2", _Bz2)
        self.add("zstd", _Zstd)

    def add(self, name: str, factory: Callable[[], Compressor]) -> None:
        with self._lock:
            if name in self._factories:
                raise CompressorError(f"compressor {name!r} already "
                                      "registered")
            self._factories[name] = factory

    def factory(self, name: str) -> Compressor:
        with self._lock:
            f = self._factories.get(name)
        if f is None:
            raise CompressorError(
                f"unknown compressor {name!r} "
                f"(have {sorted(self._factories)})")
        return f()

    def names(self):
        with self._lock:
            return sorted(self._factories)


_registry: Optional[CompressorRegistry] = None
_registry_lock = threading.Lock()


def compressors() -> CompressorRegistry:
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = CompressorRegistry()
        return _registry
