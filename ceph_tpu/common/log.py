"""Leveled subsystem debug logging — the dout/ldout + src/log/ role.

The reference gates debug output per subsystem with two levels
(log level = written to the log, gather level = kept in the in-memory
ring for crash dumps; src/log/SubsystemMap.h, src/common/dout.h) and
drains entries through an async Log thread with a bounded buffer
(src/log/Log.cc).  Same shape:

    log = get_logger()
    log.set_level("osd", 10)
    log.dout("osd", 5, "pg 1.2 peering")       # emitted (5 <= 10)
    log.dout("crush", 20, "...")               # gated (default 5)

Entries above the log level but within the gather level land ONLY in
the recent-entries ring, which `dump_recent()` returns — the
"dump_recent on crash" behavior.  A writer callable (default: stderr
when CEPH_TPU_LOG=stderr, else buffered) receives formatted lines.
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

DEFAULT_LOG_LEVEL = 5
DEFAULT_GATHER_LEVEL = 20
RING_SIZE = 10_000


class Log:
    def __init__(self, writer: Optional[Callable[[str], None]] = None):
        self._lock = threading.Lock()
        self._levels: Dict[str, Tuple[int, int]] = {}
        self._ring: Deque[str] = collections.deque(maxlen=RING_SIZE)
        self.emitted = 0
        self.gathered = 0
        if writer is None and os.environ.get("CEPH_TPU_LOG") == "stderr":
            import sys
            writer = lambda line: print(line, file=sys.stderr)  # noqa: E731
        self._writer = writer

    # ------------------------------------------------------------ levels --
    def set_level(self, subsys: str, log_level: int,
                  gather_level: Optional[int] = None) -> None:
        if gather_level is None:
            gather_level = max(log_level, DEFAULT_GATHER_LEVEL)
        self._levels[subsys] = (log_level, gather_level)

    def levels(self, subsys: str) -> Tuple[int, int]:
        return self._levels.get(subsys,
                                (DEFAULT_LOG_LEVEL, DEFAULT_GATHER_LEVEL))

    def should_gather(self, subsys: str, level: int) -> bool:
        """The dout_impl gate: cheap check before formatting."""
        return level <= self.levels(subsys)[1]

    # -------------------------------------------------------------- dout --
    def dout(self, subsys: str, level: int, msg: str) -> None:
        log_lvl, gather_lvl = self.levels(subsys)
        if level > gather_lvl:
            return
        line = (f"{time.strftime('%Y-%m-%d %H:%M:%S')} "
                f"{level:2d} {subsys}: {msg}")
        with self._lock:
            self._ring.append(line)
            self.gathered += 1
            if level <= log_lvl:
                self.emitted += 1
                if self._writer is not None:
                    self._writer(line)

    # -------------------------------------------------------------- dump --
    def dump_recent(self, n: Optional[int] = None) -> List[str]:
        with self._lock:
            out = list(self._ring)
        return out if n is None else out[-n:]


_logger: Optional[Log] = None
_logger_lock = threading.Lock()


def get_logger() -> Log:
    global _logger
    with _logger_lock:
        if _logger is None:
            _logger = Log()
        return _logger


def dout(subsys: str, level: int, msg: str) -> None:
    get_logger().dout(subsys, level, msg)
