"""CRC32 combine algebra + the one-pass integrity scan (ZeroWire).

The wire tier's remaining CPU cost (PR 7's trace decomposition) was
three separate ``zlib.crc32`` passes over every payload byte: the
frame crc on send, the verify on receive, and BlueStore's per-4KiB
blob csums — each ~0.8 GB/s, so ~3.6 ms CPU/MiB of pure re-scanning.
CRC32 is linear over GF(2), which makes all three derivable from ONE
scan: compute per-block sub-crcs once, then *combine* them —

    crc(a || b) == crc32_combine(crc(a), crc(b), len(b))

— where the combine is a 32x32 GF(2) matrix apply (zlib's
crc32_combine, src/common/crc32c.cc ceph_crc32c combine role).  The
sender combines sub-crcs into the frame crc, the receiver's single
verify scan RE-DERIVES the sub-crcs and hands them to the store as
trusted blob csums, and the store never scans payload bytes again.

The combine operator for a fixed length is cached as four 256-entry
byte tables, so a per-4KiB combine costs 4 lookups + 4 XORs instead
of a 4096-byte scan.

Every full-payload scan on the wire/store hot path reports here
(:func:`note_scan`) so ``bench_wire_async`` / ``scripts/check_wire.py``
can count crc passes per MiB falsifiably; avoidable buffer
materializations report through :func:`note_copy` the same way.
"""
from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Sequence, Tuple

_POLY = 0xEDB88320          # reflected CRC-32 (the zlib polynomial)
_M32 = 0xFFFFFFFF

# default sub-crc granularity: BlueStore's min_alloc, so wire sub-crcs
# land 1:1 as blob csums (cluster/bluestore.py _make_blob)
CSUM_BLOCK = 4096


def as_u8(buf) -> memoryview:
    """``buf`` as a flat uint8 memoryview — the one normalization
    every byte-addressed consumer on the zero-copy spine (wire
    framing, shm ring, store, crc kernels) shares."""
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    return mv


# ------------------------------------------------------ GF(2) matrices ---
# A 32x32 matrix over GF(2) is a list of 32 column ints: column i is
# the image of basis vector (1 << i).

def _matrix_times(mat: List[int], vec: int) -> int:
    s = 0
    i = 0
    while vec:
        if vec & 1:
            s ^= mat[i]
        vec >>= 1
        i += 1
    return s


def _matrix_mul(a: List[int], b: List[int]) -> List[int]:
    """a @ b (apply b first, then a)."""
    return [_matrix_times(a, col) for col in b]


def _matrix_square(mat: List[int]) -> List[int]:
    return _matrix_mul(mat, mat)


def _zero_matrix(length: int) -> List[int]:
    """Operator advancing a crc register through ``length`` zero BYTES
    (zlib crc32_combine's squaring walk, composed into one matrix)."""
    ident = [1 << i for i in range(32)]
    if length <= 0:
        return ident
    odd = [_POLY] + [1 << i for i in range(31)]   # one zero BIT
    even = _matrix_square(odd)                    # two bits
    odd = _matrix_square(even)                    # four bits
    acc = ident
    n = length
    while True:
        even = _matrix_square(odd)                # next power of two
        if n & 1:
            acc = _matrix_mul(even, acc)
        n >>= 1
        if not n:
            break
        odd = _matrix_square(even)
        if n & 1:
            acc = _matrix_mul(odd, acc)
        n >>= 1
        if not n:
            break
    return acc


def _tables_of(mat: List[int]) -> List[List[int]]:
    """Byte-indexed apply tables: mat @ v == t[0][v&255] ^ t[1][..] ^
    t[2][..] ^ t[3][v>>24] — the per-block combine drops from a 32-bit
    walk to 4 lookups."""
    out: List[List[int]] = []
    for k in range(4):
        t = [0] * 256
        for b in range(8):
            img = mat[8 * k + b]
            bit = 1 << b
            for v in range(bit, 256):
                if v & bit:
                    t[v] = t[v ^ bit] ^ img
        out.append(t)
    return out


_op_cache: Dict[int, List[List[int]]] = {}

# byte-apply tables are cached ONLY for lengths that repeat hot (the
# per-block combine in Csums.scan hoists its own via _zero_op); every
# other length — frame totals, buffer tails, arbitrary series parts —
# goes through the log(n) power-of-two matrix walk below, so a
# long-lived daemon serving many distinct payload sizes does not
# accrete a ~37 KB table per size
_OP_CACHE_MAX = 64

# _pow_mats[k] = operator advancing a crc through 2^k zero BYTES
# (immutable tuple swapped atomically: a racing rebuild recomputes
# identical values, last writer wins)
_pow_mats: Tuple[List[int], ...] = ()


def _zero_op(length: int) -> List[List[int]]:
    t = _op_cache.get(length)
    if t is None:
        t = _tables_of(_zero_matrix(length))
        if len(_op_cache) < _OP_CACHE_MAX:
            _op_cache[length] = t
    return t


def _pow_matrices(nbits: int) -> Tuple[List[int], ...]:
    global _pow_mats
    mats = _pow_mats
    if len(mats) < nbits:
        lst = list(mats)
        if not lst:
            one_bit = [_POLY] + [1 << i for i in range(31)]
            one_byte = _matrix_square(_matrix_square(
                _matrix_square(one_bit)))
            lst.append(one_byte)
        while len(lst) < nbits:
            lst.append(_matrix_square(lst[-1]))
        _pow_mats = mats = tuple(lst)
    return mats


def _advance_zeros(crc: int, length: int) -> int:
    """Advance ``crc`` through ``length`` zero bytes: one 32x32
    matrix-vector apply per set bit of ``length`` (bounded work,
    nothing cached per distinct length)."""
    mats = _pow_matrices(length.bit_length())
    k = 0
    while length:
        if length & 1:
            crc = _matrix_times(mats[k], crc)
        length >>= 1
        k += 1
    return crc


def crc32_combine(crc1: int, crc2: int, len2: int) -> int:
    """crc of the concatenation from the parts' crcs (zlib
    crc32_combine): advance ``crc1`` through ``len2`` zero bytes (a
    GF(2) matrix apply), then xor ``crc2``."""
    if len2 <= 0:
        return crc1 & _M32
    t = _op_cache.get(len2)
    if t is not None:
        v = (t[0][crc1 & 0xFF] ^ t[1][(crc1 >> 8) & 0xFF] ^
             t[2][(crc1 >> 16) & 0xFF] ^ t[3][(crc1 >> 24) & 0xFF])
    else:
        v = _advance_zeros(crc1 & _M32, len2)
    return (v ^ crc2) & _M32


def combine_series(crc: int, subs: Sequence[int],
                   lens: Sequence[int]) -> int:
    """Fold per-part sub-crcs onto a running crc in order."""
    for sub, ln in zip(subs, lens):
        crc = crc32_combine(crc, sub, ln)
    return crc


# ------------------------------------------------------------ hot flags ---
# observer-cached ZeroWire config flags (wire_one_pass / wire_zero_copy)
# shared by the wire framing and the store: the hot path pays one dict
# hit, never a layered-options lookup per frame/blob.

_flag_cache: Dict[str, bool] = {}


def flag(name: str) -> bool:
    v = _flag_cache.get(name)
    if v is None:
        from .options import config
        cfg = config()

        def _refresh(_n, val, _name=name):
            _flag_cache[_name] = bool(val)

        cfg.observe(name, _refresh)
        v = _flag_cache[name] = bool(cfg.get(name))
    return v


# ---------------------------------------------------------- scan counts ---
# hot-path integrity accounting, shared by wire.py / bluestore.py /
# shm_ring.py: every FULL payload scan (a zlib.crc32 walk over wire
# bytes) and every avoidable payload copy is counted here, which is
# what lets the bench and scripts/check_wire.py assert "one crc pass
# per byte" instead of taking it on faith.

_pc = None


def _counters():
    global _pc
    if _pc is None:
        from .perf_counters import perf
        _pc = perf("wire.zero")
    return _pc


def note_scan(nbytes: int, site: str) -> None:
    """One crc pass over ``nbytes`` payload bytes at ``site``
    (send / verify / store / client / shm)."""
    if nbytes <= 0:
        return
    pc = _counters()
    pc.inc("crc_scans")
    pc.inc("crc_scan_bytes", int(nbytes))
    pc.inc(f"scan_{site}_bytes", int(nbytes))


def note_copy(nbytes: int, site: str) -> None:
    """One avoidable payload materialization (legacy copy path)."""
    if nbytes <= 0:
        return
    pc = _counters()
    pc.inc("copies")
    pc.inc("copy_bytes", int(nbytes))
    pc.inc(f"copy_{site}_bytes", int(nbytes))


def note_trusted(nbytes: int) -> None:
    """Bytes whose blob csums arrived pre-verified (store scan saved)."""
    if nbytes > 0:
        _counters().inc("trusted_csum_bytes", int(nbytes))


def wire_zero_counters(cluster_dir: Optional[str] = None,
                       n_osds: int = 0,
                       include_local: bool = True) -> Dict[str, float]:
    """Summed ``perf('wire.zero')`` counters across this process
    (``include_local``) and every OSD daemon's asok — the one
    falsifiable sensor behind every crc-passes/copies-per-MiB
    assertion (bench.py decompositions, scripts/check_wire.py,
    tests)."""
    out: Dict[str, float] = {}

    def add(d):
        for k, v in (d or {}).items():
            if isinstance(v, (int, float)):
                out[k] = out.get(k, 0) + v

    if include_local:
        add(_counters().dump())
    if cluster_dir is not None:
        import os
        from .admin import admin_request
        for i in range(int(n_osds)):
            asok = os.path.join(cluster_dir, f"osd.{i}.asok")
            try:
                r = admin_request(asok, {"prefix": "perf dump"}) \
                    .get("result") or {}
            except (OSError, IOError):
                continue
            add(r.get("wire.zero"))
    return out


# --------------------------------------------------------------- Csums ---

class Csums:
    """Per-block sub-crcs of one payload buffer — the product of the
    single integrity scan, carried from wherever the bytes were first
    scanned (sender framing, receiver verify, device crc kernel) to
    every downstream consumer (frame crc, staging digest, BlueStore
    blob csums)."""

    __slots__ = ("block", "subs", "length", "combined")

    def __init__(self, block: int, subs: List[int], length: int,
                 combined: Optional[int] = None):
        self.block = int(block)
        self.subs = subs
        self.length = int(length)
        if combined is None:
            combined = 0
            off = 0
            for sub in subs:
                n = min(self.block, length - off)
                combined = crc32_combine(combined, sub, n)
                off += n
        self.combined = combined & _M32

    @classmethod
    def scan(cls, buf, block: int = CSUM_BLOCK,
             site: str = "send") -> "Csums":
        """THE one pass: per-block sub-crcs + the combined whole-buffer
        crc from a single walk over ``buf``.  The inner loop is the
        wire tier's hottest Python: combine tables and bound methods
        are hoisted so a full block costs one zlib call + 4 lookups."""
        mv = as_u8(buf)
        length = len(mv)
        subs: List[int] = []
        combined = 0
        full_end = length - (length % block)
        if full_end:
            crc32 = zlib.crc32
            append = subs.append
            t0, t1, t2, t3 = _zero_op(block)
            off = 0
            while off < full_end:
                sub = crc32(mv[off:off + block])
                append(sub)
                combined = (t0[combined & 0xFF] ^
                            t1[(combined >> 8) & 0xFF] ^
                            t2[(combined >> 16) & 0xFF] ^
                            t3[combined >> 24]) ^ sub
                off += block
        if full_end < length:
            sub = zlib.crc32(mv[full_end:])
            subs.append(sub)
            combined = crc32_combine(combined, sub,
                                     length - full_end)
        note_scan(length, site)
        return cls(block, subs, length, combined & _M32)

    def block_lens(self) -> List[int]:
        return [min(self.block, self.length - off)
                for off in range(0, self.length, self.block)]

    def __repr__(self) -> str:  # debug only
        return (f"Csums(block={self.block}, n={len(self.subs)}, "
                f"len={self.length}, crc={self.combined:#x})")


def verify_blocks(buf, block: int, want_combined: int,
                  site: str = "verify") -> Tuple[bool, Csums]:
    """Receiver-side single pass: scan ``buf`` per block, combine,
    compare against the sender's combined crc.  Returns (ok, csums) —
    on ok the csums are TRUSTED (they verified the payload) and flow
    to the store without another scan."""
    cs = Csums.scan(buf, block=block, site=site)
    return cs.combined == (want_combined & _M32), cs
