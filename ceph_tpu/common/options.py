"""Typed configuration registry — the L0 config/flag substrate.

Plays the role of the reference's single typed options table
(src/common/options.cc — ~1,704 `Option` rows with type / default /
min / max / enum / description) and its layered `md_config_t`
(src/common/config.{h,cc}): compiled defaults < config file < env
< runtime `set`, with observer callbacks for live reconfig
(src/common/config_obs.h).

Design differences from the reference (deliberate, TPU-native):
  * the table is tiny and grows with the framework — every tunable the
    runtime reads (lookup strategy, lane caps, cache capacities) is
    REQUIRED to come from here, so a `config().dump()` shows the entire
    knob surface the way `ceph daemon ... config show` does;
  * values are plain Python scalars — the accelerator never sees the
    registry, only operands derived from it at dispatch time.

Env layering: option `foo_bar` reads `CEPH_TPU_FOO_BAR` (the round-1
ad-hoc env names are preserved as `env` aliases where they differed).
"""
from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

TYPE_INT = "int"
TYPE_FLOAT = "float"
TYPE_BOOL = "bool"
TYPE_STR = "str"

# precedence of value sources, low to high (reference: config layering,
# src/common/config.cc — default < file < env < runtime override)
LEVEL_DEFAULT = 0
LEVEL_FILE = 1
LEVEL_ENV = 2
LEVEL_RUNTIME = 3


class OptionError(ValueError):
    pass


@dataclass(frozen=True)
class Option:
    """One typed knob (reference schema: src/common/options.h)."""
    name: str
    type: str
    default: Any
    desc: str = ""
    min: Optional[float] = None
    max: Optional[float] = None
    enum_values: Optional[Tuple[str, ...]] = None
    env: Optional[str] = None            # env var override (default derived)
    runtime: bool = True                 # changeable after startup

    def env_var(self) -> str:
        return self.env or ("CEPH_TPU_" + self.name.upper())

    def coerce(self, value: Any) -> Any:
        try:
            if self.type == TYPE_INT:
                v = int(value)
            elif self.type == TYPE_FLOAT:
                v = float(value)
            elif self.type == TYPE_BOOL:
                if isinstance(value, str):
                    lv = value.strip().lower()
                    if lv in ("1", "true", "yes", "on"):
                        v = True
                    elif lv in ("0", "false", "no", "off"):
                        v = False
                    else:
                        raise OptionError(
                            f"{self.name}: bad bool {value!r}")
                else:
                    v = bool(value)
            elif self.type == TYPE_STR:
                v = str(value)
            else:
                raise OptionError(f"{self.name}: unknown type {self.type}")
        except (TypeError, ValueError) as e:
            raise OptionError(f"{self.name}: {e}") from e
        if self.min is not None and v < self.min:
            raise OptionError(f"{self.name}: {v} < min {self.min}")
        if self.max is not None and v > self.max:
            raise OptionError(f"{self.name}: {v} > max {self.max}")
        if self.enum_values is not None and v not in self.enum_values:
            raise OptionError(
                f"{self.name}: {v!r} not in {self.enum_values}")
        return v


class Options:
    """The registry + layered value store."""

    def __init__(self, table: Sequence[Option] = ()):
        self._lock = threading.RLock()
        self._schema: Dict[str, Option] = {}
        # name -> {level: value}
        self._values: Dict[str, Dict[int, Any]] = {}
        self._observers: Dict[str, List[Callable[[str, Any], None]]] = {}
        for opt in table:
            self.register(opt)

    # ------------------------------------------------------------ schema --
    def register(self, opt: Option) -> None:
        with self._lock:
            if opt.name in self._schema:
                raise OptionError(f"duplicate option {opt.name}")
            self._schema[opt.name] = opt

    def schema(self, name: str) -> Option:
        try:
            return self._schema[name]
        except KeyError:
            raise OptionError(f"unknown option {name}") from None

    def names(self) -> List[str]:
        return sorted(self._schema)

    # ------------------------------------------------------------ values --
    def get(self, name: str) -> Any:
        opt = self.schema(name)
        with self._lock:
            levels = self._values.get(name, {})
            if LEVEL_RUNTIME in levels:
                return levels[LEVEL_RUNTIME]
            if LEVEL_ENV in levels:
                return levels[LEVEL_ENV]
            ev = os.environ.get(opt.env_var())
            if ev is not None:
                # malformed env fails LOUDLY: silently regressing an
                # operator's setting to the default is worse than a crash
                return opt.coerce(ev)
            if LEVEL_FILE in levels:
                return levels[LEVEL_FILE]
            return opt.default

    def __getitem__(self, name: str) -> Any:
        return self.get(name)

    def set(self, name: str, value: Any, level: int = LEVEL_RUNTIME) -> Any:
        opt = self.schema(name)
        if level == LEVEL_RUNTIME and not opt.runtime:
            raise OptionError(f"{name} is not runtime-changeable")
        v = opt.coerce(value)
        with self._lock:
            self._values.setdefault(name, {})[level] = v
            obs = list(self._observers.get(name, ()))
        # observers see the EFFECTIVE value: a set at a masked level
        # (e.g. file under an env override) must not poison caches
        try:
            eff = self.get(name)
        except OptionError:
            eff = None
        if eff is not None:
            for cb in obs:
                cb(name, eff)
        return v

    def clear(self, name: str, level: int = LEVEL_RUNTIME) -> None:
        self.schema(name)
        with self._lock:
            removed = self._values.get(name, {}).pop(level, None)
            obs = list(self._observers.get(name, ()))
        if removed is None:
            return
        # observers track the EFFECTIVE value: clearing an override
        # changes it just like set() does, and a cached-flag observer
        # (perf enablement, the data plane's enabled()) left unnotified
        # would keep honoring the removed override forever
        try:
            eff = self.get(name)
        except OptionError:
            eff = None
        if eff is not None:
            for cb in obs:
                cb(name, eff)

    def load_file(self, path: str) -> None:
        """JSON config file: {"option": value, ...} at LEVEL_FILE."""
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            raise OptionError(f"{path}: expected a JSON object")
        for k, v in data.items():
            self.set(k, v, level=LEVEL_FILE)

    # --------------------------------------------------------- observers --
    def observe(self, name: str, cb: Callable[[str, Any], None]) -> None:
        """Live-reconfig callback (reference: config_obs.h)."""
        self.schema(name)
        with self._lock:
            self._observers.setdefault(name, []).append(cb)

    # -------------------------------------------------------------- dump --
    def dump(self) -> Dict[str, Dict[str, Any]]:
        """`config show`-style dump: value + provenance per option."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for name, opt in sorted(self._schema.items()):
                levels = self._values.get(name, {})
                if LEVEL_RUNTIME in levels:
                    src = "runtime"
                elif LEVEL_ENV in levels or \
                        os.environ.get(opt.env_var()) is not None:
                    src = "env"
                elif LEVEL_FILE in levels:
                    src = "file"
                else:
                    src = "default"
                try:
                    value = self.get(name)
                except OptionError as e:
                    value, src = f"<invalid: {e}>", "env"
                out[name] = {"value": value, "source": src,
                             "type": opt.type, "desc": opt.desc}
        return out


# ---------------------------------------------------------------- table ----
# The framework-wide knob table.  Round-1 env names are kept as aliases
# so existing workflows keep working (CEPH_TPU_LOOKUP etc.).
_TABLE: Tuple[Option, ...] = (
    Option("lookup_strategy", TYPE_STR, "auto",
           "device table lookup lowering: auto picks gather on CPU, "
           "onehot (MXU matmul) on accelerators",
           enum_values=("auto", "gather", "onehot"), env="CEPH_TPU_LOOKUP"),
    Option("fastmap_enabled", TYPE_BOOL, True,
           "use the level-synchronous candidate-grid CRUSH mapper for "
           "supported rules", env="CEPH_TPU_FASTMAP"),
    Option("fastmap_extra_tries", TYPE_INT, 4,
           "extra retry candidates per replica slot in the fast mapper "
           "grid (lanes exceeding it fall back to the exact path); 4 "
           "measured fastest on v5e-1 at <1e-4 fallback for 3-replica "
           "sweeps — grid work scales with numrep+extra",
           min=2, max=64, env="CEPH_TPU_FASTMAP_EXTRA"),
    Option("straw2_select", TYPE_STR, "approx",
           "straw2 argmin mode: approx = f32 polynomial prefilter + "
           "exact top-2 re-check; exact = full-width fixed-point LUT",
           enum_values=("approx", "exact"), env="CEPH_TPU_SELECT"),
    Option("mapper_max_lanes_per_call", TYPE_INT, 1 << 17,
           "general mapper: max x lanes per device dispatch (one-hot "
           "intermediates are ~S*385 bytes per lane-level; keep the "
           "working set inside HBM)", min=1 << 10),
    Option("fastmap_max_grid_lanes", TYPE_INT, 1 << 23,
           "fast mapper: max (lane x candidate) product per dispatch",
           min=1 << 12),
    Option("fastmap_max_grid_mib", TYPE_INT, 12288,
           "fast mapper: HBM budget (MiB) per [rows, level-width] "
           "working buffer; lanes per dispatch scale down to fit "
           "(swept 8/12/14 GiB on v5e-1: larger chunks cut the 1M-PG "
           "sweep 2.7s -> 2.0s; 12 GiB leaves room for device-resident "
           "EC shards during recovery)",
           min=64),
    Option("ec_table_cache_size", TYPE_INT, 2516,
           "decode-matrix LRU entries per codec (reference: "
           "ErasureCodeIsaTableCache.h:35)", min=1),
    Option("ec_kernel", TYPE_STR, "auto",
           "GF(2^8) matmul lowering: auto = pallas VMEM-unpack kernel "
           "on TPU, xla elsewhere; both bit-identical",
           enum_values=("auto", "xla", "pallas")),
    Option("erasure_code_default_plugin", TYPE_STR, "jax",
           "plugin used when a profile names none (reference: "
           "osd_pool_default_erasure_code_profile, options.cc:2748)"),
    Option("erasure_code_default_layout", TYPE_STR, "bitsliced",
           "chunk layout injected into jax-plugin EC profiles that name "
           "none: bitsliced = jerasure-packet plane layout consumed "
           "directly by the masked-XOR region kernel (the at-rest "
           "format, like jerasure_schedule_encode packets, "
           "ErasureCodeJerasure.cc:162); bytes = byte-symbol compat "
           "layout (bit-plane MXU matmul path)",
           enum_values=("bytes", "bitsliced")),
    Option("osd_device_staging", TYPE_BOOL, True,
           "stage EC shard payloads in device HBM as int32 plane words "
           "(the ECBackend shard store role, ECBackend.cc:934,1015): "
           "encode/decode/recovery consume the staged planes without "
           "host round-trips; the objectstore keeps the same bytes as "
           "the durable tier"),
    Option("osd_objectstore", TYPE_STR, "bluestore",
           "ObjectStore backend for OSD daemons (reference: "
           "osd_objectstore, src/common/options.cc): bluestore = "
           "block-device extent store with allocator/csum/compression/"
           "deferred writes (cluster/bluestore.py); filestore = "
           "log-structured store; memstore = RAM (tests)",
           enum_values=("bluestore", "filestore", "memstore")),
    Option("bluestore_min_alloc_size", TYPE_INT, 4096,
           "block granularity of the BlueStore allocator and csum "
           "unit (reference: bluestore_min_alloc_size)", min=64),
    Option("bluestore_compression_algorithm", TYPE_STR, "",
           "compressor plugin for BlueStore blobs ('' = off; "
           "reference: bluestore_compression_algorithm)"),
    Option("parallel_data_plane", TYPE_BOOL, False,
           "execute the cluster hot loops (batched put encode, "
           "degraded-get/recovery decode, map_pgs_batch sweeps) "
           "sharded across the device mesh (parallel/data_plane.py — "
           "the multi-chip ParallelPGMapper + messenger fan-out role, "
           "src/osd/OSDMapMapping.h:18); off = single-device paths "
           "unchanged; ignored on hosts with fewer than 2 devices"),
    Option("parallel_data_plane_devices", TYPE_INT, 0,
           "mesh size for the sharded data plane (0 = every visible "
           "device); values above the visible device count disable "
           "the plane rather than fail mid-dispatch", min=0),
    Option("parallel_data_plane_stripes", TYPE_INT, 0,
           "stripe-row count of the MeshPlane2D (stripe, shard) 2-D "
           "mesh (parallel/mesh.py make_mesh_2d): 0/1 = the legacy "
           "1-D stripe-batch mesh; >= 2 reshapes the device list "
           "row-major into (stripes, devices/stripes) so the k+m "
           "shard dimension shards over the columns too; a count "
           "that does not divide the device count disables the "
           "plane rather than fail mid-dispatch", min=0),
    Option("multihost_coordinator", TYPE_STR, "",
           "jax.distributed coordinator address (host:port) for the "
           "multi-process MeshPlane2D ('' = single-process fallback, "
           "every data-plane path byte-identical to today's; env "
           "CEPH_TPU_COORDINATOR overrides)"),
    Option("multihost_processes", TYPE_INT, 0,
           "process count of the multi-process plane (0/1 = single-"
           "process fallback; env CEPH_TPU_NUM_PROCESSES overrides)",
           min=0),
    Option("multihost_process_id", TYPE_INT, -1,
           "this process's id in the multi-process plane (-1 = "
           "unset/fallback; env CEPH_TPU_PROCESS_ID overrides)",
           min=-1),
    Option("osd_max_backfills", TYPE_INT, 1,
           "recovery/backfill reservations an OSD grants concurrently "
           "per role (local primary-side + remote replica-side, the "
           "reference's AsyncReserver pair, src/common/AsyncReserver.h "
           "/ osd_max_backfills): concurrent PG recoveries above the "
           "cap are deferred and requeued, so recovery saturates spare "
           "bandwidth without unbounded fan-in on one OSD", min=1),
    Option("perf_counters_enabled", TYPE_BOOL, True,
           "collect dispatch/cache/bytes counters"),
    Option("op_tracker_enabled", TYPE_BOOL, True,
           "track per-op lifecycle events (objecter -> OSD queue -> "
           "device dispatch; reference: osd_enable_op_tracker)"),
    Option("op_tracker_complaint_time", TYPE_FLOAT, 30.0,
           "seconds before an op counts as slow (reference: "
           "osd_op_complaint_time)", min=0.0),
    Option("op_tracker_history_size", TYPE_INT, 100,
           "completed ops kept for dump_historic_ops (reference: "
           "osd_op_history_size)", min=1),
    Option("op_tracker_history_slow_size", TYPE_INT, 20,
           "slow ops kept for dump_historic_slow_ops (reference: "
           "osd_op_history_slow_op_size)", min=1),
    Option("op_tracker_max_inflight", TYPE_INT, 1024,
           "bound on the in-flight tracking table; ops past it run "
           "untracked (counted as op_tracker.ops_untracked)", min=1),
    Option("trace_enabled", TYPE_BOOL, True,
           "distributed tracing master switch (reference: "
           "jaeger_tracing_enable): armed, every submitted op carries "
           "a (trace_id, span_id) context across wire frames and "
           "in-process dispatch and daemons open linked child spans; "
           "disarmed, trace sites cost one dict-miss check"),
    Option("trace_max_spans", TYPE_INT, 10000,
           "bounded finished-span buffer per process; trims drop the "
           "oldest half (counted as tracer.spans_dropped) except "
           "spans of pinned (auto-sampled slow) traces", min=100),
    Option("objecter_wire_streams", TYPE_INT, 4,
           "parallel pipelined connections per OSD daemon in the "
           "async objecter's stream pool (the ms_async_op_threads / "
           "multi-connection fan-out role): one logical op's k+m "
           "shard fan-out stripes across them", min=1),
    Option("objecter_wire_window", TYPE_INT, 16,
           "per-stream send window (frames in flight before submit "
           "blocks) — the Throttle role on the async wire path",
           min=1),
    Option("objecter_wire_mode", TYPE_STR, "crc",
           "data mode of async objecter streams after the cephx "
           "handshake (reference ms_client_mode): 'crc' = "
           "plaintext payload, crc32 bound into the HMAC'd header "
           "(integrity only, the reference's intra-cluster default), "
           "'secure' = sealed payloads",
           enum_values=("crc", "secure")),
    Option("osd_cluster_wire_mode", TYPE_STR, "crc",
           "data mode of intra-cluster daemon->daemon links "
           "(replica sub-writes, recovery pushes — the reference's "
           "ms_cluster_mode, independent of the client-facing "
           "objecter_wire_mode): 'crc' keeps the one-pass "
           "trusted-csum handoff at zlib speed, 'secure' seals peer "
           "payloads",
           enum_values=("crc", "secure")),
    Option("wire_one_pass", TYPE_BOOL, True,
           "ZeroWire one-pass integrity: scatter-gather frame crcs "
           "are computed/verified as per-4KiB sub-crcs folded by "
           "crc32_combine (wire values bit-identical to a whole-"
           "payload crc32), and the receive-side verify scan's "
           "sub-crcs flow to BlueStore as trusted blob csums — one "
           "crc pass per byte per process instead of three on the "
           "put path; off = the legacy whole-buffer scans (the "
           "bench's 'before' lane)"),
    Option("wire_zero_copy", TYPE_BOOL, True,
           "ZeroWire buffer spine: bulk payloads move as memoryviews "
           "end to end (SockReader hands out views, split_sg does "
           "not materialize, _make_blob pwrites views) — off = the "
           "legacy bytes() materializations, each COUNTED on "
           "perf('wire.zero') so the bench can price copies/MiB"),
    Option("wire_shm_ring_kib", TYPE_INT, 4096,
           "shared-memory ring bytes (KiB) per client<->OSD stream "
           "pool for the same-host lane (msg/shm_ring.py): bulk "
           "payloads cross via mmap with only a doorbell on the "
           "socket; 0 disables the lane (pure socket fallback, same "
           "bytes on the wire)", min=0),
    Option("wire_device_crc", TYPE_STR, "auto",
           "batched crc32 as a GF(2) matmul next to the EC kernels "
           "(ops/crc32_gf2.py) for shards already staged in HBM: "
           "'auto' engages on accelerator backends only (a CPU "
           "matmul loses to a zlib scan), 'on' forces it (bench/"
           "test), 'off' always scans on host",
           enum_values=("auto", "on", "off")),
    Option("wire_reply_ring", TYPE_BOOL, True,
           "RingReply same-host reply lane: the daemon answers bulk "
           "reads (get/recovery pulls) through a daemon-created shm "
           "reply ring (msg/shm_ring.py, 'zwreply') with only a "
           "doorbell on the socket — zero-copy in BOTH directions, "
           "and the store-trusted blob csums ride the doorbell so "
           "the daemon sends with zero scans; requires the request "
           "ring (wire_shm_ring_kib > 0), disabled under secure "
           "mode with it; off = bulk replies ride MSG_REPLY_SG on "
           "the socket (csums still folded, zero send scans)"),
    Option("osd_mclock_scheduler_client_res", TYPE_FLOAT, 0.2,
           "default dmClock RESERVATION for a per-tenant client "
           "class (reference osd_mclock_scheduler_client_res): the "
           "fraction of dispatch slots a tenant is guaranteed under "
           "backlog before weights share the leftovers; per-tenant "
           "overrides ride the cluster spec's qos_tenants table",
           min=0.0),
    Option("osd_mclock_scheduler_client_wgt", TYPE_FLOAT, 1.0,
           "default dmClock WEIGHT for a per-tenant client class "
           "(reference osd_mclock_scheduler_client_wgt): the "
           "tenant's share of capacity left over after every "
           "reservation is met", min=0.0),
    Option("osd_mclock_scheduler_client_lim", TYPE_FLOAT, 0.0,
           "default dmClock LIMIT for a per-tenant client class "
           "(reference osd_mclock_scheduler_client_lim); 0 = "
           "unlimited", min=0.0),
    Option("metrics_history_samples", TYPE_INT, 64,
           "per-level ring bound of the leader mon's metrics history "
           "(mgr/metrics_history.py, the mgr MetricCollector / PGMap "
           "delta-history role): level 0 keeps this many raw "
           "report_perf deliveries per reporter before log2 "
           "downsampling folds the oldest pairs upward", min=2),
    Option("metrics_history_levels", TYPE_INT, 6,
           "log2 downsampling levels of the metrics history: level i "
           "holds samples whose window fuses 2^i raw deliveries, so "
           "retained wall coverage grows ~2^levels x samples while "
           "memory stays levels x samples entries per reporter",
           min=1),
    Option("pg_heat_half_life", TYPE_FLOAT, 60.0,
           "exponential-decay half life of the per-PG client-io heat "
           "ledgers (cluster/pg_heat.py, the pool HitSet role): "
           "seconds on the daemon tier, heartbeat TICKS on the sim "
           "tier's deterministic clock", min=0.001),
)

_config: Optional[Options] = None
_config_lock = threading.Lock()


def config() -> Options:
    """The process-wide registry (CephContext._conf analog)."""
    global _config
    with _config_lock:
        if _config is None:
            _config = Options(_TABLE)
        return _config
