"""CTL1xx wire hot-path rules — CTL130: copy-introducing patterns;
CTL131: reply-direction re-scans outside the combine chokepoint.

ZeroWire (ISSUE 15) made the wire data path zero-copy end to end:
payload buffers cross the client, the frames, the receive path and
the store as memoryviews, and every byte pays for integrity exactly
once.  The regression class this rule polices is the quiet
re-introduction of a payload materialization on that path —

  * ``bytes(data)`` / ``bytes(payload)`` — a full duplicate of the
    buffer the spine worked to keep as a view;
  * ``b"".join(...)`` — the meta+data concatenation the
    scatter-gather frame layout (MSG_REQ_SG) exists to avoid;
  * ``meta + data``-style ``+`` concatenation of payload buffers.

Scope — the wire hot path: every function in ``msg/wire.py`` /
``msg/shm_ring.py`` / ``cluster/async_objecter.py``, plus the
objecter fan-out in ``client/``: functions that submit to the async
core (``call_async`` / ``aio_osd_call`` / ``osd_call``) and, over the
PR-12 whole-program graph (precise edges), every ``client/`` helper
such a fan-out reaches — a copy inside a helper is the same cost
wearing a wrapper.  Counted legacy paths and fault-injection joins
carry ``# noqa: CTL130`` with justification; everything else must
stay view-clean.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Set, Tuple

from . import astutil
from .core import Finding, ParsedModule, Rule

# buffer-bearing names: flagging is restricted to these so the rule
# targets PAYLOAD materializations, not every bytes() in sight
_PAYLOAD_NAMES = frozenset((
    "data", "payload", "body", "buf", "chunk", "shard_bytes",
    "frame_bytes"))

# submits into the async wire core — the objecter fan-out roots
_SUBMIT_CALLS = frozenset(("call_async", "aio_osd_call", "osd_call",
                           "submit", "try_submit", "ring_put"))


def _is_payload(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id in _PAYLOAD_NAMES
    if isinstance(node, ast.Subscript):
        return _is_payload(node.value)
    return False


def _copy_patterns(fn: ast.AST) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == "bytes" and \
                    len(node.args) == 1 and _is_payload(node.args[0]):
                out.append((node.lineno,
                            "bytes() materializes a payload buffer"))
            elif isinstance(f, ast.Attribute) and f.attr == "join" \
                    and isinstance(f.value, ast.Constant) \
                    and isinstance(f.value.value, bytes):
                out.append((node.lineno,
                            "b''.join concatenates payload buffers "
                            "(the scatter-gather frame exists to "
                            "avoid this)"))
        elif isinstance(node, ast.BinOp) and \
                isinstance(node.op, ast.Add) and \
                (_is_payload(node.left) or _is_payload(node.right)):
            out.append((node.lineno,
                        "+ concatenation of payload buffers"))
    return out


def _submits_to_wire(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SUBMIT_CALLS:
            return True
    return False


class WireCopyRule(Rule):
    rule_id = "CTL130"
    name = "wire-hot-path-copy"
    description = ("copy-introducing pattern (bytes(payload) / "
                   "b''.join / + concatenation of payload buffers) "
                   "on the zero-copy wire hot path — msg/ framing, "
                   "the async objecter, and the client fan-out "
                   "(interprocedural over the whole-program graph)")

    def __init__(self) -> None:
        super().__init__()
        # (mod, fn) in scope; client fan-out roots resolved in finish
        self._wire_fns: List[Tuple[ParsedModule, ast.AST]] = []
        self._client_roots: List[Tuple[ParsedModule, ast.AST]] = []
        self._client_mods: List[ParsedModule] = []

    @staticmethod
    def _relnorm(mod: ParsedModule) -> str:
        return mod.relpath.replace("\\", "/")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        rel = self._relnorm(mod)
        dirs, base = rel.split("/")[:-1], rel.split("/")[-1]
        if "msg" in dirs or base == "async_objecter.py":
            for fn, _cls in astutil.walk_functions(mod.tree):
                self._wire_fns.append((mod, fn))
            return ()
        if "client" in dirs:
            self._client_mods.append(mod)
            for fn, _cls in astutil.walk_functions(mod.tree):
                if _submits_to_wire(fn):
                    self._client_roots.append((mod, fn))
        return ()

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()

        def report(mod: ParsedModule, fn: ast.AST, line: int,
                   msg: str, via: str = "") -> None:
            key = (mod.relpath, line)
            if key in seen or mod.suppressed(line, self.rule_id):
                return
            seen.add(key)
            name = getattr(fn, "name", "?")
            out.append(Finding(
                self.rule_id, mod.relpath, line,
                f"{msg} in wire hot-path function '{name}'{via} — "
                f"keep payload buffers as views end to end "
                f"(memoryview / scatter-gather parts)"))

        for mod, fn in self._wire_fns:
            for line, msg in _copy_patterns(fn):
                report(mod, fn, line, msg)
        # client fan-out: the root functions themselves, plus every
        # client/ helper they reach over the precise program graph
        graph = astutil.program_graph(self.program) \
            if self.program is not None else None
        client_fn_owner = {}
        for mod in self._client_mods:
            for fn, _cls in astutil.walk_functions(mod.tree):
                client_fn_owner[id(fn)] = (mod, fn)
        for mod, fn in self._client_roots:
            targets = [(mod, fn)]
            if graph is not None:
                for g in graph.reachable([fn]):
                    owner = client_fn_owner.get(id(g))
                    if owner is not None and g is not fn:
                        targets.append(owner)
            for tmod, tfn in targets:
                via = "" if tfn is fn else \
                    f" (reached from '{getattr(fn, 'name', '?')}')"
                for line, msg in _copy_patterns(tfn):
                    report(tmod, tfn, line, msg, via)
        return out


# ---------------------------------------------------------- CTL131 ---
# RingReply (ISSUE 20) deleted the reply lane's send-side scan: a bulk
# reply's sub-crcs are already TRUSTED (BlueStore blob csums adopted at
# receive verify), so the frame crc is a crc32_combine fold, never a
# rescan.  The regression class: a reply-building function that calls
# zlib.crc32 / Csums.scan on payload bytes anyway — the silent
# double-scan.  Folding functions (they call crc32_combine /
# combine_series — the sanctioned chokepoint) are exempt; counted
# fallbacks carry # noqa: CTL131 with justification.

_COMBINE_CALLS = frozenset(("crc32_combine", "combine_series"))
_SCAN_ATTRS = frozenset(("crc32", "scan"))


def _references_reply(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and \
                node.attr.startswith("MSG_REPLY"):
            return True
        if isinstance(node, ast.Name) and \
                node.id.startswith("MSG_REPLY"):
            return True
    return False


def _sends_frames(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else \
            (f.id if isinstance(f, ast.Name) else "")
        if name in ("prepare_frame", "put"):
            return True
    return False


def _calls_combine(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else \
            (f.id if isinstance(f, ast.Name) else "")
        if name in _COMBINE_CALLS:
            return True
    return False


def _rescan_patterns(fn: ast.AST) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _SCAN_ATTRS \
                and node.args and _is_payload(node.args[0]):
            what = "zlib.crc32" if f.attr == "crc32" else "Csums.scan"
            out.append((node.lineno,
                        f"{what}() re-scans payload bytes"))
    return out


class WireReplyRescanRule(Rule):
    rule_id = "CTL131"
    name = "reply-direction-rescan"
    description = ("reply-direction send that re-scans payload bytes "
                   "(zlib.crc32 / Csums.scan) outside the "
                   "crc32_combine chokepoint — trusted sub-crcs from "
                   "the store side table must FOLD into the frame "
                   "crc, never trigger a second traversal "
                   "(interprocedural over the whole-program graph)")

    def __init__(self) -> None:
        super().__init__()
        self._roots: List[Tuple[ParsedModule, ast.AST]] = []
        self._scope_mods: List[ParsedModule] = []

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        rel = mod.relpath.replace("\\", "/")
        dirs = rel.split("/")[:-1]
        if "msg" in dirs or "cluster" in dirs:
            self._scope_mods.append(mod)
            for fn, _cls in astutil.walk_functions(mod.tree):
                if _references_reply(fn) and _sends_frames(fn):
                    self._roots.append((mod, fn))
        return ()

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()
        owner = {}
        for mod in self._scope_mods:
            for fn, _cls in astutil.walk_functions(mod.tree):
                owner[id(fn)] = (mod, fn)
        graph = astutil.program_graph(self.program) \
            if self.program is not None else None

        def report(mod: ParsedModule, fn: ast.AST, line: int,
                   msg: str, via: str) -> None:
            key = (mod.relpath, line)
            if key in seen or mod.suppressed(line, self.rule_id):
                return
            seen.add(key)
            name = getattr(fn, "name", "?")
            out.append(Finding(
                self.rule_id, mod.relpath, line,
                f"{msg} on the reply send path in '{name}'{via} — "
                f"trusted csums fold via crc32_combine at the "
                f"chokepoint; a rescan here is the double-scan the "
                f"reply lane exists to delete"))

        for mod, fn in self._roots:
            targets = [(mod, fn)]
            if graph is not None:
                for g in graph.reachable([fn]):
                    o = owner.get(id(g))
                    if o is not None and g is not fn:
                        targets.append(o)
            for tmod, tfn in targets:
                if _calls_combine(tfn):
                    continue          # the sanctioned fold chokepoint
                via = "" if tfn is fn else \
                    f" (reached from '{getattr(fn, 'name', '?')}')"
                for line, msg in _rescan_patterns(tfn):
                    report(tmod, tfn, line, msg, via)
        return out


def register(reg) -> None:
    reg.add("CTL130", WireCopyRule)
    reg.add("CTL131", WireReplyRescanRule)
