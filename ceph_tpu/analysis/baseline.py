"""Checked-in finding baseline — the deliberate-exception ledger.

Findings whose (rule, path, msg) key appears in the baseline file are
reported as "baselined" instead of failing ``--check``: the workflow
for a violation that is intentional is either an inline
``# noqa: CTL###`` (preferred — the justification lives next to the
code) or, for whole-finding grandfathering, one baseline entry.  The
file is JSON, sorted, and small by policy (the lint gate test caps
it), so every entry is reviewable in a diff.
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, List, Set, Tuple

from .core import Finding, LintError

Key = Tuple[str, str, str]


def load(path: str) -> Set[Key]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return set()
    except json.JSONDecodeError as e:
        raise LintError(f"{path}: bad baseline json: {e}") from e
    if not isinstance(data, dict) or \
            not isinstance(data.get("findings"), list):
        raise LintError(f"{path}: expected {{'findings': [...]}}")
    out: Set[Key] = set()
    for entry in data["findings"]:
        try:
            out.add((entry["rule"], entry["path"], entry["msg"]))
        except (TypeError, KeyError) as e:
            raise LintError(
                f"{path}: baseline entry needs rule/path/msg: "
                f"{entry!r}") from e
    return out


def save(path: str, findings: Iterable) -> None:
    """Accepts Findings or raw (rule, path, msg) keys."""
    entries = sorted({f.key() if isinstance(f, Finding) else tuple(f)
                      for f in findings})
    data = {
        "comment": "cephtpu-lint baseline: deliberate exceptions "
                   "only. Prefer inline '# noqa: CTL###' with a "
                   "justification; regenerate via "
                   "scripts/lint.py --write-baseline.",
        "findings": [{"rule": r, "path": p, "msg": m}
                     for r, p, m in entries],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def split(findings: Iterable[Finding], baseline: Set[Key]
          ) -> Tuple[List[Finding], List[Finding], List[Key]]:
    """(new, baselined, stale-baseline-entries)."""
    new: List[Finding] = []
    old: List[Finding] = []
    seen: Set[Key] = set()
    for f in findings:
        if f.key() in baseline:
            old.append(f)
            seen.add(f.key())
        else:
            new.append(f)
    stale = sorted(baseline - seen)
    return new, old, stale
