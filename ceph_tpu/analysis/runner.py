"""Lint runner — walk the tree, run every rule, apply noqa + baseline,
render human or JSON output.

Library entry: ``run(root, ...)``.  CLI entry: ``main(argv)`` — shared
by ``scripts/lint.py`` and ``ceph_tpu.tools.ceph_cli lint``.

Scopes:
  * lint paths (findings reported): ``ceph_tpu/`` + ``scripts/``
  * evidence paths (scanned for cross-references only — admin
    dispatches, perf writes, Option declarations): ``tests/``

JSON output shape (``--json``)::

    {"root": str, "count": int,          # unsuppressed findings
     "baselined": int, "noqa": int,
     "findings":       [{rule, path, line, msg} ...],
     "baselined_findings": [...same shape...],
     "stale_baseline": [{rule, path, msg} ...],
     "rules": {rule_id: description}}
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import astutil
from . import baseline as baseline_mod
from .core import Finding, LintError, ParsedModule, Program, \
    apply_noqa, parse_module
from .registry import RuleRegistry

DEFAULT_LINT_PATHS = ("ceph_tpu", "scripts")
DEFAULT_EVIDENCE_PATHS = ("tests",)
DEFAULT_BASELINE = os.path.join("scripts", "lint_baseline.json")
_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", "data", "golden",
              "node_modules"}


def _iter_py(root: str, rel: str) -> Iterable[Tuple[str, str]]:
    top = os.path.join(root, rel)
    if os.path.isfile(top):
        if top.endswith(".py"):
            yield top, os.path.relpath(top, root).replace(os.sep, "/")
        return
    for dirpath, dirnames, filenames in os.walk(top):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in _SKIP_DIRS)
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                full = os.path.join(dirpath, fname)
                yield full, os.path.relpath(full, root).replace(
                    os.sep, "/")


def _scope_covers(key, select, paths) -> bool:
    """Could a run restricted to ``select`` rules and ``paths`` have
    re-derived this baseline entry?  Entries outside the scope must be
    neither reported as stale nor dropped by --write-baseline."""
    rule, path, _ = key
    if select and not any(rule.upper().startswith(s.upper())
                          for s in select):
        return False
    if paths:
        norm = [p.replace(os.sep, "/").rstrip("/") for p in paths]
        if not any(p in (".", "") or path == p or
                   path.startswith(p + "/") for p in norm):
            return False
    return True


class Result:
    def __init__(self, findings: List[Finding],
                 baselined: List[Finding],
                 noqa: List[Finding],
                 stale_baseline: List[Tuple[str, str, str]],
                 program: Optional[Program] = None):
        self.findings = findings          # unsuppressed
        self.baselined = baselined
        self.noqa = noqa
        self.stale_baseline = stale_baseline
        self.program = program            # the parsed tree (--graph)

    @property
    def all_findings(self) -> List[Finding]:
        return sorted(self.findings + self.baselined,
                      key=lambda f: (f.path, f.line, f.rule))


def run(root: str,
        paths: Optional[Sequence[str]] = None,
        evidence_paths: Optional[Sequence[str]] = None,
        select: Optional[Sequence[str]] = None,
        baseline: Optional[str] = None) -> Result:
    """Run the suite; ``baseline`` is a path or None (no baseline)."""
    root = os.path.abspath(root)
    paths = list(paths) if paths is not None else \
        [p for p in DEFAULT_LINT_PATHS
         if os.path.exists(os.path.join(root, p))]
    evidence_paths = list(evidence_paths) \
        if evidence_paths is not None else \
        [p for p in DEFAULT_EVIDENCE_PATHS
         if os.path.exists(os.path.join(root, p))]

    rules = RuleRegistry.instance().create(select)
    modules: Dict[str, ParsedModule] = {}
    findings: List[Finding] = []
    for evidence, rels in ((False, paths), (True, evidence_paths)):
        for rel in rels:
            for full, relpath in _iter_py(root, rel):
                if relpath in modules:
                    continue
                mod, err = parse_module(full, relpath,
                                        evidence=evidence)
                if err is not None:
                    if not evidence:
                        findings.append(err)
                    continue
                modules[relpath] = mod

    # the whole parsed tree: whole-program rules resolve cross-module
    # calls through ONE shared graph cached on this object (built on
    # first use, reused by every rule in the run — the wall-time
    # budget depends on it)
    program = Program(modules)
    for mod in modules.values():
        mod.program = program

    for rule in rules:
        rule.begin(program)
    for mod in modules.values():
        for rule in rules:
            findings.extend(rule.check_module(mod))
    for rule in rules:
        findings.extend(rule.finish())

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.msg))
    kept, noqa = apply_noqa(findings, modules)
    base = baseline_mod.load(baseline) if baseline else set()
    new, old, stale = baseline_mod.split(kept, base)
    # a scoped run (--select / explicit paths) cannot see findings
    # outside its scope: their baseline entries are not stale
    stale = [k for k in stale if _scope_covers(k, select, paths)]
    return Result(new, old, noqa, stale, program=program)


# ----------------------------------------------------------------- CLI ----

def main(argv: Optional[Sequence[str]] = None, out=None) -> int:
    out = out or sys.stdout
    ap = argparse.ArgumentParser(
        prog="cephtpu-lint",
        description="AST-based static analysis for ceph_tpu "
                    "(JAX hot-path, dtype, concurrency, registry "
                    "hygiene)")
    ap.add_argument("paths", nargs="*",
                    help="paths to lint (default: ceph_tpu scripts)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: autodetect from this "
                         "package's location)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--sarif", action="store_true",
                    help="SARIF 2.1.0 output (the GitHub "
                         "code-scanning schema) — CI uploads it so "
                         "findings annotate the diff inline")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when unsuppressed findings OR stale "
                         "baseline entries exist (the CI gate)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: "
                         f"{DEFAULT_BASELINE}; 'none' disables)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current unsuppressed findings to the "
                         "baseline file and exit")
    ap.add_argument("--select", action="append", default=None,
                    metavar="CTL###",
                    help="run only matching rules (exact id or "
                         "family prefix, repeatable)")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="CTL###", dest="rule",
                    help="family filter, alias of --select "
                         "(`ceph lint --rule CTL8`)")
    ap.add_argument("--graph", default=None, metavar="module.fn",
                    help="dump the whole-program call graph around "
                         "one function (who-reaches-this / "
                         "what-this-reaches) and exit — the triage "
                         "companion for whole-program findings")
    ap.add_argument("--list-rules", action="store_true")
    ns = ap.parse_args(argv)
    if ns.rule:
        ns.select = (ns.select or []) + ns.rule

    if ns.list_rules:
        for rid, meta in RuleRegistry.instance().describe().items():
            out.write(f"{rid}  {meta['name']}: "
                      f"{meta['description']}\n")
        return 0

    root = ns.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if ns.baseline == "none":
        bpath = None
    else:
        bpath = os.path.join(root, ns.baseline or DEFAULT_BASELINE)
        if ns.baseline and not os.path.isabs(ns.baseline) and \
                os.path.exists(ns.baseline):
            bpath = os.path.abspath(ns.baseline)

    try:
        if ns.write_baseline:
            res = run(root, paths=ns.paths or None,
                      select=ns.select, baseline=None)
            if bpath is None:
                raise LintError("--write-baseline needs a baseline "
                                "path")
            entries = {f.key() for f in res.findings}
            # scoped rewrite: keep every entry this run could not have
            # re-derived (other families under --select, other paths
            # under explicit path args) — refreshing one slice must
            # not silently drop the rest of the grandfather ledger
            eff_paths = ns.paths or list(DEFAULT_LINT_PATHS)
            entries |= {k for k in baseline_mod.load(bpath)
                        if not _scope_covers(k, ns.select, eff_paths)}
            baseline_mod.save(bpath, entries)
            out.write(f"wrote {len(entries)} finding(s) to "
                      f"{bpath}\n")
            return 0
        if ns.graph is not None:
            return _dump_graph(root, ns, out)
        res = run(root, paths=ns.paths or None, select=ns.select,
                  baseline=bpath)
    except LintError as e:
        out.write(f"lint error: {e}\n")
        return 2

    if ns.sarif:
        out.write(json.dumps(_sarif(res), indent=2) + "\n")
    elif ns.json:
        out.write(json.dumps({
            "root": root,
            "count": len(res.findings),
            "baselined": len(res.baselined),
            "noqa": len(res.noqa),
            "findings": [f.to_json() for f in res.findings],
            "baselined_findings": [f.to_json()
                                   for f in res.baselined],
            "stale_baseline": [
                {"rule": r, "path": p, "msg": m}
                for r, p, m in res.stale_baseline],
            "rules": {rid: meta["description"] for rid, meta in
                      RuleRegistry.instance().describe().items()},
        }, indent=2) + "\n")
    else:
        for f in res.findings:
            out.write(f.render() + "\n")
        for key in res.stale_baseline:
            out.write(f"stale baseline entry (fixed? remove it): "
                      f"{key[0]} {key[1]}: {key[2]}\n")
        out.write(f"{len(res.findings)} finding(s), "
                  f"{len(res.baselined)} baselined, "
                  f"{len(res.noqa)} noqa-suppressed\n")
    if ns.check and (res.findings or res.stale_baseline):
        # stale baseline entries fail the gate too: a suppression
        # whose finding no longer fires anywhere has stopped guarding
        # anything and silently shrinks the gate — remove it
        return 1
    return 0


def _sarif(res: Result) -> dict:
    """SARIF 2.1.0 document (the subset GitHub code scanning
    ingests): one run, the registered rules as tool metadata, every
    unsuppressed finding as an ``error`` result and every baselined
    finding as a ``note`` (visible but non-blocking — mirroring the
    --check gate).  Paths stay repo-relative via SRCROOT so the
    upload action can anchor them to the checkout."""
    from .. import __version__
    described = RuleRegistry.instance().describe()

    def result(f: Finding, level: str) -> dict:
        return {
            "ruleId": f.rule,
            "level": level,
            "message": {"text": f.msg},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        }

    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "cephtpu-lint",
                "version": __version__,
                "informationUri":
                    "https://example.invalid/cephtpu-lint",
                "rules": [{
                    "id": rid,
                    "name": meta["name"],
                    "shortDescription": {"text": meta["description"]},
                } for rid, meta in described.items()],
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results":
                [result(f, "error") for f in res.findings] +
                [result(f, "note") for f in res.baselined],
        }],
    }


def _dump_graph(root: str, ns, out) -> int:
    """`--graph module.fn`: resolve the function by dotted-suffix
    match and print its direct callers/callees plus the transitive
    closure sizes — who-reaches-this / what-this-reaches."""
    mods: Dict[str, ParsedModule] = {}
    paths = list(ns.paths) if ns.paths else \
        [p for p in DEFAULT_LINT_PATHS
         if os.path.exists(os.path.join(root, p))]
    evidence = [p for p in DEFAULT_EVIDENCE_PATHS
                if os.path.exists(os.path.join(root, p))]
    for ev, rels in ((False, paths), (True, evidence)):
        for rel in rels:
            for full, relpath in _iter_py(root, rel):
                if relpath in mods:
                    continue
                m, err = parse_module(full, relpath, evidence=ev)
                if err is None:
                    mods[relpath] = m
    program = Program(mods)
    for m in mods.values():
        m.program = program
    g = astutil.program_graph(program)
    targets = g.find(ns.graph)
    if not targets:
        out.write(f"--graph: no function matches {ns.graph!r}\n")
        return 2
    for fn in targets:
        mod = g.mod_of[fn]
        out.write(f"{g.qualname(fn)}  "
                  f"({mod.relpath}:{fn.lineno})\n")
        callers = sorted(g.qualname(c) for c in g.callers_of(fn))
        callees = sorted(g.qualname(c) for c in g.callees(fn))
        up = g.reachable([fn], forward=False)
        down = g.reachable([fn], forward=True)
        out.write(f"  reached-by ({len(callers)} direct, "
                  f"{len(up)} transitive):\n")
        for q in callers:
            out.write(f"    < {q}\n")
        out.write(f"  reaches ({len(callees)} direct, "
                  f"{len(down)} transitive):\n")
        for q in callees:
            out.write(f"    > {q}\n")
    return 0


if __name__ == "__main__":      # pragma: no cover
    raise SystemExit(main())
