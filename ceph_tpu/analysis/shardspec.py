"""ShardCheck abstract domain — the static SPMD/mesh-axis model the
CTL10xx rules (analysis/rules_shard.py) interpret.

JAX pins every collective to a mesh axis by a *string name* that
nothing checks until runtime on a real multi-device host: a misspelled
``lax.psum(x, "shrad")`` traces fine on the forced-CPU CI mesh and
detonates only at multi-host scale.  This module builds, once per lint
run, the whole-program facts those checks need:

  * **axis constants** — module-level ``NAME_AXIS = "str"`` bindings
    tree-wide, with the ``parallel/mesh.py`` set blessed as the shared
    vocabulary (CTL1001's "no hardcoded axis strings" rule);
  * **shard_map sites** — every ``shard_map(body, mesh=..., in_specs=,
    out_specs=)`` call with the body function(s) resolved (innermost
    enclosing scope first, then the PR-12 ``ProgramGraph``), the mesh
    axis tuple when statically resolvable (inline ``Mesh(...)``, a
    name bound to one, or an in-tree factory returning one), and both
    spec pytrees parsed into per-position :class:`SpecElem` facts;
  * **per-site reachability** — the transitive closure of each body
    over the resolved cross-module call graph (the set CTL1001/CTL1003
    walk);
  * **device context** — the jit/shard_map-reachable ("hot") set,
    shared VERBATIM with CTL1xx/CTL6xx via ``astutil._program_hot``
    (shard_map bodies join it there), plus the messenger-callback
    roots CTL110 consumes — so every rule family agrees on one
    reachability computation per run.

Everything is cached on ``Program._cache['device_ctx']``; rules call
:func:`device_context` and share the single instance.
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, \
    Tuple

from . import astutil
from .astutil import SHARD_MAP_NAMES  # noqa: F401  (re-export)

# canonical (post-alias) collective spellings -> positional index of
# the axis-name argument (keyword forms checked by name)
COLLECTIVES: Dict[str, int] = {
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.pshuffle": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.axis_index": 0,
    "jax.lax.axis_size": 0,
}
_AXIS_KWARGS = ("axis_name", "axis_names", "axis_index_groups_axis")

PSPEC_NAMES = {
    "jax.sharding.PartitionSpec",
    "jax.experimental.PartitionSpec",
    "jax.interpreters.pxla.PartitionSpec",
}
MESH_CTORS = {
    "jax.sharding.Mesh",
    "jax.experimental.maps.Mesh",
    "jax.make_mesh",
}


def is_mesh_module(relpath: str) -> bool:
    """The shared-axis-vocabulary module(s): ``parallel/mesh.py`` (or
    any ``mesh.py``) may define axis strings; everyone else imports."""
    return relpath.replace("\\", "/").rsplit("/", 1)[-1] == "mesh.py"


# --------------------------------------------------------------- specs ----

class SpecElem:
    """One positional element of an in_specs/out_specs pytree.

    ``axes``     — resolved axis-name strings mentioned by the element
    ``axis_nodes`` — (value, node, is_literal) per resolved axis
    ``empty``    — True: definitely ``P()`` (fully replicated);
                   False: definitely carries at least one axis;
                   None: unknown / conditional (stay quiet)
    """

    def __init__(self) -> None:
        self.axes: Set[str] = set()
        self.axis_nodes: List[Tuple[str, ast.AST, bool]] = []
        self.empty: Optional[bool] = None

    def merge(self, other: "SpecElem") -> "SpecElem":
        out = SpecElem()
        out.axes = self.axes | other.axes
        out.axis_nodes = self.axis_nodes + other.axis_nodes
        out.empty = self.empty if self.empty == other.empty else None
        return out


class SpecInfo:
    """A parsed in_specs/out_specs expression: positional arity (when
    the pytree is a literal tuple/list) plus per-position facts."""

    def __init__(self) -> None:
        self.count: Optional[int] = None
        self.elems: List[SpecElem] = []

    @property
    def axes(self) -> Set[str]:
        out: Set[str] = set()
        for e in self.elems:
            out |= e.axes
        return out

    @property
    def axis_nodes(self) -> List[Tuple[str, ast.AST, bool]]:
        out: List[Tuple[str, ast.AST, bool]] = []
        for e in self.elems:
            out.extend(e.axis_nodes)
        return out


# ----------------------------------------------------- name environments --

def fn_env(fn: ast.AST) -> Dict[str, ast.AST]:
    """name -> last simple ``name = expr`` assignment inside ``fn``
    (the single-assignment expansion CTL1004/CTL1005 use to see
    through ``mspec = P(SHARD_AXIS) if per_batch else P()``)."""
    env: Dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            env[node.targets[0].id] = node.value
    return env


def mod_env(mod) -> Dict[str, ast.AST]:
    """Module-level simple assignments (``MESH = Mesh(...)``)."""
    cached = mod._cache.get("shard_mod_env")
    if cached is not None:
        return cached
    env: Dict[str, ast.AST] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            env[node.targets[0].id] = node.value
    mod._cache["shard_mod_env"] = env
    return env


# --------------------------------------------------------------- context --

class ShardSite:
    """One statically-collected ``shard_map(...)`` call."""

    def __init__(self, mod, call: ast.Call, enclosing: str,
                 bodies: List[ast.AST],
                 mesh_axes: Optional[FrozenSet[str]],
                 in_specs: Optional[SpecInfo],
                 out_specs: Optional[SpecInfo],
                 reach: Set[ast.AST]) -> None:
        self.mod = mod
        self.call = call
        self.lineno = call.lineno
        self.enclosing = enclosing
        self.bodies = bodies          # FunctionDef / Lambda nodes
        self.mesh_axes = mesh_axes    # None: not statically resolvable
        self.in_specs = in_specs
        self.out_specs = out_specs
        self.reach = reach            # bodies + transitive callees

    def spec_axes(self) -> Set[str]:
        out: Set[str] = set()
        for s in (self.in_specs, self.out_specs):
            if s is not None:
                out |= s.axes
        return out

    def where(self) -> str:
        return f"{self.enclosing}() ({self.mod.relpath})"


class DeviceContext:
    """The once-per-run shared reachability + SPMD facts (see module
    docstring).  CTL602, CTL110 and every CTL10xx rule read this; the
    jit/shard_map-hot set is the SAME object ``astutil.hot_functions``
    slices, so the families cannot disagree."""

    def __init__(self, program) -> None:
        self.program = program
        self.graph = astutil.program_graph(program)
        hot = astutil._program_hot(program)
        self.hot: Set[ast.AST] = hot.hot
        self.direct = hot.direct
        # (dotted module, NAME) -> value for NAME_AXIS = "str"
        self.axis_consts: Dict[Tuple[str, str], str] = {}
        self.mesh_axis_values: Set[str] = set()   # blessed vocabulary
        self.axis_values: Set[str] = set()        # every known value
        self.sites: List[ShardSite] = []
        # root callable -> (origin name, ParsedModule, enclosing cls)
        self.callback_roots: Dict[ast.AST, tuple] = {}
        self._reach_cache: Dict[ast.AST, Set[ast.AST]] = {}
        for mod in program.modules.values():
            self._collect_axis_consts(mod)
        for mod in program.modules.values():
            if not mod.evidence:
                self._scan_module(mod)
        # fn -> shard_map sites whose bodies reach it
        self.shard_fns: Dict[ast.AST, List[ShardSite]] = {}
        for site in self.sites:
            for fn in site.reach:
                self.shard_fns.setdefault(fn, []).append(site)

    # ------------------------------------------------------- hot slices --
    def hot_in(self, mod) -> Set[ast.AST]:
        """Hot functions OF one module — the per-module slice CTL602
        (and CTL101/102 via ``hot_functions``) key off; same
        underlying whole-program set, computed once."""
        return astutil.hot_functions(mod).hot

    def mod_of(self, fn: ast.AST, site: Optional[ShardSite] = None):
        """Owning module of a reached callable; a Lambda body is not
        in the graph index and belongs to its site's module."""
        mod = self.graph.mod_of.get(fn)
        if mod is None and site is not None:
            return site.mod
        return mod

    # -------------------------------------------------- axis constants --
    def _collect_axis_consts(self, mod) -> None:
        dn = astutil.module_dotted(mod.relpath)
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            if not (name.isupper() and name.endswith("_AXIS")):
                continue
            if isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                val = node.value.value
                self.axis_consts[(dn, name)] = val
                self.axis_values.add(val)
                if is_mesh_module(mod.relpath):
                    self.mesh_axis_values.add(val)

    def resolve_axis(self, mod, env: Dict[str, ast.AST],
                     node: ast.AST,
                     _seen: Optional[Set[str]] = None
                     ) -> Optional[str]:
        """Static value of an axis-name expression: a string literal,
        a module-level ``*_AXIS`` constant (same module or imported),
        or a local name bound to one."""
        seen = _seen if _seen is not None else set()
        if isinstance(node, ast.Constant):
            return node.value if isinstance(node.value, str) else None
        d = astutil.dotted(node)
        if d is None:
            return None
        parts = d.split(".")
        if len(parts) == 1:
            name = parts[0]
            if name in env and name not in seen:
                seen.add(name)
                return self.resolve_axis(mod, env, env[name], seen)
            dn = astutil.module_dotted(mod.relpath)
            if (dn, name) in self.axis_consts:
                return self.axis_consts[(dn, name)]
            tgt = astutil.program_aliases_of(mod).get(name)
            if tgt and "." in tgt:
                mn, _, cname = tgt.rpartition(".")
                return self.axis_consts.get((mn, cname))
            return None
        head = astutil.program_aliases_of(mod).get(parts[0])
        if head:
            mn = ".".join([head] + parts[1:-1])
            return self.axis_consts.get((mn, parts[-1]))
        return None

    # ------------------------------------------------------ mesh axes --
    def _mesh_axes(self, mod, env: Dict[str, ast.AST], node: ast.AST,
                   depth: int = 3) -> Optional[FrozenSet[str]]:
        """The axis-name tuple a mesh expression binds, when statically
        resolvable; None (check against the spec/constant vocabulary
        instead) for runtime meshes like ``self.mesh``."""
        if depth <= 0 or node is None:
            return None
        if isinstance(node, ast.Name) and node.id in env:
            nenv = dict(env)
            nenv.pop(node.id)              # break self-reference
            return self._mesh_axes(mod, nenv, env[node.id], depth - 1)
        if not isinstance(node, ast.Call):
            return None
        aliases = astutil.aliases_of(mod)
        cn = astutil.resolve(node.func, aliases)
        if cn in MESH_CTORS:
            ax = None
            if len(node.args) >= 2:
                ax = node.args[1]
            for kw in node.keywords:
                if kw.arg == "axis_names":
                    ax = kw.value
            if ax is None:
                return None
            elts = ax.elts if isinstance(ax, (ast.Tuple, ast.List)) \
                else [ax]
            vals: Set[str] = set()
            for e in elts:
                v = self.resolve_axis(mod, env, e)
                if v is None:
                    return None
                vals.add(v)
            return frozenset(vals)
        # in-tree factory returning a Mesh (parallel.mesh.make_mesh)
        for fac in self.graph.resolve_call(mod, None, node,
                                           precise=True):
            fmod = self.graph.mod_of[fac]
            fenv = {**mod_env(fmod), **fn_env(fac)}
            for ret in ast.walk(fac):
                if isinstance(ret, ast.Return) and \
                        ret.value is not None:
                    got = self._mesh_axes(fmod, fenv, ret.value,
                                          depth - 1)
                    if got is not None:
                        return got
        return None

    # ---------------------------------------------------------- specs --
    def parse_spec_elem(self, mod, env: Dict[str, ast.AST],
                        node: ast.AST,
                        _seen: Optional[Set[str]] = None) -> SpecElem:
        seen = _seen if _seen is not None else set()
        elem = SpecElem()
        if node is None or (isinstance(node, ast.Constant)
                            and node.value is None):
            elem.empty = True
            return elem
        if isinstance(node, ast.Name) and node.id in env \
                and node.id not in seen:
            seen.add(node.id)
            return self.parse_spec_elem(mod, env, env[node.id], seen)
        if isinstance(node, ast.IfExp):
            a = self.parse_spec_elem(mod, env, node.body, seen)
            b = self.parse_spec_elem(mod, env, node.orelse, seen)
            return a.merge(b)
        if isinstance(node, ast.Call):
            cn = astutil.resolve(node.func, astutil.aliases_of(mod))
            if cn in PSPEC_NAMES:
                unresolved = False
                for arg in node.args:
                    items = arg.elts \
                        if isinstance(arg, (ast.Tuple, ast.List)) \
                        else [arg]
                    for item in items:
                        if isinstance(item, ast.Constant) and \
                                item.value is None:
                            continue
                        v = self.resolve_axis(mod, env, item)
                        if v is None:
                            unresolved = True
                            continue
                        lit = isinstance(item, ast.Constant)
                        elem.axes.add(v)
                        elem.axis_nodes.append((v, item, lit))
                if elem.axes:
                    elem.empty = False
                elif not unresolved:
                    elem.empty = True
                return elem
        return elem                      # unknown expression

    def parse_specs(self, mod, env: Dict[str, ast.AST],
                    node: Optional[ast.AST]) -> Optional[SpecInfo]:
        if node is None:
            return None
        info = SpecInfo()
        if isinstance(node, ast.Name) and node.id in env:
            nenv = dict(env)
            nenv.pop(node.id)
            return self.parse_specs(mod, nenv, env[node.id])
        if isinstance(node, (ast.Tuple, ast.List)):
            info.count = len(node.elts)
            for e in node.elts:
                info.elems.append(self.parse_spec_elem(mod, env, e))
            return info
        elem = self.parse_spec_elem(mod, env, node)
        if elem.axes or elem.empty is not None or \
                isinstance(node, (ast.Call, ast.IfExp, ast.Constant)):
            info.count = 1
            info.elems = [elem]
        return info

    # ---------------------------------------------------------- sites --
    def _resolve_bodies(self, mod, cls: Optional[str],
                        stack: List[ast.AST],
                        arg: ast.AST) -> List[ast.AST]:
        if isinstance(arg, ast.Lambda):
            return [arg]
        # innermost enclosing scope first: the data_plane idiom is a
        # nested `def local(...)` right next to its shard_map call,
        # and four same-named locals per module make the graph's
        # module-local index too coarse here
        if isinstance(arg, ast.Name):
            for encl in reversed(stack):
                hits = [n for n in ast.walk(encl)
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                        and n.name == arg.id and n is not encl]
                if hits:
                    return hits
        return self.graph.resolve_ref(mod, cls, arg)

    def _site_reach(self, mod, cls: Optional[str],
                    bodies: List[ast.AST]) -> Set[ast.AST]:
        reach: Set[ast.AST] = set()
        roots: List[ast.AST] = []
        for b in bodies:
            reach.add(b)
            if b in self.graph.mod_of:
                roots.append(b)
            else:                         # Lambda: resolve its calls
                for call in ast.walk(b):
                    if isinstance(call, ast.Call):
                        roots.extend(self.graph.resolve_call(
                            mod, cls, call))
        for b in roots:
            if b in self._reach_cache:
                reach |= self._reach_cache[b]
                continue
            sub = {b} | self.graph.reachable([b])
            self._reach_cache[b] = sub
            reach |= sub
        return reach

    def _scan_module(self, mod) -> None:
        aliases = astutil.aliases_of(mod)
        menv = mod_env(mod)
        graph = self.graph

        def note_cb(v: ast.AST, cls) -> None:
            """CTL110 messenger-callback root (migrated here so the
            reachability families share one collection pass)."""
            if isinstance(v, ast.Lambda):
                self.callback_roots.setdefault(
                    v, ("<lambda callback>", mod, cls))
            else:
                for fn in graph.resolve_ref(mod, cls, v):
                    tmod = graph.mod_of[fn]
                    if not tmod.evidence:
                        self.callback_roots.setdefault(
                            fn, (fn.name, tmod, graph.cls_of[fn]))

        def note_site(call: ast.Call, cls,
                      stack: List[ast.AST]) -> None:
            mesh_e = call.args[1] if len(call.args) > 1 else None
            in_e = call.args[2] if len(call.args) > 2 else None
            out_e = call.args[3] if len(call.args) > 3 else None
            for kw in call.keywords:
                if kw.arg == "mesh":
                    mesh_e = kw.value
                elif kw.arg == "in_specs":
                    in_e = kw.value
                elif kw.arg == "out_specs":
                    out_e = kw.value
            env = dict(menv)
            if stack:
                env.update(fn_env(stack[-1]))
            bodies = self._resolve_bodies(
                mod, cls, stack, call.args[0]) if call.args else []
            self.sites.append(ShardSite(
                mod, call,
                stack[-1].name if stack else "<module>",
                bodies,
                self._mesh_axes(mod, env, mesh_e)
                if mesh_e is not None else None,
                self.parse_specs(mod, env, in_e),
                self.parse_specs(mod, env, out_e),
                self._site_reach(mod, cls, bodies)))

        def visit(node: ast.AST, cls,
                  stack: List[ast.AST]) -> None:
            for ch in ast.iter_child_nodes(node):
                ncls = ch.name if isinstance(ch, ast.ClassDef) else cls
                nstack = stack + [ch] if isinstance(
                    ch, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    else stack
                if isinstance(ch, ast.Call):
                    if astutil.resolve(ch.func, aliases) \
                            in SHARD_MAP_NAMES and ch.args:
                        note_site(ch, cls, stack)
                    for kw in ch.keywords:
                        if kw.arg == "cb":
                            note_cb(kw.value, cls)
                    if isinstance(ch.func, ast.Attribute) and \
                            ch.func.attr in ("set_complete_callback",
                                             "add_done_callback") \
                            and ch.args:
                        note_cb(ch.args[0], cls)
                visit(ch, ncls, nstack)

        visit(mod.tree, None, [])


def device_context(program) -> DeviceContext:
    """The per-run shared context (built once, cached on Program)."""
    ctx = program._cache.get("device_ctx")
    if ctx is None:
        ctx = program._cache["device_ctx"] = DeviceContext(program)
    return ctx


def collective_axis_nodes(call: ast.Call,
                          idx: int) -> Iterable[ast.AST]:
    """The axis-name argument expression(s) of a collective call —
    positional by ``idx`` or by keyword; tuple axis args flattened."""
    nodes: List[ast.AST] = []
    if len(call.args) > idx:
        nodes.append(call.args[idx])
    for kw in call.keywords:
        if kw.arg in _AXIS_KWARGS:
            nodes.append(kw.value)
    for n in nodes:
        if isinstance(n, (ast.Tuple, ast.List)):
            for e in n.elts:
                yield e
        else:
            yield n
