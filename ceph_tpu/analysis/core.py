"""Lint core — findings, parsed modules, the rule interface,
``# noqa`` suppression.

A finding's identity for baseline purposes is (rule, path, msg) — line
numbers shift with every edit, so they are display-only.  Messages are
therefore written WITHOUT line numbers in them.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple


class LintError(RuntimeError):
    """Framework-level failure (bad rule registration, bad baseline)."""


@dataclass(frozen=True)
class Finding:
    """One violation: ``path:line: CTL### message``."""
    rule: str
    path: str          # posix relpath from the lint root
    line: int
    msg: str

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity (line-independent)."""
        return (self.rule, self.path, self.msg)

    def to_json(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path,
                "line": self.line, "msg": self.msg}

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.msg}"


# `# noqa` (bare: suppress everything) / `# noqa: CTL101[,CTL302] ...`
# (code list: suppress ONLY the named codes — a flake8-style
# `# noqa: E402` must NOT blanket-suppress CTL rules)
_NOQA_RE = re.compile(
    r"#\s*noqa\b(?P<colon>\s*:\s*(?P<codes>[^#]*))?", re.IGNORECASE)
_NOQA_CODE_RE = re.compile(r"[A-Za-z]{1,4}\d{3,4}")


class ParsedModule:
    """One parsed source file handed to every rule.

    ``evidence`` modules (tests/) are scanned so whole-program rules
    see their usages (admin dispatches, perf writes) but rules must
    never REPORT findings located in them.
    """

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.AST, evidence: bool = False):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.evidence = evidence
        self.lines = source.splitlines()
        self._cache: Dict[str, Any] = {}   # shared per-module analyses
        self.program: Optional["Program"] = None   # set by the runner

    def parts(self) -> Tuple[str, ...]:
        return tuple(self.relpath.split("/"))

    def suppressed(self, line: int, rule: str) -> bool:
        """True when the physical line carries a noqa for ``rule``
        (bare ``# noqa`` suppresses every rule)."""
        if not 1 <= line <= len(self.lines):
            return False
        m = _NOQA_RE.search(self.lines[line - 1])
        if m is None:
            return False
        if m.group("colon") is None:
            return True                       # bare `# noqa`
        codes = {c.upper()
                 for c in _NOQA_CODE_RE.findall(m.group("codes"))}
        return rule.upper() in codes


class Program:
    """The whole parsed tree of one lint run — every ParsedModule
    (evidence included) plus a shared cache for cross-module analyses
    (the resolved call graph, the whole-program jit-reachability set).
    Built once per run by the runner and handed to every rule through
    ``Rule.begin``; the cache is what keeps the interprocedural graph
    a one-time cost no matter how many rules walk it."""

    def __init__(self, modules: Dict[str, "ParsedModule"]):
        self.modules = modules
        self._cache: Dict[str, Any] = {}

    def lint_modules(self) -> Iterable["ParsedModule"]:
        """Modules findings may be reported in (evidence excluded)."""
        return (m for m in self.modules.values() if not m.evidence)


def parse_module(path: str, relpath: str,
                 evidence: bool = False) -> Tuple[Optional[ParsedModule],
                                                  Optional[Finding]]:
    """Parse one file; a syntax error is itself a finding (CTL000)."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return None, Finding("CTL000", relpath, e.lineno or 1,
                             f"syntax error: {e.msg}")
    return ParsedModule(path, relpath, source, tree,
                        evidence=evidence), None


class Rule:
    """One lint rule.  Subclasses set the id/name/description and
    implement ``check_module`` (called once per parsed module,
    evidence modules included) and optionally ``finish`` (called once
    after every module was seen — whole-program rules emit there).

    Rules are instantiated fresh per run through the registry, so any
    cross-module state lives on ``self``.
    """

    rule_id = "CTL000"
    name = "base"
    description = ""

    def __init__(self) -> None:
        self.program: Optional[Program] = None

    def begin(self, program: Program) -> None:
        """Called once, before any ``check_module``, with the whole
        parsed tree — whole-program rules keep the handle for
        ``finish`` and for the shared interprocedural graph."""
        self.program = program

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        return ()

    def finish(self) -> Iterable[Finding]:
        return ()

    # ------------------------------------------------------------ helpers --
    def finding(self, mod_or_path, line: int, msg: str) -> Finding:
        relpath = (mod_or_path.relpath
                   if isinstance(mod_or_path, ParsedModule)
                   else mod_or_path)
        return Finding(self.rule_id, relpath, line, msg)


def apply_noqa(findings: Iterable[Finding],
               modules: Dict[str, ParsedModule]
               ) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (kept, noqa-suppressed)."""
    kept: List[Finding] = []
    dropped: List[Finding] = []
    for f in findings:
        mod = modules.get(f.path)
        if mod is not None and mod.suppressed(f.line, f.rule):
            dropped.append(f)
        else:
            kept.append(f)
    return kept, dropped
