"""CTL10xx — ShardCheck: static SPMD/mesh-axis verification.

Every collective in a ``shard_map`` body is pinned to a mesh axis by a
string name, every spec position promises a layout, and nothing checks
either until the program runs on a real multi-device mesh — CI's
forced-CPU single-device mesh traces the broken program fine.  These
rules interpret the ShardCheck abstract domain
(analysis/shardspec.py, riding the PR-12 ``ProgramGraph``) and close
that gap statically, the way CTL8xx closed the wire-protocol contract:

  CTL1001  collective-axis closure — every axis name a collective
           reachable from a shard_map body uses (across modules) must
           be bound by that site's mesh; misspelled/unbound = error,
           and hardcoded axis string literals outside parallel/mesh.py
           are flagged (import the shared constants)
  CTL1002  trace-time side effects — host-state mutation (perf counter
           incs, self attr/dict mutation, appends to captured host
           lists, logging/print) in jit/shard_map-reachable code runs
           ONCE at trace time and silently lies thereafter
  CTL1003  per-device host sync — ``jax.device_get``, ``int(x)``/
           ``float(x)`` tracer casts, ``.addressable_shards`` /
           ``.devices()`` introspection inside shard_map-reachable
           code (the np.*/.item()/.block_until_ready() forms are
           CTL101's, which covers shard bodies through the same
           shared hot set)
  CTL1004  spec discipline — in_specs arity matches the wrapped
           function's parameters, out_specs arity matches its
           returns, and every PartitionSpec axis exists in the mesh
           bound at that call site
  CTL1005  unreduced accounting — a shard_map body returning a
           reduction through a replicated out_spec with no psum-class
           collective reads one device's partial as the cluster total
           (the bug PR 4's psum accounting exists to prevent); plus
           literal ppermute permutations must not repeat a source or
           destination
  CTL1006  process-rank divergence — ``jax.process_index()`` /
           ``jax.process_count()`` inside jit/shard_map-reachable
           code is a trace-time constant, so per-process branching
           traces a DIFFERENT program on each host (the classic
           multi-host deadlock); rank reads belong in host code
           (parallel.multihost)
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import astutil, shardspec
from .core import Finding, ParsedModule, Rule


def _sorted_reach(ctx, site) -> List[ast.AST]:
    return sorted(
        site.reach,
        key=lambda f: (ctx.mod_of(f, site).relpath,
                       getattr(f, "lineno", 0)))


class AxisClosureRule(Rule):
    rule_id = "CTL1001"
    name = "shard-axis-closure"
    description = ("collective axis name reachable from a shard_map "
                   "body is not bound by that site's mesh (misspelled "
                   "axes detonate only on a real multi-device mesh), "
                   "or a hardcoded axis string bypasses the shared "
                   "constants in parallel/mesh.py")

    def finish(self) -> Iterable[Finding]:
        ctx = shardspec.device_context(self.program)
        out: List[Finding] = []
        emitted: Set[Tuple[str, int, str]] = set()

        def emit(mod, line: int, msg: str) -> None:
            key = (mod.relpath, line, msg)
            if key not in emitted:
                emitted.add(key)
                out.append(self.finding(mod, line, msg))

        for site in ctx.sites:
            # mesh not statically resolvable (self.mesh): bound =
            # the axes the site's own specs use plus the BLESSED
            # vocabulary from parallel/mesh.py — a misspelled name
            # pinned as a constant elsewhere must still be unbound
            bound = site.mesh_axes if site.mesh_axes is not None \
                else frozenset(site.spec_axes()
                               | ctx.mesh_axis_values)
            for fn in _sorted_reach(ctx, site):
                mod = ctx.mod_of(fn, site)
                if mod.evidence:
                    continue
                aliases = astutil.aliases_of(mod)
                env = shardspec.fn_env(fn) \
                    if not isinstance(fn, ast.Lambda) else {}
                fname = getattr(fn, "name", "<lambda>")
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    cn = astutil.resolve(call.func, aliases)
                    idx = shardspec.COLLECTIVES.get(cn or "")
                    if idx is None:
                        continue
                    tail = cn.rsplit(".", 1)[-1]
                    for anode in shardspec.collective_axis_nodes(
                            call, idx):
                        val = ctx.resolve_axis(mod, env, anode)
                        lit = isinstance(anode, ast.Constant)
                        if lit and not shardspec.is_mesh_module(
                                mod.relpath):
                            emit(mod, anode.lineno,
                                 f"hardcoded axis string {val!r} in "
                                 f"lax.{tail}() inside {fname}() — "
                                 f"import the shared axis constants "
                                 f"from parallel/mesh.py so the 2-D "
                                 f"mesh rename is one edit")
                        if val is None:
                            continue      # runtime axis: stay quiet
                        if val not in bound:
                            emit(mod, call.lineno,
                                 f"collective axis {val!r} in "
                                 f"lax.{tail}() inside {fname}() is "
                                 f"not bound by the mesh at shard_map "
                                 f"site {site.where()} — bound axes: "
                                 f"{sorted(bound)}")
            # hardcoded axis literals inside the spec pytrees
            for spec in (site.in_specs, site.out_specs):
                if spec is None or shardspec.is_mesh_module(
                        site.mod.relpath):
                    continue
                for val, node, lit in spec.axis_nodes:
                    if lit:
                        emit(site.mod, node.lineno,
                             f"hardcoded axis string {val!r} in a "
                             f"PartitionSpec at shard_map site "
                             f"{site.where()} — import the shared "
                             f"axis constants from parallel/mesh.py")
        return out


# mutating container/profiling verbs whose receiver is host state
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "write", "put",
}
# perf-counter verbs: host state even through a local handle
_COUNTER_MUTATORS = {"inc", "tinc", "hinc"}
_LOG_ATTRS = {"debug", "info", "warning", "warn", "error",
              "exception", "critical", "log"}
_LOG_RECV = {"logger", "log", "_log", "_logger", "LOG"}


def _own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Nodes executed in ``fn``'s own frame — nested def/lambda bodies
    excluded (they are hot in their own right only if reached)."""
    work = list(ast.iter_child_nodes(fn))
    while work:
        n = work.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            work.extend(ast.iter_child_nodes(n))


def _local_names(fn: ast.AST) -> Set[str]:
    """Names bound inside ``fn`` (params, assignments, loop/with
    targets, comprehensions) MINUS global/nonlocal declarations —
    mutation of anything else escapes the trace."""
    out: Set[str] = set()
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        out.add(p.arg)
    if a.vararg:
        out.add(a.vararg.arg)
    if a.kwarg:
        out.add(a.kwarg.arg)
    escaped: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            escaped.update(node.names)
            continue
        tgts: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            tgts = list(node.targets)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                               ast.For, ast.AsyncFor)):
            tgts = [node.target]
        elif isinstance(node, ast.NamedExpr):
            tgts = [node.target]
        elif isinstance(node, (ast.withitem,)):
            if node.optional_vars is not None:
                tgts = [node.optional_vars]
        elif isinstance(node, ast.comprehension):
            tgts = [node.target]
        elif isinstance(node, (ast.FunctionDef,
                               ast.AsyncFunctionDef)) and node is not fn:
            out.add(node.name)
        for t in tgts:
            # only a bare Name (possibly inside tuple/list
            # destructuring) BINDS — `counts["k"] = 1` mutates the
            # existing object and must not make `counts` look local
            work2 = [t]
            while work2:
                n = work2.pop()
                if isinstance(n, ast.Name):
                    out.add(n.id)
                elif isinstance(n, (ast.Tuple, ast.List)):
                    work2.extend(n.elts)
                elif isinstance(n, ast.Starred):
                    work2.append(n.value)
    return out - escaped


def _root_name(node: ast.AST) -> Optional[str]:
    """The leftmost Name of an attribute/subscript chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_at_set(call: ast.Call) -> bool:
    """``x.at[idx].set(...)`` — JAX's functional update, NOT host
    mutation."""
    f = call.func
    return (isinstance(f, ast.Attribute)
            and f.attr in ("set", "add", "multiply", "divide",
                           "min", "max", "apply", "get")
            and isinstance(f.value, ast.Subscript)
            and isinstance(f.value.value, ast.Attribute)
            and f.value.value.attr == "at")


class TraceTimeEffectRule(Rule):
    rule_id = "CTL1002"
    name = "shard-trace-time-effect"
    description = ("host-state mutation (perf counter inc, self "
                   "attr/dict mutation, append to a captured list, "
                   "logging/print) in jit/shard_map-reachable code — "
                   "it runs ONCE at trace time, so every count and "
                   "log after the first call silently lies")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        ctx = shardspec.device_context(mod.program)
        hot = ctx.hot_in(mod)
        if not hot:
            return ()
        aliases = astutil.aliases_of(mod)
        out: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()

        def emit(line: int, msg: str) -> None:
            if (line, msg) not in seen:
                seen.add((line, msg))
                out.append(self.finding(mod, line, msg))

        for fn in hot:
            fname = getattr(fn, "name", "<fn>")
            local = _local_names(fn)

            def host_chain(node: ast.AST) -> Optional[str]:
                """Dotted text when the chain roots in host state."""
                root = _root_name(node)
                if root is None:
                    return None
                if root in ("self", "cls") or root not in local:
                    return astutil.dotted(node) or root
                return None

            for node in _own_nodes(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    tgts = node.targets \
                        if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in tgts:
                        if not isinstance(t, (ast.Attribute,
                                              ast.Subscript)):
                            continue
                        chain = host_chain(t)
                        if chain:
                            emit(node.lineno,
                                 f"mutation of host state "
                                 f"'{chain}' in jit-reachable "
                                 f"{fname}() happens once at trace "
                                 f"time, not per call — hoist it out "
                                 f"of the traced path or carry the "
                                 f"value through the computation")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                if _is_at_set(node):
                    continue
                f = node.func
                if isinstance(f, ast.Name) and f.id == "print":
                    emit(node.lineno,
                         f"print() in jit-reachable {fname}() runs "
                         f"once at trace time — use jax.debug.print "
                         f"for per-call output")
                    continue
                if isinstance(f, ast.Attribute):
                    recv = f.value
                    if f.attr in _COUNTER_MUTATORS:
                        emit(node.lineno,
                             f".{f.attr}() perf-counter write in "
                             f"jit-reachable {fname}() counts the "
                             f"trace, not the calls — move it to the "
                             f"dispatch boundary")
                        continue
                    if f.attr in _MUTATORS:
                        chain = host_chain(recv)
                        if chain:
                            emit(node.lineno,
                                 f".{f.attr}() on captured host "
                                 f"state '{chain}' in jit-reachable "
                                 f"{fname}() mutates once at trace "
                                 f"time — every later call silently "
                                 f"skips it")
                        continue
                    if f.attr in _LOG_ATTRS:
                        rn = astutil.resolve(recv, aliases)
                        root = _root_name(recv)
                        if (rn and rn.split(".")[0] == "logging") or \
                                root in _LOG_RECV:
                            emit(node.lineno,
                                 f"logging call in jit-reachable "
                                 f"{fname}() fires once at trace "
                                 f"time — use jax.debug.print or log "
                                 f"at the dispatch boundary")
        return out


_STATIC_CAST_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize"}
_DEVICE_SYNC_CALLS = {"jax.device_get", "jax.device_put"}
_DEVICE_INTROSPECT_ATTRS = {"addressable_shards", "global_shards",
                            "addressable_data", "devices"}


def _static_cast_arg(node: ast.AST,
                     env: Dict[str, ast.AST]) -> bool:
    """``int(x.shape[0])`` / ``int(len(xs))`` are trace-time statics;
    only a cast of an actual array value forces a device sync.
    Expands local single assignments so ``lead = x.shape[:-2];
    int(np.prod(lead))`` resolves as static too."""
    seen: Set[str] = set()
    work: List[ast.AST] = [node]
    while work:
        e = work.pop()
        if isinstance(e, ast.Constant):
            return True
        for n in ast.walk(e):
            if isinstance(n, ast.Attribute) and \
                    n.attr in _STATIC_CAST_ATTRS:
                return True
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Name) and \
                    n.func.id == "len":
                return True
            if isinstance(n, ast.Name) and n.id in env \
                    and n.id not in seen:
                seen.add(n.id)
                work.append(env[n.id])
    return False


class ShardHostSyncRule(Rule):
    rule_id = "CTL1003"
    name = "shard-per-device-sync"
    description = ("per-device host sync (device_get, int(x)/float(x) "
                   "tracer cast, .addressable_shards/.devices() "
                   "introspection) inside shard_map-reachable code — "
                   "each device round-trips to the host per step")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        ctx = shardspec.device_context(mod.program)
        here = [(fn, sites) for fn, sites in ctx.shard_fns.items()
                if ctx.mod_of(fn, sites[0]) is mod]
        if not here:
            return ()
        aliases = astutil.aliases_of(mod)
        out: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()

        def emit(line: int, msg: str) -> None:
            if (line, msg) not in seen:
                seen.add((line, msg))
                out.append(self.finding(mod, line, msg))

        for fn, sites in sorted(
                here, key=lambda p: getattr(p[0], "lineno", 0)):
            fname = getattr(fn, "name", "<lambda>")
            env = shardspec.fn_env(fn) \
                if not isinstance(fn, ast.Lambda) else {}
            site = min(sites, key=lambda s: (s.mod.relpath, s.lineno))
            ctx_txt = (f"shard_map-reachable {fname}() (from site "
                       f"{site.where()})")
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute) and \
                        node.attr in _DEVICE_INTROSPECT_ATTRS:
                    emit(node.lineno,
                         f".{node.attr} inside {ctx_txt} "
                         f"introspects per-device placement on the "
                         f"host — hoist it out of the traced body")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                cn = astutil.resolve(node.func, aliases)
                if cn in _DEVICE_SYNC_CALLS:
                    emit(node.lineno,
                         f"{cn}() inside {ctx_txt} forces a "
                         f"per-device host round trip every step")
                    continue
                if isinstance(node.func, ast.Name) and \
                        node.func.id in ("int", "float", "bool") \
                        and len(node.args) == 1 and \
                        not _static_cast_arg(node.args[0], env):
                    emit(node.lineno,
                         f"{node.func.id}() cast of a traced value "
                         f"inside {ctx_txt} blocks on device->host "
                         f"transfer (ConcretizationTypeError on an "
                         f"abstract tracer) — keep it an array")
        return out


def _body_arity(fn: ast.AST) -> Optional[int]:
    """Positional parameter count of a shard_map body; None when
    *args makes the arity open."""
    if isinstance(fn, ast.Lambda):
        a = fn.args
    elif isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = fn.args
    else:
        return None
    if a.vararg is not None:
        return None
    params = [p.arg for p in a.posonlyargs + a.args]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    return len(params)


def _return_arity(fn: ast.AST) -> Optional[int]:
    """Consistent tuple-arity of ``fn``'s own returns, else None."""
    counts: Set[int] = set()
    for node in _own_nodes(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            v = node.value
            counts.add(len(v.elts)
                       if isinstance(v, ast.Tuple) else 1)
    if len(counts) == 1:
        return counts.pop()
    return None


class SpecDisciplineRule(Rule):
    rule_id = "CTL1004"
    name = "shard-spec-discipline"
    description = ("shard_map spec discipline: in_specs arity must "
                   "match the wrapped function's parameters, "
                   "out_specs arity its returns, and every "
                   "PartitionSpec axis must exist in the mesh bound "
                   "at that call site")

    def finish(self) -> Iterable[Finding]:
        ctx = shardspec.device_context(self.program)
        out: List[Finding] = []
        for site in ctx.sites:
            body = next((b for b in site.bodies
                         if isinstance(b, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.Lambda))), None)
            bname = getattr(body, "name", "<lambda>") \
                if body is not None else "<unresolved>"
            if body is not None and site.in_specs is not None \
                    and site.in_specs.count is not None:
                arity = _body_arity(body)
                if arity is not None and arity != site.in_specs.count:
                    out.append(self.finding(
                        site.mod, site.lineno,
                        f"in_specs carries {site.in_specs.count} "
                        f"spec(s) but shard_map body {bname}() takes "
                        f"{arity} positional argument(s) at site "
                        f"{site.where()} — the pytree mismatch "
                        f"surfaces as a confusing runtime error"))
            if body is not None and not isinstance(body, ast.Lambda) \
                    and site.out_specs is not None \
                    and site.out_specs.count is not None:
                rarity = _return_arity(body)
                if rarity is not None and \
                        rarity != site.out_specs.count:
                    out.append(self.finding(
                        site.mod, site.lineno,
                        f"out_specs carries {site.out_specs.count} "
                        f"spec(s) but shard_map body {bname}() "
                        f"returns {rarity} value(s) at site "
                        f"{site.where()}"))
            bound = site.mesh_axes if site.mesh_axes is not None \
                else (frozenset(ctx.mesh_axis_values)
                      if ctx.mesh_axis_values else None)
            if bound is None:
                continue
            for label, spec in (("in_specs", site.in_specs),
                                ("out_specs", site.out_specs)):
                if spec is None:
                    continue
                for val, node, _lit in spec.axis_nodes:
                    if val not in bound:
                        out.append(self.finding(
                            site.mod, node.lineno,
                            f"PartitionSpec axis {val!r} in {label} "
                            f"at shard_map site {site.where()} does "
                            f"not exist in the mesh bound there — "
                            f"known axes: {sorted(bound)}"))
        return out


_REDUCTIONS = {"sum", "mean", "max", "min", "prod", "count_nonzero",
               "nansum", "nanmean", "average", "any", "all"}
_COLLECTIVE_TAILS = {cn.rsplit(".", 1)[-1]
                     for cn in shardspec.COLLECTIVES}


def _call_names(expr: ast.AST, env: Dict[str, ast.AST],
                aliases: Dict[str, str]) -> Set[str]:
    """Resolved callee names in ``expr``, expanded through local
    single assignments (sees through ``rows = psum(...)``)."""
    names: Set[str] = set()
    seen: Set[str] = set()
    work: List[ast.AST] = [expr]
    while work:
        e = work.pop()
        for n in ast.walk(e):
            if isinstance(n, ast.Call):
                cn = astutil.resolve(n.func, aliases)
                if cn:
                    names.add(cn)
            elif isinstance(n, ast.Name) and n.id in env \
                    and n.id not in seen:
                seen.add(n.id)
                work.append(env[n.id])
    return names


def _perm_pairs(node: ast.AST) -> Optional[List[Tuple[int, int]]]:
    """Literal ppermute permutation pairs, else None."""
    if not isinstance(node, (ast.List, ast.Tuple)):
        return None
    pairs: List[Tuple[int, int]] = []
    for e in node.elts:
        if not (isinstance(e, (ast.Tuple, ast.List))
                and len(e.elts) == 2
                and all(isinstance(x, ast.Constant)
                        and isinstance(x.value, int)
                        for x in e.elts)):
            return None
        pairs.append((e.elts[0].value, e.elts[1].value))
    return pairs


class UnreducedAccountingRule(Rule):
    rule_id = "CTL1005"
    name = "shard-unreduced-accounting"
    description = ("shard_map body returns a reduction through a "
                   "replicated out_spec with no psum-class collective "
                   "— one device's partial reads as the cluster "
                   "total; also flags literal ppermute permutations "
                   "with duplicate sources/destinations")

    def finish(self) -> Iterable[Finding]:
        ctx = shardspec.device_context(self.program)
        out: List[Finding] = []
        emitted: Set[Tuple[str, int, str]] = set()

        def emit(mod, line: int, msg: str) -> None:
            key = (mod.relpath, line, msg)
            if key not in emitted:
                emitted.add(key)
                out.append(self.finding(mod, line, msg))

        for site in ctx.sites:
            spec = site.out_specs
            body = next((b for b in site.bodies
                         if isinstance(b, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))),
                        None)
            if spec is None or spec.count is None or body is None:
                continue
            bmod = ctx.mod_of(body, site)
            if bmod.evidence:
                continue
            aliases = astutil.aliases_of(bmod)
            env = shardspec.fn_env(body)
            for ret in _own_nodes(body):
                if not isinstance(ret, ast.Return) or \
                        ret.value is None:
                    continue
                elems = ret.value.elts \
                    if isinstance(ret.value, ast.Tuple) \
                    else [ret.value]
                if len(elems) != spec.count:
                    continue               # CTL1004's department
                for i, e in enumerate(elems):
                    if spec.elems[i].empty is not True:
                        continue           # sharded or unknown spec
                    names = _call_names(e, env, aliases)
                    tails = {n.rsplit(".", 1)[-1] for n in names}
                    if tails & _COLLECTIVE_TAILS:
                        continue
                    if tails & _REDUCTIONS:
                        emit(bmod, ret.lineno,
                             f"shard_map body {body.name}() returns "
                             f"a per-shard reduction through "
                             f"replicated out_spec position {i} at "
                             f"site {site.where()} with no lax.psum "
                             f"over the mesh axis — each device's "
                             f"partial reads as the cluster total")
        # literal ppermute permutation validity, tree-wide
        for mod in self.program.lint_modules():
            aliases = astutil.aliases_of(mod)
            for fn, _cls in astutil.walk_functions(mod.tree):
                env = shardspec.fn_env(fn)
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    if astutil.resolve(call.func, aliases) != \
                            "jax.lax.ppermute":
                        continue
                    pnode = call.args[2] if len(call.args) > 2 \
                        else None
                    for kw in call.keywords:
                        if kw.arg == "perm":
                            pnode = kw.value
                    if isinstance(pnode, ast.Name) and \
                            pnode.id in env:
                        pnode = env[pnode.id]
                    pairs = _perm_pairs(pnode) \
                        if pnode is not None else None
                    if pairs is None:
                        continue
                    srcs = [s for s, _ in pairs]
                    dsts = [d for _, d in pairs]
                    if len(set(srcs)) != len(srcs) or \
                            len(set(dsts)) != len(dsts):
                        emit(mod, call.lineno,
                             f"ppermute permutation in {fn.name}() "
                             f"repeats a source or destination — "
                             f"a permutation must be a bijection or "
                             f"shards are silently dropped/"
                             f"overwritten")
        return out


_PROCESS_RANK_CALLS = {"jax.process_index", "jax.process_count",
                       "jax.distributed.initialize"}


class ProcessRankDivergenceRule(Rule):
    rule_id = "CTL1006"
    name = "shard-process-rank-divergence"
    description = ("jax.process_index()/process_count() inside "
                   "jit/shard_map-reachable code — the rank is a "
                   "trace-time Python int, so per-process branching "
                   "bakes a DIFFERENT program into each host's "
                   "executable and the SPMD fleet deadlocks or "
                   "silently diverges at the first collective; read "
                   "the rank host-side via parallel.multihost")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        ctx = shardspec.device_context(mod.program)
        hot = ctx.hot_in(mod)
        if not hot:
            return ()
        aliases = astutil.aliases_of(mod)
        out: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()

        def emit(line: int, msg: str) -> None:
            if (line, msg) not in seen:
                seen.add((line, msg))
                out.append(self.finding(mod, line, msg))

        for fn in hot:
            fname = getattr(fn, "name", "<fn>")
            for node in _own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                cn = astutil.resolve(node.func, aliases)
                if cn in _PROCESS_RANK_CALLS:
                    what = cn.rsplit(".", 1)[-1]
                    emit(node.lineno,
                         f"{cn}() in jit-reachable {fname}() is a "
                         f"trace-time constant — each process traces "
                         f"a different program and the SPMD "
                         f"collectives deadlock or diverge; hoist "
                         f"the {what} read to host code "
                         f"(parallel.multihost.{what}) and pass the "
                         f"result in as data")
        return out


def register(reg) -> None:
    reg.add(AxisClosureRule.rule_id, AxisClosureRule)
    reg.add(TraceTimeEffectRule.rule_id, TraceTimeEffectRule)
    reg.add(ShardHostSyncRule.rule_id, ShardHostSyncRule)
    reg.add(SpecDisciplineRule.rule_id, SpecDisciplineRule)
    reg.add(UnreducedAccountingRule.rule_id, UnreducedAccountingRule)
    reg.add(ProcessRankDivergenceRule.rule_id,
            ProcessRankDivergenceRule)
