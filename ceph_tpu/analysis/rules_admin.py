"""CTL5xx — admin-command registry hygiene.

The admin socket is a string-keyed dispatch seam (common/admin.py):
``AdminServer.register("prefix", handler)`` on one side,
``{"prefix": "..."}`` requests on the other.  Nothing ties the two
ends together until a human runs the command — a renamed registration
turns every caller into ``unknown command`` replies, and a command
nobody dispatches is dead weight on the daemon surface.  These rules
close the loop statically:

  CTL501  a literal prefix dispatched somewhere in the package that no
          register site declares
  CTL502  a registered prefix that no dispatch site (package, scripts,
          tools, OR tests — tests count as the command's exercise)
          ever names

Dispatch evidence: dict literals carrying a ``"prefix"`` key, plus
module-level ``*_COMMANDS`` string tuples (the CLI's advertised
surface, tools/ceph_cli.py).  Register evidence: two-argument
``.register("prefix", handler)`` calls — the arity plus literal first
argument distinguishes admin registrations from the EC/mgr/cls
registries that share the method name.

The WIRE protocol's twin closure (``{"cmd": ...}`` sends vs daemon
dispatch arms) is the CTL8xx family (rules_protocol.py) — same
two-sided dead-surface/unreachable-command model, applied to the
messenger seam instead of the admin socket.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from .core import Finding, ParsedModule, Rule


def _collect(mod: ParsedModule):
    """(registered, dispatched) literal prefixes with sites — computed
    once per module and shared by CTL501/CTL502."""
    cached = mod._cache.get("admin_prefixes")
    if cached is not None:
        return cached
    registered: Dict[str, Tuple[str, int]] = {}
    dispatched: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "register" and \
                len(node.args) == 2 and not node.keywords and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            registered.setdefault(node.args[0].value,
                                  (mod.relpath, node.lineno))
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and \
                        k.value == "prefix" and \
                        isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    dispatched.setdefault(v.value,
                                          (mod.relpath, node.lineno))
        elif isinstance(node, ast.Assign) and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id.endswith("COMMANDS") and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            for e in node.value.elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, str):
                    dispatched.setdefault(e.value,
                                          (mod.relpath, node.lineno))
    mod._cache["admin_prefixes"] = (registered, dispatched)
    return registered, dispatched


class _AdminBase(Rule):
    def __init__(self) -> None:
        self.registered: Dict[str, Tuple[str, int]] = {}
        self.dispatched: Dict[str, Tuple[str, int]] = {}
        self.pkg_registered: Dict[str, Tuple[str, int]] = {}
        self.pkg_dispatched: Dict[str, Tuple[str, int]] = {}

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        reg, disp = _collect(mod)
        for k, site in reg.items():
            self.registered.setdefault(k, site)
            if not mod.evidence:
                self.pkg_registered.setdefault(k, site)
        for k, site in disp.items():
            self.dispatched.setdefault(k, site)
            if not mod.evidence:
                self.pkg_dispatched.setdefault(k, site)
        return ()


class UnregisteredDispatchRule(_AdminBase):
    rule_id = "CTL501"
    name = "admin-dispatch-unregistered"
    description = ("admin command dispatched by prefix but never "
                   "registered on any AdminServer")

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for prefix in sorted(set(self.pkg_dispatched) -
                             set(self.registered)):
            path, line = self.pkg_dispatched[prefix]
            out.append(Finding(
                self.rule_id, path, line,
                f"admin command {prefix!r} is dispatched here but no "
                f"AdminServer.register() declares it — every caller "
                f"gets an 'unknown command' reply"))
        return out


class UndispatchedRegisterRule(_AdminBase):
    rule_id = "CTL502"
    name = "admin-register-undispatched"
    description = ("admin command registered but never dispatched by "
                   "any caller, CLI surface, or test")

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for prefix in sorted(set(self.pkg_registered) -
                             set(self.dispatched)):
            path, line = self.pkg_registered[prefix]
            out.append(Finding(
                self.rule_id, path, line,
                f"admin command {prefix!r} is registered but nothing "
                f"(CLI, scripts, tests) ever dispatches it — dead "
                f"surface or missing coverage"))
        return out


def register(reg) -> None:
    reg.add(UnregisteredDispatchRule.rule_id,
            UnregisteredDispatchRule)
    reg.add(UndispatchedRegisterRule.rule_id,
            UndispatchedRegisterRule)
