"""CTL3xx — concurrency: static lock-order checking against the SAME
edge model common/lockdep.py enforces at runtime, plus the raw-lock
ban in daemon-plane modules.

CTL301 extracts every lexically-nested ``with lock:`` pair across the
whole tree into one order graph (outer -> inner) and reports any edge
whose reverse is already reachable — the identical cycle condition
lockdep._before_acquire aborts on at runtime, caught here before the
code ever runs.  Lock identity: a ``LockdepLock("name")`` contributes
its runtime NAME (so the static graph and the runtime graph share a
namespace); a raw threading lock contributes ``module.Class.attr``.
Only with-targets that resolve to a known lock binding participate;
call results (``with self._pg_lock(coll):``) are skipped — identity is
unprovable statically, and the runtime half covers them.

CTL302 flags raw ``threading.Lock/RLock/Condition`` construction in
daemon-plane modules (cluster/ + msg/), which bypasses lockdep
entirely.  Storage engines (bluestore/filestore/kv/wal_kv) are exempt
by design: each owns a single coarse leaf lock on a per-op hot path
where the wrapper's bookkeeping is measurable; common/ is exempt
because lockdep itself and the substrates it is built on live there.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import astutil
from .core import Finding, ParsedModule, Rule

_RAW_CTORS = {"threading.Lock", "threading.RLock",
              "threading.Condition"}
_LOCKDEP_TAIL = "LockdepLock"

# storage engines: single coarse leaf lock each, per-op hot path
_ENGINE_EXEMPT = {"bluestore.py", "filestore.py", "kv.py",
                  "wal_kv.py", "objectstore.py", "blockdev.py",
                  "crashdev.py"}


def _lock_ctor_kind(call: ast.Call,
                    aliases: Dict[str, str]) -> Optional[str]:
    """'raw' | 'lockdep' | None for a constructor call."""
    cn = astutil.resolve(call.func, aliases)
    if cn in _RAW_CTORS:
        return "raw"
    if cn and cn.rsplit(".", 1)[-1] == _LOCKDEP_TAIL:
        return "lockdep"
    return None


class _ModuleLocks(ast.NodeVisitor):
    """Collect lock bindings + lexical with-nesting edges for one
    module."""

    def __init__(self, mod: ParsedModule, aliases: Dict[str, str]):
        self.mod = mod
        self.aliases = aliases
        stem = mod.relpath.rsplit("/", 1)[-1].removesuffix(".py")
        self.stem = stem
        self.cls: Optional[str] = None
        # binding key ('self', cls, attr) or ('name', None, name)
        self.bindings: Dict[Tuple[str, Optional[str], str], str] = {}
        # (outer, inner, line) lexical nesting edges
        self.edges: List[Tuple[str, str, int]] = []
        self.raw_sites: List[Tuple[int, str]] = []
        self._held: List[str] = []

    # ------------------------------------------------------------ binding --
    def _lock_name(self, call: ast.Call, kind: str,
                   attr: str) -> str:
        if kind == "lockdep" and call.args and \
                isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, str):
            return call.args[0].value          # runtime lockdep name
        cls = f"{self.cls}." if self.cls else ""
        return f"{self.stem}.{cls}{attr}"

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            kind = _lock_ctor_kind(node.value, self.aliases)
            if kind is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        name = self._lock_name(node.value, kind,
                                               tgt.id)
                        self.bindings[("name", None, tgt.id)] = name
                    elif isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        name = self._lock_name(node.value, kind,
                                               tgt.attr)
                        self.bindings[("self", self.cls,
                                       tgt.attr)] = name
                if kind == "raw":
                    ctor = astutil.resolve(node.value.func,
                                           self.aliases)
                    self.raw_sites.append((node.lineno, ctor))
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self.cls = self.cls, node.name
        self.generic_visit(node)
        self.cls = prev

    # ------------------------------------------------------------ nesting --
    def _resolve_with(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return self.bindings.get(("name", None, expr.id))
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return self.bindings.get(("self", self.cls, expr.attr))
        return None

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            lock = self._resolve_with(item.context_expr)
            if lock is None:
                continue
            for held in self._held:
                if held != lock:
                    self.edges.append((held, lock, node.lineno))
            self._held.append(lock)
            pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        # With.items expressions may themselves contain nested nodes
        for item in node.items:
            self.visit(item.context_expr)
        del self._held[len(self._held) - pushed:]


class LockOrderRule(Rule):
    rule_id = "CTL301"
    name = "lock-order-inversion"
    description = ("static with-nesting lock-order inversion (the "
                   "lockdep cycle condition, caught at lint time)")

    def __init__(self) -> None:
        # edge -> first site; graph for reachability
        self.sites: Dict[Tuple[str, str],
                         Tuple[str, int]] = {}
        self.graph: Dict[str, Set[str]] = {}

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()          # tests invert deliberately (lockdep's own)
        aliases = astutil.aliases_of(mod)
        v = _ModuleLocks(mod, aliases)
        v.visit(mod.tree)
        for outer, inner, line in v.edges:
            self.sites.setdefault((outer, inner), (mod.relpath, line))
            self.graph.setdefault(outer, set()).add(inner)
        return ()

    def _reaches(self, src: str, dst: str) -> bool:
        stack, seen = [src], set()
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self.graph.get(cur, ()))
        return False

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        reported: Set[frozenset] = set()
        for (a, b), (path, line) in sorted(self.sites.items()):
            if frozenset((a, b)) in reported:
                continue
            # removing the direct edge a->b, can b still reach a?
            if self._reaches(b, a):
                rev = next((s for (x, y), s in sorted(
                    self.sites.items()) if x == b), ("?", 0))
                out.append(Finding(
                    self.rule_id, path, line,
                    f"lock order inversion: {a!r} -> {b!r} here, but "
                    f"{b!r} -> ... -> {a!r} is recorded elsewhere "
                    f"(e.g. {rev[0]}) — same cycle lockdep would "
                    f"abort on at runtime"))
                reported.add(frozenset((a, b)))
        return out


class RawLockRule(Rule):
    rule_id = "CTL302"
    name = "raw-lock-in-daemon-plane"
    description = ("raw threading.Lock/RLock in a daemon-plane module "
                   "bypasses lockdep — use common.lockdep.LockdepLock")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        parts = mod.parts()
        if mod.evidence or not ({"cluster", "msg"} & set(parts)) or \
                parts[-1] in _ENGINE_EXEMPT:
            return ()
        aliases = astutil.aliases_of(mod)
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    _lock_ctor_kind(node, aliases) == "raw":
                ctor = astutil.resolve(node.func, aliases)
                out.append(self.finding(
                    mod, node.lineno,
                    f"{ctor}() in a daemon-plane module bypasses "
                    f"lockdep order checking — use "
                    f"common.lockdep.LockdepLock"))
        return out


def register(reg) -> None:
    reg.add(LockOrderRule.rule_id, LockOrderRule)
    reg.add(RawLockRule.rule_id, RawLockRule)
